#!/usr/bin/env bash
# Serving kernel-path parity gate: the Pallas paged-attention kernels
# (interpret mode) against the jnp references, plus the gather-view vs
# paged-path A/B acceptance smoke — run under the tier-1 marker set so CI's
# gate trio covers the serving hot path even when the full suite is not in
# the loop. Usage: scripts/parity.sh [extra pytest args...]
set -euo pipefail

cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m pytest \
    tests/kernels/test_paged_attention.py \
    "tests/unit/test_serving.py::TestPagedKernelAB" \
    -q -m 'not slow' -p no:cacheprovider "$@"
