#!/bin/bash
# Round-5 on-chip artifact runner — priority-ordered so a short tunnel
# window still lands the VERDICT-critical evidence first.
#   1. headline train bench (tracked config #1)
#   2. MoE sparse train (scatter-free dispatch — VERDICT #4 target >=0.40)
#   3. quantized decode int8/w8a8/int4 (VERDICT #3 targets)
#   4. offload overlap (VERDICT #5)
#   5. remaining tracked configs (#2 resident, #5 bloom, MoE inference)
#   6. kernel/offload validations + rlhf + einsum fallback
# Each entry is its own process; a tunnel drop mid-run only loses the
# current entry. Re-run the script to fill gaps (done files are kept).
set -u
cd "$(dirname "$0")/.."
TAG=${1:-r05}
run() {
  name=$1
  f="bench_results/$TAG/$name.json"
  # complete = a parsed result landed and it isn't a backend-outage skip;
  # structured-OOM records (rc=1 by design) COUNT as complete, while
  # segfaults/timeouts (no "result") re-run
  if [ -f "$f" ] && grep -q '"result"' "$f" \
     && ! grep -q '"skipped": true' "$f"; then
    echo "[keep] $name"
    return
  fi
  python scripts/run_bench_suite.py "$TAG" "$name"
}
run bench
run bench_moe_sparse
run bench_infer_bf16
run bench_infer_int8
run bench_infer_w8a8
run bench_infer_int4
run validate_offload_overlap_1.3b
run bench_zero_optim_offload
run bench_infer_moe8e
run bench_zero2_resident_opt1.3b
run bench_zero2_resident_opt125m
run bench_infer_bloom7b_int8
run bench_infer_bloom7b
run validate_offload_overlap
run bench_zero_param_offload_7b
run bench_moe_einsum
run bench_rlhf
run validate_kernels
run validate_offload
echo "artifacts:"
ls "bench_results/$TAG/" 2>/dev/null
