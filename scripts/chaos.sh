#!/usr/bin/env bash
# Chaos gate: the fault-injection recovery smoke (docs/resilience.md).
#
# Two halves of the self-healing acceptance loop, both CPU-only:
#   (a) in-process: a supervised session on the 8-virtual-device mesh
#       survives an injected NaN step via numerics-sentinel abort →
#       rollback-to-verified-checkpoint, with the post-recovery loss
#       sequence bit-identical to a clean run restarted from the same
#       checkpoint, the lost time attributed to the goodput `recovery`
#       bucket (bucket sums == wall), and the report CLI showing the
#       recovery event;
#   (b) multi-process: the real ElasticAgent + run_training_session on an
#       8-process mesh survives an injected rank SIGKILL (DSTPU_FAULT_PLAN)
#       — kill → membership shrink 8→6 through the elastic batch math →
#       re-rendezvous → per-rank resume — and the post-recovery losses are
#       bit-identical to a clean control run from the same restore point.
#
# Plus the durability + hardening satellites: checkpoint truncation /
# bit-flip → crc verify → previous-good-tag fallback, and the agent's
# backoff / circuit-breaker / eviction-channel behavior.
#
# Usage: scripts/chaos.sh [extra pytest args...]
set -euo pipefail

cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m pytest \
    "tests/unit/test_session.py" \
    "tests/unit/test_checkpoint_v2.py::TestDurability" \
    "tests/unit/test_launcher.py::TestAgentRestartHardening" \
    -q -p no:cacheprovider "$@"
