#!/usr/bin/env python
"""Run-over-run bench artifact comparison — the trajectory, diffable.

Compares two bench runs' artifacts and prints metric deltas with
regression flags, so "did r06 get slower than r05" is a command instead of
an eyeball pass over JSON:

    python scripts/benchdiff.py bench_results/r05 bench_results/r06
    python scripts/benchdiff.py old_metrics.jsonl new_metrics.jsonl
    python scripts/benchdiff.py --strict --threshold 10 r05/ r06/

* Directory args: each ``<name>.json`` written by ``run_bench_suite.py``
  (``{"name", "result": {...}}``) is flattened to dotted numeric paths
  (``result.value``, ``result.autotune_ab.tuned.p50_ttft_ms``) and diffed
  against the same path in the other run. Skipped benches diff as absent.
* ``.jsonl`` args: metrics JSONL (``BENCH_metrics_*.jsonl`` /
  ``timeseries.jsonl``) — the LAST value per (name, labels) series is
  diffed.
* ``profile_summary.json`` args (the deep profiler's measured-vs-predicted
  artifact): each entry's measured/predicted step ms, model_error and
  measured MFU are diffed per entry — a widening model_error run-over-run
  flags as a REGRESSION (the cost model is drifting from the chip).

A delta is flagged as a REGRESSION when the metric's better-direction is
known from its name (``*_ms``/``ttft``/``tpot``/``burn``/latency → lower
is better; ``tokens_per_sec``/``goodput``/``mfu``/throughput → higher) and
the change moves the wrong way by more than ``--threshold`` percent
(default 5). Unknown-direction metrics are printed but never flagged.
``--strict`` exits 1 when any regression was flagged (CI wiring).

Stdlib only — runs anywhere the artifacts do.
"""

import argparse
import json
import os
import sys
from typing import Any, Dict, Iterable, Optional, Tuple

LOWER_IS_BETTER = ("_ms", "ttft", "tpot", "burn", "latency", "wall_s",
                   "wall_seconds", "preemptions", "sheds", "dropped",
                   "rollbacks", "deaths", "failures", "recompile",
                   "model_error", "device_s", "host_s")
HIGHER_IS_BETTER = ("tokens_per_sec", "goodput", "mfu", "throughput",
                    "requests_per_sec", "acceptance_rate", "hit_rate",
                    "roofline_frac", "fraction")


def direction(path: str) -> Optional[int]:
    """-1 lower-is-better, +1 higher-is-better, None unknown. Checked
    most-specific token first so ``goodput_fraction`` beats ``_ms``-style
    substring accidents."""
    p = path.lower()
    for tok in HIGHER_IS_BETTER:
        if tok in p:
            return 1
    for tok in LOWER_IS_BETTER:
        if tok in p:
            return -1
    return None


def flatten(obj: Any, prefix: str = "") -> Dict[str, float]:
    """Dotted-path -> numeric leaf (bools excluded: a True/False flip is
    reported separately, not as 1.0 vs 0.0)."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(flatten(v, f"{prefix}.{k}" if prefix else str(k)))
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)):
        out[prefix] = float(obj)
    return out


def load_run_dir(path: str) -> Dict[str, Dict[str, float]]:
    """bench name -> flattened numeric metrics from <name>.json files."""
    out: Dict[str, Dict[str, float]] = {}
    for fn in sorted(os.listdir(path)):
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(path, fn)) as fh:
                rec = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"  !! unreadable {fn}: {exc}", file=sys.stderr)
            continue
        name = rec.get("name", fn[:-5]) if isinstance(rec, dict) else fn[:-5]
        if isinstance(rec, dict) and rec.get("skipped"):
            continue
        result = rec.get("result", rec) if isinstance(rec, dict) else rec
        flat = flatten(result)
        # Bench results name their headline scalar via a sibling "metric"
        # string; fold it into the path so direction() can classify it.
        if isinstance(result, dict) and "value" in flat \
                and isinstance(result.get("metric"), str):
            flat[f"value[{result['metric']}]"] = flat.pop("value")
        out[name] = flat
    return out


def load_metrics_jsonl(path: str) -> Dict[str, Dict[str, float]]:
    """One pseudo-bench ("metrics") -> last value per (name, labels)."""
    series: Dict[str, float] = {}
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "name" not in rec:
                continue
            if "value" not in rec or isinstance(rec["value"], (dict, list)):
                continue
            labels = rec.get("labels") or {}
            key = rec["name"] + "".join(
                f"{{{k}={labels[k]}}}" for k in sorted(labels))
            try:
                series[key] = float(rec["value"])
            except (TypeError, ValueError):
                continue
    return {"metrics": series}


def load_profile_summary(path: str) -> Dict[str, Dict[str, float]]:
    """One pseudo-bench ("profile_summary") -> per-entry measured vs
    predicted columns from the deep profiler's artifact
    (``observability/profiler.py``). Column paths carry the entry name
    (``serving/decode.model_error``) so direction() classifies them and
    run-over-run deltas stay per-entry."""
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "entries" not in doc:
        raise SystemExit(f"benchdiff: {path} is not a profile_summary.json "
                         "(no 'entries' key)")
    flat: Dict[str, float] = {}
    for entry, row in sorted(doc.get("entries", {}).items()):
        if not isinstance(row, dict):
            continue
        for col in ("measured_step_ms", "predicted_step_ms", "model_error",
                    "measured_mfu", "device_s", "host_s", "invocations"):
            v = row.get(col)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                flat[f"{entry}.{col}"] = float(v)
    return {"profile_summary": flat}


def load(path: str) -> Dict[str, Dict[str, float]]:
    if os.path.isdir(path):
        return load_run_dir(path)
    if path.endswith(".jsonl"):
        return load_metrics_jsonl(path)
    if path.endswith(".json"):
        return load_profile_summary(path)
    raise SystemExit(f"benchdiff: {path} is neither a run directory, a "
                     ".jsonl metrics file, nor a profile_summary.json")


def diff(old: Dict[str, Dict[str, float]],
         new: Dict[str, Dict[str, float]],
         threshold_pct: float) -> Iterable[Tuple[str, str, Optional[float],
                                                 Optional[float], str]]:
    """(bench, metric, old, new, flag) rows; flag in
    {'', 'REGRESSION', 'improved', 'added', 'removed'}."""
    for bench in sorted(set(old) | set(new)):
        o, n = old.get(bench), new.get(bench)
        if o is None or n is None:
            yield (bench, "*", None, None,
                   "added" if o is None else "removed")
            continue
        for path in sorted(set(o) | set(n)):
            ov, nv = o.get(path), n.get(path)
            if ov is None or nv is None:
                yield (bench, path, ov, nv,
                       "added" if ov is None else "removed")
                continue
            if ov == nv:
                continue
            pct = (100.0 * (nv - ov) / abs(ov)) if ov else float("inf")
            d = direction(path)
            flag = ""
            if d is not None and abs(pct) >= threshold_pct:
                worse = (pct > 0) if d < 0 else (pct < 0)
                flag = "REGRESSION" if worse else "improved"
            yield (bench, path, ov, nv, flag)


def main(argv) -> int:
    ap = argparse.ArgumentParser(
        description="diff two bench runs (directories of <name>.json or "
                    "metrics .jsonl files)")
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--threshold", type=float, default=5.0,
                    help="percent change to flag (default 5)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any REGRESSION was flagged")
    ap.add_argument("--all", action="store_true",
                    help="print unchanged-direction/small deltas too")
    args = ap.parse_args(argv)

    rows = list(diff(load(args.old), load(args.new), args.threshold))
    regressions = 0
    printed = 0
    print(f"benchdiff: {args.old} -> {args.new} "
          f"(threshold {args.threshold:g}%)")
    for bench, path, ov, nv, flag in rows:
        if flag == "REGRESSION":
            regressions += 1
        elif not args.all and flag not in ("improved", "added", "removed"):
            continue
        if ov is None or nv is None:
            print(f"  [{flag:>10}] {bench}: {path}")
        else:
            pct = (100.0 * (nv - ov) / abs(ov)) if ov else float("inf")
            mark = flag or "changed"
            print(f"  [{mark:>10}] {bench}: {path}  "
                  f"{ov:g} -> {nv:g} ({pct:+.1f}%)")
        printed += 1
    if not printed:
        print("  no flagged deltas")
    print(f"benchdiff: {regressions} regression(s) flagged")
    return 1 if (args.strict and regressions) else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
