#!/usr/bin/env bash
# Repo concurrency gate: tpusync over the host-orchestration scope
# (serving/, serving/fleet/, observability/, launcher/, runtime/session.py,
# runtime/checkpoint.py) against the committed baseline. Exits non-zero on
# any new finding — unguarded shared write, lock-order inversion, blocking
# call under a lock, signal-unsafe handler, callback under a lock — or a
# stale baseline entry. Usage: scripts/sync.sh [extra tpusync args...]
set -euo pipefail

cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m tools.tpusync \
    --baseline .tpusync-baseline.json "$@"
