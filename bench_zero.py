#!/usr/bin/env python
"""ZeRO + offload benchmark — BASELINE tracked config #2 (ZeRO Adam on
OPT-1.3B). Prints ONE JSON line.

The single-chip showcase of the offload tier (reference ZeRO-Offload blog
claim: 1.4B trainable on one V100-16GB, docs/_posts/2021-03-08-zero3-offload):
OPT-1.3B AdamW training on one 16 GB chip — the fp32 master + moments
(~15.6 GB, 12 bytes/param) live in host memory via
``offload_optimizer.device='cpu'``; HBM holds only bf16 params + grads +
remat'd activations. Without offload this config does not fit.

``vs_baseline`` = MFU / 0.5 (same north-star normalisation as bench.py).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from bench import peak_flops_per_chip


def _maybe_report_oom(e: Exception, metric: str, preset: str) -> None:
    """On device OOM, print a structured record instead of only a traceback:
    a resident-ZeRO config that physically exceeds one chip's HBM (BASELINE
    tracked config #2 as specified: OPT-1.3B Adam => ~21 GB fp32 state +
    bf16 params/grads on a 16 GB v5e) is an honest single-chip result, not a
    harness failure — partitioned ZeRO states need world > 1 to shrink."""
    msg = str(e)
    if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
        print(json.dumps({
            "metric": metric, "value": None, "unit": "tokens/s",
            "vs_baseline": None, "oom": True,
            "single_chip_caveat": (
                f"{preset} resident ZeRO does not fit one chip's HBM "
                "(fp32 Adam state is 12 bytes/param; ZeRO partitioning "
                "reduces per-chip state only at world > 1) — the offload "
                "variants are the single-chip path"),
            "reason": msg[-300:],
        }))


def main() -> None:
    import deepspeed_tpu
    from deepspeed_tpu.models import create_model

    preset = os.environ.get("BENCH_ZERO_MODEL", "opt-1.3b")
    batch = int(os.environ.get("BENCH_ZERO_BATCH", 4))
    seq = int(os.environ.get("BENCH_ZERO_SEQ", 1024))
    stage = int(os.environ.get("BENCH_ZERO_STAGE", 2))
    offload = os.environ.get("BENCH_ZERO_OFFLOAD", "cpu")
    # BENCH_ZERO_PARAM_OFFLOAD=cpu|nvme: ZeRO-3 param offload — the whole
    # model's params stream through HBM per layer block (llama-7b trains on
    # one 16 GB chip; bf16 params alone are 13.5 GB). Forces stage 3 and
    # takes over the optimizer-state placement (host fp32).
    param_offload = os.environ.get("BENCH_ZERO_PARAM_OFFLOAD", "none")
    kw = {}
    if os.environ.get("BENCH_ZERO_LAYERS"):     # depth override: scale probes
        kw["num_layers"] = int(os.environ["BENCH_ZERO_LAYERS"])
    model = create_model(preset, dtype=jnp.bfloat16, remat=True,
                         remat_policy="dots", max_seq_len=seq, **kw)
    if param_offload != "none":
        stage, offload = 3, "none"
        zero_cfg = {"stage": 3,
                    "offload_param": {
                        "device": param_offload,
                        "buffer_size": int(os.environ.get(
                            "BENCH_ZERO_BUFFER", 800_000_000))}}
    else:
        zero_cfg = {"stage": stage}
        if offload != "none":
            zero_cfg["offload_optimizer"] = {"device": offload}
    cfg = {
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": zero_cfg,
    }
    tag = (f"param_offload-{param_offload}" if param_offload != "none"
           else f"offload-{offload}")
    metric = f"{preset}_zero{stage}_{tag}_train_tokens_per_sec_per_chip"
    try:
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    except Exception as e:  # noqa: BLE001 — structured OOM record below
        _maybe_report_oom(e, metric, preset)
        raise

    # BENCH_ZERO_WARM=<seconds>: AOT-compile the offload segment programs
    # into the persistent XLA cache under a wall-clock budget, then exit.
    # Re-run until it reports remaining=0, then run the bench normally —
    # this is how >10B models fit a per-command time limit
    # (docs/offload_design.md scale status).
    warm = float(os.environ.get("BENCH_ZERO_WARM", 0))
    if warm > 0 and engine._param_offload is not None:
        done = engine._param_offload.compile_step_programs(
            (batch, seq), budget_s=warm)
        print(json.dumps({"metric": "warm_compile", "compiled": done}))
        return

    ids = jax.random.randint(jax.random.PRNGKey(0), (1, batch, seq), 0,
                             model.config.vocab_size)
    batch_tree = {"input_ids": ids}
    # BENCH_WARMUP: compile/stream warmup steps before timing (at the >10B
    # offload tier each step is minutes over the dev tunnel — 1 suffices
    # once the compile cache is warm)
    try:
        for _ in range(int(os.environ.get("BENCH_WARMUP", 2))):
            float(engine.train_batch(batch=batch_tree))
    except Exception as e:  # noqa: BLE001 — structured OOM record below
        _maybe_report_oom(e, metric, preset)
        raise

    steps = int(os.environ.get("BENCH_STEPS", 5))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch_tree)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    n_params = (engine._n_params if engine.params is None
                else sum(int(p.size) for p in jax.tree.leaves(engine.params)))
    cfg_m = model.config
    flops_per_token = (6 * n_params
                       + 12 * cfg_m.num_layers * cfg_m.hidden_size * seq)
    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip()
    print(json.dumps({
        "metric": metric,
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "params": n_params,
        "vs_baseline": round(mfu / 0.5, 4),
    }))


if __name__ == "__main__":
    main()
