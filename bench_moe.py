#!/usr/bin/env python
"""MoE training benchmark — BASELINE tracked config #4 (8-expert GPT,
all-to-all dispatch). Prints ONE JSON line.

On one chip the expert all-to-all is intra-device (the dispatch/combine
einsums still run); multi-chip EP rides the same program with the expert
axis sharded — dry-run validated by __graft_entry__/tests, measured here
for per-chip throughput.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from bench import peak_flops_per_chip


def _layer0_drop_rate(engine, cfg_m, ids, batch, seq, k) -> float:
    """Routing stats on the exact pre-MLP hidden of layer 0 (learned-pos
    decoder path: embed + attention sub-block + ln2)."""
    import jax

    from deepspeed_tpu.models.transformer import (_norm,
                                                  dot_product_attention)
    from deepspeed_tpu.parallel.moe import top1_plan, top2_plan

    p = engine.params
    l0 = jax.tree.map(lambda x: x[0], p["layers"])
    B, S, H = batch, seq, cfg_m.hidden_size
    N, D = cfg_m.num_heads, cfg_m.head_dim

    @jax.jit
    def pre_mlp_hidden(params, ids):
        x = params["embed"]["tokens"][ids].astype(jnp.float32)
        if cfg_m.position == "learned":
            x = x + params["pos"][jnp.arange(S)].astype(jnp.float32)
        h = _norm(x, l0["ln1"]["scale"], l0["ln1"].get("bias"),
                  cfg_m.norm, cfg_m.norm_eps)
        q = (h @ l0["attn"]["wq"].astype(jnp.float32)
             + l0["attn"].get("bq", 0.0)).reshape(B, S, N, D)
        kk = (h @ l0["attn"]["wk"].astype(jnp.float32)
              + l0["attn"].get("bk", 0.0)).reshape(B, S, N, D)
        v = (h @ l0["attn"]["wv"].astype(jnp.float32)
             + l0["attn"].get("bv", 0.0)).reshape(B, S, N, D)
        attn = dot_product_attention(q, kk, v, None, causal=True)
        out = (attn.reshape(B, S, N * D) @ l0["attn"]["wo"].astype(jnp.float32)
               + l0["attn"].get("bo", 0.0))
        x = x + out
        h2 = _norm(x, l0["ln2"]["scale"], l0["ln2"].get("bias"),
                   cfg_m.norm, cfg_m.norm_eps)
        return (h2.reshape(B * S, H)
                @ l0["router"].astype(jnp.float32))

    logits = pre_mlp_hidden(p, ids)
    plan = (top2_plan(logits, cfg_m.moe_capacity_factor,
                      cfg_m.moe_min_capacity) if k == 2 else
            top1_plan(logits, cfg_m.moe_capacity_factor,
                      cfg_m.moe_min_capacity))
    kept = float(plan.valid.sum())
    return 1.0 - kept / (batch * seq * k)


def main() -> None:
    import deepspeed_tpu
    from deepspeed_tpu.models import create_model

    batch = int(os.environ.get("BENCH_BATCH", 16))
    seq = int(os.environ.get("BENCH_SEQ", 1024))
    # 350m-8e (~1.7B total params) exceeds one v5e's HBM with optimizer
    # state; the 125m-8e variant (~560M) is the single-chip default
    preset = os.environ.get("BENCH_MOE_MODEL", "moe-gpt-125m-8e")
    # unlike the dense bench, full unroll does NOT pay here: the expert
    # dispatch/combine einsums dominate (25.1k tok/s unrolled vs 25.7k
    # scanned on v5e) and the unrolled 8-expert program OOMs compile
    unroll = int(os.environ.get("BENCH_UNROLL", 1))
    dispatch = os.environ.get("BENCH_MOE_DISPATCH", "sparse")
    remat = os.environ.get("BENCH_REMAT", "1") == "1"
    model = create_model(preset, dtype=jnp.bfloat16, remat=remat,
                         remat_policy="dots", scan_unroll=unroll,
                         max_seq_len=seq, moe_dispatch=dispatch)
    cfg = {
        "train_micro_batch_size_per_gpu": batch,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, batch, seq), 0,
                             model.config.vocab_size)
    tree = {"input_ids": ids}
    for _ in range(2):
        loss = engine.train_batch(batch=tree)
    float(loss)
    steps = int(os.environ.get("BENCH_STEPS", 8))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=tree)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    cfg_m = model.config
    # active params per token: dense part + top_k of E experts + router
    n_all = sum(int(p.size) for p in jax.tree.leaves(engine.params))
    expert_params = sum(int(p.size) for p in
                        jax.tree.leaves(engine.params["layers"]["mlp"]))
    active = (n_all - expert_params
              + expert_params * cfg_m.moe_top_k // cfg_m.moe_num_experts)
    flops_per_token = 6 * active + 12 * cfg_m.num_layers * cfg_m.hidden_size * seq

    # ---- roofline accounting (VERDICT r2 #9, r3 weak #1) ----------------
    # einsum: the dense (T,EC)x(T,H) one-hot contraction pays 2*T*E*C*H
    # flops each way — at E=8, cap 1.25, top-2 that is ~5x the expert MLP
    # itself, so that formulation is dispatch-BOUND.
    # sparse (default): dispatch is a GATHER (no flops) and combine is a
    # (T,K,H) gather + weighted sum — dispatch cost scales with routed
    # tokens and the roofline is set by expert compute again.
    from deepspeed_tpu.parallel.moe import _capacity

    H, F, L = cfg_m.hidden_size, cfg_m.ffn_hidden_size, cfg_m.num_layers
    E, k = cfg_m.moe_num_experts, cfg_m.moe_top_k
    T = batch * seq
    C = _capacity(T, E, cfg_m.moe_capacity_factor * (2 if k == 2 else 1),
                  cfg_m.moe_min_capacity)
    n_mat = 3 if cfg_m.activation == "swiglu" else 2
    expert_fwd = 2 * E * C * H * F * n_mat            # per layer
    if cfg_m.moe_dispatch == "einsum":
        dispatch_fwd = 2 * (2 * T * E * C * H)        # dispatch + combine
    else:
        dispatch_fwd = 2 * T * k * H                  # sparse combine only
    # extra fwd flops beyond what 6*active already counts: experts run on
    # CAPACITY slots (E*C >= k*T tokens) plus the dense dispatch einsums
    moe_extra = L * (expert_fwd + dispatch_fwd) - L * 2 * T * (
        expert_params // L) * k // E
    # train = fwd + bwd (2x) + remat recompute (~1x) => 4x forward cost for
    # the MoE layers (dots policy recomputes the einsums)
    total_step_flops = flops_per_token * T + 4 * moe_extra
    roofline_tps = peak_flops_per_chip() * T / total_step_flops
    dispatch_frac = (4 * L * dispatch_fwd) / total_step_flops

    # capacity-drop rate on the TRUE layer-0 router input (embed + attention
    # sub-block + ln2, replicated with the model's own helpers — raw token
    # embeddings route differently): fraction of (token, expert) assignments
    # that exceeded capacity
    drop_rate = _layer0_drop_rate(engine, cfg_m, ids[0], batch, seq, k)

    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip()
    print(json.dumps({
        "metric": f"{preset}_bf16_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "active_param_mfu": round(mfu, 4),
        "vs_baseline": round(mfu / 0.5, 4),
        "vs_roofline": round(tokens_per_sec / roofline_tps, 4),
        "roofline_tokens_per_sec": round(roofline_tps, 1),
        "dispatch_flops_frac": round(dispatch_frac, 4),
        "capacity_drop_rate": round(drop_rate, 4),
        "dispatch_impl": dispatch,
    }))


if __name__ == "__main__":
    main()
