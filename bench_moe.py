#!/usr/bin/env python
"""MoE training benchmark — BASELINE tracked config #4 (8-expert GPT,
all-to-all dispatch). Prints ONE JSON line.

On one chip the expert all-to-all is intra-device (the dispatch/combine
einsums still run); multi-chip EP rides the same program with the expert
axis sharded — dry-run validated by __graft_entry__/tests, measured here
for per-chip throughput.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from bench import peak_flops_per_chip


def main() -> None:
    import deepspeed_tpu
    from deepspeed_tpu.models import create_model

    batch = int(os.environ.get("BENCH_BATCH", 16))
    seq = int(os.environ.get("BENCH_SEQ", 1024))
    # 350m-8e (~1.7B total params) exceeds one v5e's HBM with optimizer
    # state; the 125m-8e variant (~560M) is the single-chip default
    preset = os.environ.get("BENCH_MOE_MODEL", "moe-gpt-125m-8e")
    # unlike the dense bench, full unroll does NOT pay here: the expert
    # dispatch/combine einsums dominate (25.1k tok/s unrolled vs 25.7k
    # scanned on v5e) and the unrolled 8-expert program OOMs compile
    unroll = int(os.environ.get("BENCH_UNROLL", 1))
    model = create_model(preset, dtype=jnp.bfloat16, remat=True,
                         remat_policy="dots", scan_unroll=unroll,
                         max_seq_len=seq)
    cfg = {
        "train_micro_batch_size_per_gpu": batch,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, batch, seq), 0,
                             model.config.vocab_size)
    tree = {"input_ids": ids}
    for _ in range(2):
        loss = engine.train_batch(batch=tree)
    float(loss)
    steps = int(os.environ.get("BENCH_STEPS", 8))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=tree)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    cfg_m = model.config
    # active params per token: dense part + top_k of E experts + router
    n_all = sum(int(p.size) for p in jax.tree.leaves(engine.params))
    expert_params = sum(int(p.size) for p in
                        jax.tree.leaves(engine.params["layers"]["mlp"]))
    active = (n_all - expert_params
              + expert_params * cfg_m.moe_top_k // cfg_m.moe_num_experts)
    flops_per_token = 6 * active + 12 * cfg_m.num_layers * cfg_m.hidden_size * seq
    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip()
    print(json.dumps({
        "metric": f"{preset}_bf16_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "active_param_mfu": round(mfu, 4),
        "vs_baseline": round(mfu / 0.5, 4),
    }))


if __name__ == "__main__":
    main()
