// Async file I/O handle — native side of deepspeed_tpu.ops.aio.
//
// Reference: csrc/aio/ (deepspeed_aio_thread.cpp thread pool +
// deepspeed_py_aio_handle.cpp pread/pwrite queue over libaio). This image
// ships no libaio/liburing headers, so the asynchrony comes from a
// std::thread worker pool issuing positional pread/pwrite (optionally
// O_DIRECT with aligned buffers) — same queue_depth/submit/wait surface,
// same overlap behavior for the NVMe swapper design in
// docs/offload_design.md.
//
// C ABI (ctypes-friendly): every function returns <0 on error.

#include <atomic>
#include <condition_variable>
#include <cerrno>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <functional>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Task {
  bool write;
  int fd;
  char *buf;
  size_t nbytes;
  off_t offset;
};

struct Handle {
  int block_size;
  int queue_depth;
  std::vector<std::thread> workers;
  std::deque<Task> queue;
  std::mutex mu;
  std::condition_variable cv_submit;
  std::condition_variable cv_done;
  std::atomic<long> inflight{0};
  std::atomic<long> completed{0};
  std::atomic<long> errors{0};
  bool stop = false;

  explicit Handle(int block_size_, int queue_depth_, int num_threads)
      : block_size(block_size_), queue_depth(queue_depth_) {
    for (int i = 0; i < num_threads; ++i) {
      workers.emplace_back([this] { run(); });
    }
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_submit.notify_all();
    for (auto &w : workers) w.join();
  }

  void run() {
    for (;;) {
      Task t;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_submit.wait(lk, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        t = queue.front();
        queue.pop_front();
      }
      bool ok = do_io(t);
      {
        // state changes under the mutex — a decrement outside it can race
        // the wait_all predicate check and lose the wakeup
        std::lock_guard<std::mutex> lk(mu);
        if (!ok) errors.fetch_add(1);
        completed.fetch_add(1);
        inflight.fetch_sub(1);
      }
      cv_done.notify_all();
    }
  }

  static bool do_io(const Task &t) {
    size_t done = 0;
    while (done < t.nbytes) {
      ssize_t n =
          t.write ? pwrite(t.fd, t.buf + done, t.nbytes - done, t.offset + done)
                  : pread(t.fd, t.buf + done, t.nbytes - done, t.offset + done);
      if (n < 0 && errno == EINTR) continue;  // interrupted — retry
      if (n <= 0) return false;               // error, or EOF short read
      done += static_cast<size_t>(n);
    }
    return true;
  }

  int submit(bool write, int fd, char *buf, size_t nbytes, off_t offset) {
    {
      std::unique_lock<std::mutex> lk(mu);
      // bounded queue: respect queue_depth like the reference aio context
      cv_done.wait(lk, [this] {
        return static_cast<int>(queue.size()) < queue_depth;
      });
      queue.push_back(Task{write, fd, buf, nbytes, offset});
      inflight.fetch_add(1);
    }
    cv_submit.notify_one();
    return 0;
  }

  long wait_all() {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [this] { return inflight.load() == 0; });
    long e = errors.exchange(0);
    return e == 0 ? completed.load() : -e;
  }
};

} // namespace

extern "C" {

void *dsaio_create(int block_size, int queue_depth, int num_threads) {
  if (num_threads <= 0 || queue_depth <= 0) return nullptr;
  return new Handle(block_size, queue_depth, num_threads);
}

void dsaio_destroy(void *h) { delete static_cast<Handle *>(h); }

int dsaio_open(const char *path, int for_write, int direct) {
  int flags = for_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
#ifdef O_DIRECT
  if (direct) flags |= O_DIRECT;
#endif
  return open(path, flags, 0644);
}

int dsaio_close(int fd) { return close(fd); }

int dsaio_submit_pread(void *h, int fd, void *buf, long nbytes, long offset) {
  return static_cast<Handle *>(h)->submit(false, fd, static_cast<char *>(buf),
                                          static_cast<size_t>(nbytes),
                                          static_cast<off_t>(offset));
}

int dsaio_submit_pwrite(void *h, int fd, void *buf, long nbytes, long offset) {
  return static_cast<Handle *>(h)->submit(true, fd, static_cast<char *>(buf),
                                          static_cast<size_t>(nbytes),
                                          static_cast<off_t>(offset));
}

// blocks until every submitted op lands; returns total completed (<0: errors)
long dsaio_wait(void *h) { return static_cast<Handle *>(h)->wait_all(); }

int dsaio_block_size(void *h) { return static_cast<Handle *>(h)->block_size; }
int dsaio_queue_depth(void *h) { return static_cast<Handle *>(h)->queue_depth; }
}
