"""tpusync core — module model, annotations, rule registry, driver.

Same skeleton as ``tools/tpulint/core.py`` (stdlib-only, Finding keyed by
``path::rule``, inline suppressions, shared baseline gate), but the unit of
analysis is the **whole program**, not one module: races and deadlocks live
in the composition of modules (a router thread calling into an engine, a
signal handler re-entering the recorder), so the rules run once over a
cross-module :class:`~tools.tpusync.threadgraph.Program`.

Annotation vocabulary (all comments, all optional):

* ``# tpusync: disable=<rule>[,<rule>...]`` — suppress findings on this
  line (a comment-only line also covers the next line, tpulint semantics);
* ``# tpusync: guarded-by=<lock>`` on an attribute assignment — declare
  that ``self.<attr>`` must only be written while holding ``self.<lock>``;
  every write site is then checked, even single-root ones;
* ``# tpusync: thread-root=<name>`` on a ``def`` — declare an entry point
  the AST cannot see (RPC dispatch, C callback), adding root ``<name>``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.tpulint.core import iter_python_files

__all__ = [
    "Finding", "SyncModule", "Rule", "RULES", "register",
    "analyze_source", "analyze_paths", "build_program", "DEFAULT_SCOPE",
]

# the host-orchestration surface the gate runs over (scripts/sync.sh)
DEFAULT_SCOPE = (
    "deepspeed_tpu/serving",
    "deepspeed_tpu/observability",
    "deepspeed_tpu/launcher",
    "deepspeed_tpu/runtime/session.py",
    "deepspeed_tpu/runtime/checkpoint.py",
)

_SUPPRESS_RE = re.compile(
    r"#.*?tpusync:\s*disable="
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")
_GUARDED_RE = re.compile(r"#.*?tpusync:\s*guarded-by=([A-Za-z0-9_.]+)")
_ROOT_RE = re.compile(r"#.*?tpusync:\s*thread-root=([A-Za-z0-9_\-:.]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``key`` (path::rule) is the baseline bucket —
    identical to tpulint's so ``tools/tpulint/baseline.py`` drives the
    gate unchanged."""
    rule: str
    path: str
    line: int
    col: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.rule}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class SyncModule:
    """Parsed module plus the annotation/lookup surface the program model
    and the rules need."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.aliases = self._collect_aliases(self.tree)
        self.suppressions = self._collect_suppressions(self.lines)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self._guard_lines = self._annotation_lines(_GUARDED_RE)
        self._root_lines = self._annotation_lines(_ROOT_RE)
        # (class or "", attr) -> declared guarding lock attribute
        self.guarded_by: Dict[Tuple[str, str], str] = {}
        self._collect_guards()
        # def lineno -> declared root label
        self.thread_root_annotations: Dict[int, str] = {}
        self._collect_root_decls()

    # -- parsing helpers ---------------------------------------------------
    @staticmethod
    def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
        aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    @staticmethod
    def _collect_suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, text in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(i, set()).update(rules)
            if text.lstrip().startswith("#"):
                # a comment-only suppression covers the next CODE line —
                # the why-comment it opens may run several lines
                j = i + 1
                while j <= len(lines) and \
                        lines[j - 1].lstrip().startswith("#"):
                    j += 1
                out.setdefault(j, set()).update(rules)
        return out

    def _annotation_lines(self, rx: re.Pattern) -> Dict[int, str]:
        """line -> annotation value; a comment-only line also annotates the
        next line (mirrors suppression placement rules)."""
        out: Dict[int, str] = {}
        for i, text in enumerate(self.lines, start=1):
            m = rx.search(text)
            if not m:
                continue
            out[i] = m.group(1)
            if text.lstrip().startswith("#"):
                out.setdefault(i + 1, m.group(1))
        return out

    def _collect_guards(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                continue
            lock = self._guard_lines.get(node.lineno)
            if lock is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) else \
                [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    cls = self.enclosing_class(tgt) or ""
                    self.guarded_by[(cls, tgt.attr)] = lock
                elif isinstance(tgt, ast.Name):
                    cls = self.enclosing_class(tgt) or ""
                    self.guarded_by[(cls, tgt.id)] = lock

    def _collect_root_decls(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                label = self._root_lines.get(node.lineno)
                if label is not None:
                    self.thread_root_annotations[node.lineno] = label

    # -- lookups -----------------------------------------------------------
    def dotted(self, node: ast.AST) -> Optional[str]:
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    def enclosing_class(self, node: ast.AST) -> Optional[str]:
        """Name of the innermost class the node sits in (crossing function
        scopes — ``self.x`` inside a method belongs to the class)."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = self.parents.get(cur)
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line, ())
        return rule in rules or "all" in rules


class Rule:
    """Subclasses set ``name``/``description`` and implement
    ``check(program) -> Iterator[Finding]`` over the whole program."""

    name: str = ""
    description: str = ""

    def check(self, program) -> Iterator[Finding]:
        raise NotImplementedError


RULES: List[Rule] = []


def register(cls):
    RULES.append(cls())
    return cls


def build_program(modules: List[SyncModule]):
    """Cross-module thread/lock model. (Import deferred: threadgraph
    imports nothing from here, but keeping the seam explicit.)"""
    from .threadgraph import Program
    return Program(modules)


def _run_rules(modules: List[SyncModule],
               select: Optional[Set[str]] = None) -> List[Finding]:
    from . import rules as _rules  # noqa: F401  (registers RULES)

    program = build_program(modules)
    by_path = {m.path: m for m in modules}
    findings: List[Finding] = []
    for rule in RULES:
        if select and rule.name not in select:
            continue
        for f in rule.check(program):
            mod = by_path.get(f.path)
            if mod is None or not mod.suppressed(f.rule, f.line):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_source(source: str, path: str = "<string>",
                   select: Optional[Set[str]] = None) -> List[Finding]:
    """Single-module entry point (fixture tests). The 'program' is just
    this module."""
    try:
        module = SyncModule(path, source)
    except SyntaxError as e:
        return [Finding("syntax-error", path, e.lineno or 0, e.offset or 0,
                        f"could not parse: {e.msg}")]
    return _run_rules([module], select)


def analyze_paths(paths: Sequence[str], root: Optional[str] = None,
                  select: Optional[Set[str]] = None) -> List[Finding]:
    """Whole-program run: parse every file under ``paths`` into ONE model,
    then apply the rules once. ``root`` makes finding paths relative
    (stable baseline keys)."""
    root = root or os.getcwd()
    modules: List[SyncModule] = []
    findings: List[Finding] = []
    for fpath in iter_python_files(paths):
        rel = os.path.relpath(fpath, root).replace(os.sep, "/")
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                modules.append(SyncModule(rel, fh.read()))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            msg = getattr(e, "msg", None) or str(e)
            findings.append(Finding(
                "syntax-error", rel, getattr(e, "lineno", 0) or 0,
                getattr(e, "offset", 0) or 0, f"could not parse: {msg}"))
    findings.extend(_run_rules(modules, select))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
