"""Whole-program thread-root reachability + lock model.

The concurrency mirror of tpulint's jit-reachability graph: instead of
asking "which functions are traced under jit", tpusync asks "which functions
execute on which *thread roots*" and "which locks are held where".

**Roots** are the places a new flow of control enters Python:

* ``main`` — the importing/caller thread. Seeded onto module top-level code,
  public (non-underscore) functions and dunder methods (anything a client
  can call), then propagated through calls;
* ``thread:<name>`` — ``threading.Thread(target=f, name="<name>")`` spawns
  (the router driver, hang watchdog, async-save publisher, ...);
* ``signal:<SIG>`` — ``signal.signal(SIG, handler)`` handlers, which run
  *on top of* whatever the main thread was doing;
* ``executor:<fn>`` — ``ThreadPoolExecutor.submit/map`` operands;
* ``# tpusync: thread-root=<name>`` — annotation for entry points the AST
  cannot see (RPC dispatch, C callbacks).

Reachability closes over calls resolved by simple name: bare names within
the module (import aliases followed across analyzed modules), attribute
calls (``self.step()``, ``r.engine.submit()``) against every same-named
def in the program, and callback *bindings* (``obj.on_fire = f`` makes a
later ``x.on_fire()`` call resolve to ``f``). Deliberately name-based and
over-approximate — wrong only in the conservative direction, with inline
suppressions as the escape hatch (same contract as tpulint).

**Locks** are identified by declaration site: ``self._lock =
threading.Lock()`` in class ``C`` is the node ``C._lock`` (per module), a
module-level ``L = threading.Lock()`` is ``L``. ``with`` regions feed three
derived facts used by the rules:

* ``held_at(stmt)`` — the with-stack inside the function plus the
  *entry-held* set: locks held at EVERY call site of the function
  (intersection, to fixpoint);
* ``acquires(fn)`` — locks a function may take, closed over callees;
* the **lock-order graph** — edge ``A -> B`` when ``B`` is acquired
  (directly or via a call) inside a ``with A:`` region. A cycle is a
  potential deadlock; a self-edge is flagged only for non-reentrant kinds
  (``Lock``/``Condition`` — re-entering an ``RLock`` is its purpose).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

FunctionNode = ast.AST  # FunctionDef | AsyncFunctionDef | Lambda

_THREAD_CTORS = {"threading.Thread", "Thread"}
_EXECUTOR_CTORS = {"concurrent.futures.ThreadPoolExecutor",
                   "ThreadPoolExecutor",
                   "concurrent.futures.ProcessPoolExecutor",
                   "ProcessPoolExecutor"}
_LOCK_CTORS = {
    "threading.Lock": "Lock", "threading.RLock": "RLock",
    "threading.Condition": "Condition", "Lock": "Lock", "RLock": "RLock",
    "Condition": "Condition", "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
}
_NONREENTRANT = {"Lock", "Condition"}
_STDLIB_ROOTS = {"subprocess", "threading", "queue", "socket", "io", "os",
                 "collections", "tempfile", "multiprocessing", "selectors"}
# Method names so generic (dicts, files, stdlib containers) that resolving
# them to same-named program defs manufactures false call edges — excluded
# from the global by-name fallback (typed receivers still resolve).
_GENERIC_METHODS = {"get", "set", "close", "flush", "update", "pop", "put",
                    "copy", "clear", "read", "write", "items", "keys",
                    "values", "add", "remove"}


@dataclasses.dataclass(frozen=True)
class LockId:
    """One lock node. ``scope`` is 'cls' (class attribute), 'mod'
    (module-level name) or 'loc' (function-local)."""
    scope: str
    module: str
    owner: str      # class name / "" / function qualname
    name: str       # attribute or variable name

    @property
    def display(self) -> str:
        if self.scope == "cls":
            return f"{self.owner}.{self.name}"
        return self.name

    @property
    def key(self) -> str:
        return f"{self.module}::{self.owner}::{self.name}"


@dataclasses.dataclass(eq=False)
class FuncInfo:
    """One function/lambda in the program."""
    module: "object"            # SyncModule (untyped to avoid the cycle)
    node: FunctionNode
    name: str                   # simple name ("" for lambdas)
    qualname: str               # Class.name or name
    class_name: Optional[str]
    line: int
    roots: Set[str] = dataclasses.field(default_factory=set)
    # entry-held fixpoint state: None = not yet constrained (universe)
    entry_held: Optional[FrozenSet[LockId]] = None
    spawn_only: bool = False    # registered as a spawn/signal/executor
    #   target (main is NOT implied by having no callers)


@dataclasses.dataclass
class WriteSite:
    """One shared-state mutation (assignment or mutating method call)."""
    func: FuncInfo
    attr: str                   # attribute / global name
    owner: str                  # class name or "" for module globals
    module: "object"
    line: int
    col: int
    held: FrozenSet[LockId]
    in_init: bool


class Program:
    """The cross-module model one tpusync run reasons over."""

    def __init__(self, modules: List["object"]):
        self.modules = modules
        self.functions: List[FuncInfo] = []
        self.by_node: Dict[FunctionNode, FuncInfo] = {}
        self.defs_by_name: Dict[str, List[FuncInfo]] = {}
        # callback bindings: attr name -> FuncInfos assigned to `<x>.attr`
        self.attr_bindings: Dict[str, List[FuncInfo]] = {}
        self.locks: Dict[LockId, str] = {}           # -> kind (Lock/RLock/..)
        self.lock_decl_site: Dict[LockId, Tuple[str, int]] = {}
        self.call_edges: Dict[FuncInfo, Set[FuncInfo]] = {}
        # spawn/signal/executor registrations: (root label, target, site)
    # spawn sites double as the gate-report's per-root census
        self.spawns: List[Tuple[str, FuncInfo, Tuple[str, int]]] = []
        # lock-order edges: (A, B) -> example (path, line, via) site
        self.order_edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]] = {}
        # resolve_call memo: everything it reads (aliases, attr_classes,
        # defs) is frozen before the first resolution, and the fixpoint
        # passes re-resolve the same call nodes many times over
        self._resolve_cache: Dict[int, List[FuncInfo]] = {}
        self._collect_functions()
        # class names with at least one def — the "known types" universe
        # every precision layer checks against (hoisted: it was rebuilt
        # per resolved call)
        self._known_classes = {fi.class_name for fi in self.functions
                               if fi.class_name}
        self._collect_locks()
        self._collect_bindings()
        self._collect_attr_classes()
        self._collect_spawns()
        self._build_call_edges()
        self._propagate_roots()
        self._compute_entry_held()
        self._compute_acquires()
        self._build_order_edges()

    # -- gathering ---------------------------------------------------------
    def _collect_functions(self) -> None:
        for mod in self.modules:
            for node, class_name in _walk_defs(mod.tree):
                name = getattr(node, "name", "")
                qual = f"{class_name}.{name}" if class_name else (
                    name or f"<lambda:{node.lineno}>")
                fi = FuncInfo(module=mod, node=node, name=name,
                              qualname=qual, class_name=class_name,
                              line=node.lineno)
                self.functions.append(fi)
                self.by_node[node] = fi
                if name:
                    self.defs_by_name.setdefault(name, []).append(fi)
            # explicit thread-root annotations
            for fi in self.functions:
                if fi.module is mod:
                    label = mod.thread_root_annotations.get(fi.node.lineno)
                    if label:
                        fi.roots.add(label)
                        fi.spawn_only = True

    def _collect_locks(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign) or \
                        not isinstance(node.value, ast.Call):
                    continue
                ctor = mod.dotted(node.value.func)
                kind = _LOCK_CTORS.get(ctor or "")
                if kind is None:
                    continue
                for tgt in node.targets:
                    lid = self._lock_target_id(mod, tgt)
                    if lid is not None:
                        self.locks[lid] = kind
                        self.lock_decl_site.setdefault(
                            lid, (mod.path, node.lineno))

    def _lock_target_id(self, mod, tgt: ast.AST) -> Optional[LockId]:
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            cls = mod.enclosing_class(tgt)
            if cls:
                return LockId("cls", mod.path, cls, tgt.attr)
        elif isinstance(tgt, ast.Name):
            fn = mod.enclosing_function(tgt)
            if fn is None:
                return LockId("mod", mod.path, "", tgt.id)
            fi = self.by_node.get(fn)
            return LockId("loc", mod.path,
                          fi.qualname if fi else "?", tgt.id)
        return None

    def _collect_bindings(self) -> None:
        """``<expr>.attr = <func|lambda>`` — callback seams the attr-call
        resolver follows (``on_prefill_complete``, ``context_fn``, ...)."""
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                target_fi = self._operand_funcs(mod, node.value)
                if not target_fi:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        self.attr_bindings.setdefault(
                            tgt.attr, []).extend(target_fi)

    def _operand_funcs(self, mod, expr: ast.AST) -> List[FuncInfo]:
        """FuncInfos an expression may evaluate to (name / self-attr /
        lambda)."""
        if isinstance(expr, ast.Lambda):
            fi = self.by_node.get(expr)
            return [fi] if fi else []
        if isinstance(expr, ast.Name):
            return [fi for fi in self.defs_by_name.get(expr.id, ())
                    if fi.module is mod]
        if isinstance(expr, ast.Attribute):
            # self._drive / obj.method — match by simple name, module first
            cands = self.defs_by_name.get(expr.attr, [])
            local = [fi for fi in cands if fi.module is mod]
            return local or cands
        return []

    def _collect_attr_classes(self) -> None:
        """name -> classes it is constructed as (``self.sched =
        Scheduler(...)``, ``mon = FleetHealthMonitor(...)``) — a light type
        layer that keeps receiver-qualified calls inside the right class."""
        self.attr_classes: Dict[str, Set[str]] = {}
        known = self._known_classes
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    ctor = (mod.dotted(node.value.func) or
                            "").rpartition(".")[2]
                    if ctor not in known:
                        continue
                    for tgt in node.targets:
                        name = tgt.attr if isinstance(tgt, ast.Attribute) \
                            else (tgt.id if isinstance(tgt, ast.Name)
                                  else None)
                        if name is not None:
                            self.attr_classes.setdefault(
                                name, set()).add(ctor)
                elif isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Name):
                    # `self._engine = engine` where the enclosing function
                    # annotates the parameter: param types flow onto attrs
                    fn = mod.enclosing_function(node)
                    leaf = _param_type(mod, fn, node.value.id) \
                        if fn is not None else None
                    if leaf in known:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Attribute):
                                self.attr_classes.setdefault(
                                    tgt.attr, set()).add(leaf)
                elif isinstance(node, ast.AnnAssign):
                    # dataclass fields / annotated attrs: `engine:
                    # ServingEngine` (string annotations included)
                    ann = node.annotation
                    if isinstance(ann, ast.Constant) and \
                            isinstance(ann.value, str):
                        leaf = ann.value.rpartition(".")[2].strip("'\" ")
                    else:
                        leaf = (mod.dotted(ann) or "").rpartition(".")[2]
                    tgt = node.target
                    name = tgt.attr if isinstance(tgt, ast.Attribute) else (
                        tgt.id if isinstance(tgt, ast.Name) else None)
                    if leaf in known and name is not None:
                        self.attr_classes.setdefault(name, set()).add(leaf)

    def _collect_spawns(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = mod.dotted(node.func) or ""
                site = (mod.path, node.lineno)
                if dotted in _THREAD_CTORS:
                    target = _kwarg(node, "target") or (
                        node.args[0] if node.args else None)
                    label = None
                    name_kw = _kwarg(node, "name")
                    if isinstance(name_kw, ast.Constant) and \
                            isinstance(name_kw.value, str):
                        label = f"thread:{name_kw.value}"
                    for fi in self._operand_funcs(mod, target) \
                            if target is not None else []:
                        self._register_root(
                            fi, label or f"thread:{fi.name or 'lambda'}",
                            site)
                elif dotted == "signal.signal" and len(node.args) >= 2:
                    sig = mod.dotted(node.args[0]) or "?"
                    signame = sig.rpartition(".")[2]
                    for fi in self._operand_funcs(mod, node.args[1]):
                        self._register_root(fi, f"signal:{signame}", site)
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("submit", "map") and node.args:
                    if self._is_executor(mod, node.func.value):
                        for fi in self._operand_funcs(mod, node.args[0]):
                            self._register_root(
                                fi, f"executor:{fi.name or 'lambda'}", site)

    def _local_types(self, mod, name_node: ast.Name) -> Set[str]:
        """Classes a local variable may hold, from assignments in its
        enclosing function: ctor calls and typed-attribute loads (``rt =
        obs.reqtrace`` picks up ``reqtrace``'s construction-site type)."""
        fn = mod.enclosing_function(name_node)
        if fn is None:
            return set()
        known = self._known_classes
        out: Set[str] = set()
        resolved_all = True
        for n in ast.walk(fn):
            if isinstance(n, (ast.For, ast.AsyncFor)) and \
                    isinstance(n.target, ast.Name) and \
                    n.target.id == name_node.id:
                elems = self._iter_elem_types(mod, fn, n.iter)
                if elems:
                    out.update(elems)
                else:
                    resolved_all = False
                continue
            if not isinstance(n, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == name_node.id
                       for t in n.targets):
                continue
            if isinstance(n.value, ast.Call):
                dotted = mod.dotted(n.value.func) or ""
                ctor = dotted.rpartition(".")[2]
                if ctor in known:
                    out.add(ctor)
                elif dotted.split(".")[0] in _STDLIB_ROOTS:
                    # stdlib object (Popen, socket, deque...): its methods
                    # never resolve to program defs
                    out.add("<external>")
                else:
                    resolved_all = False
            elif isinstance(n.value, ast.Attribute):
                types = self.attr_classes.get(n.value.attr)
                if types:
                    out.update(types)
                else:
                    resolved_all = False
            else:
                resolved_all = False
        # a binding we could not type may hold anything: don't narrow
        return out if resolved_all and out else set()

    def _iter_elem_types(self, mod, fn: ast.AST,
                         iter_expr: ast.AST) -> Set[str]:
        """Element classes of a ``for x in <iter>`` loop, from the
        iterable's AnnAssign annotation (``procs: List[subprocess.Popen]``
        types every loop variable drawn from it)."""
        ann = None
        names, attrs = self._annassign_index(mod)
        if isinstance(iter_expr, ast.Name):
            # closures read enclosing-scope names: search the function,
            # then the whole module
            for n in ast.walk(fn):
                if isinstance(n, ast.AnnAssign) and \
                        isinstance(n.target, ast.Name) and \
                        n.target.id == iter_expr.id:
                    ann = n.annotation
            if ann is None:
                ann = names.get(iter_expr.id)
        elif isinstance(iter_expr, ast.Attribute) and \
                isinstance(iter_expr.value, ast.Name) and \
                iter_expr.value.id == "self":
            ann = attrs.get(iter_expr.attr)
        if not isinstance(ann, ast.Subscript):
            return set()
        elem = ann.slice
        if isinstance(elem, ast.Tuple) and elem.elts:   # Dict[K, V] → V
            elem = elem.elts[-1]
        dotted = mod.dotted(elem) or ""
        if not dotted:
            return set()
        if dotted.split(".")[0] in _STDLIB_ROOTS:
            return {"<external>"}
        leaf = dotted.rpartition(".")[2]
        known = self._known_classes
        return {leaf} if leaf in known else set()

    def _annassign_index(self, mod) -> Tuple[Dict[str, ast.AST],
                                             Dict[str, ast.AST]]:
        """One walk per module: AnnAssign annotations by plain name and by
        ``self.<attr>`` (last declaration wins, matching the linear-scan
        semantics this replaces)."""
        idx = getattr(mod, "_tpusync_ann_idx", None)
        if idx is None:
            names: Dict[str, ast.AST] = {}
            attrs: Dict[str, ast.AST] = {}
            for n in ast.walk(mod.tree):
                if not isinstance(n, ast.AnnAssign):
                    continue
                if isinstance(n.target, ast.Name):
                    names[n.target.id] = n.annotation
                elif isinstance(n.target, ast.Attribute) and \
                        isinstance(n.target.value, ast.Name) and \
                        n.target.value.id == "self":
                    attrs[n.target.attr] = n.annotation
            idx = (names, attrs)
            mod._tpusync_ann_idx = idx
        return idx

    def _is_executor(self, mod, recv: ast.AST) -> bool:
        """Does this receiver look like a futures executor? (``submit`` is
        also the serving API's verb — only spelled receivers count.)"""
        text = mod.dotted(recv) or ""
        leaf = text.rpartition(".")[2].lower()
        if "pool" in leaf or "executor" in leaf:
            return True
        # local name assigned (or with-bound) from an executor ctor
        fn = mod.enclosing_function(recv)
        scope = fn if fn is not None else mod.tree
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and mod.dotted(n.value.func) in _EXECUTOR_CTORS:
                for t in n.targets:
                    if isinstance(t, ast.Name) and t.id == text:
                        return True
            if isinstance(n, ast.withitem) and \
                    isinstance(n.context_expr, ast.Call) and \
                    mod.dotted(n.context_expr.func) in _EXECUTOR_CTORS and \
                    isinstance(n.optional_vars, ast.Name) and \
                    n.optional_vars.id == text:
                return True
        return False

    def _register_root(self, fi: FuncInfo, label: str,
                       site: Tuple[str, int]) -> None:
        fi.roots.add(label)
        fi.spawn_only = True
        self.spawns.append((label, fi, site))

    # -- call graph + root propagation -------------------------------------
    def _build_call_edges(self) -> None:
        for fi in self.functions:
            edges: Set[FuncInfo] = set()
            for node in _own_nodes(fi.node):
                if isinstance(node, ast.Call):
                    edges.update(self.resolve_call(fi.module, node))
            self.call_edges[fi] = edges

    def resolve_call(self, mod, call: ast.Call) -> List[FuncInfo]:
        cached = self._resolve_cache.get(id(call))
        if cached is None:
            cached = self._resolve_call_uncached(mod, call)
            self._resolve_cache[id(call)] = cached
        return cached

    def _resolve_call_uncached(self, mod, call: ast.Call) -> List[FuncInfo]:
        fn = call.func
        if isinstance(fn, ast.Name):
            # bare name: module-local defs, else alias-followed cross-module
            local = [fi for fi in self.defs_by_name.get(fn.id, ())
                     if fi.module is mod]
            if local:
                return local
            dotted = mod.aliases.get(fn.id)
            if dotted:
                leaf = dotted.rpartition(".")[2]
                return [fi for fi in self.defs_by_name.get(leaf, ())
                        if fi.module is not mod]
            return []
        if isinstance(fn, ast.Attribute):
            # stdlib Thread/lock methods on thread-like receivers must not
            # resolve to same-named program defs (Thread.start vs
            # Router.start) — spawn targets are modeled explicitly
            if fn.attr in ("start", "join", "run", "is_alive", "acquire",
                           "release", "cancel_join_thread") and \
                    _thread_like_recv(mod, fn.value):
                return []
            out: List[FuncInfo] = []
            cands = self.defs_by_name.get(fn.attr, [])
            if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                cls = mod.enclosing_class(fn)
                same_cls = [fi for fi in cands if fi.module is mod
                            and fi.class_name == cls]
                if same_cls:
                    return same_cls
            # receiver typed by construction site (`self.sched =
            # Scheduler(...)` makes `self.sched.X()` resolve only inside
            # Scheduler)
            recv_leaf = None
            if isinstance(fn.value, ast.Attribute):
                recv_leaf = fn.value.attr
            elif isinstance(fn.value, ast.Name) and fn.value.id != "self":
                recv_leaf = fn.value.id
                local = self._local_types(mod, fn.value)
                if local:
                    return [fi for fi in cands if fi.class_name in local]
                # imported-module receiver (``os.kill``, ``time.sleep``,
                # ``reqtrace.get_tracer``): resolve against that module's
                # top-level defs only — never method candidates
                target = mod.aliases.get(fn.value.id)
                if target is not None:
                    mpath = target.lstrip(".").replace(".", "/") + ".py"
                    return [fi for fi in cands
                            if not fi.class_name
                            and fi.module.path.endswith(mpath)]
            if recv_leaf is not None:
                types = self.attr_classes.get(recv_leaf)
                if types:
                    return [fi for fi in cands if fi.class_name in types]
            if fn.attr in _GENERIC_METHODS:
                return []
            out.extend(cands)
            out.extend(self.attr_bindings.get(fn.attr, []))
            return out
        return []

    def _propagate_roots(self) -> None:
        # seed main: public defs, dunders, and module-top-level callees
        for fi in self.functions:
            if fi.name and (not fi.name.startswith("_")
                            or (fi.name.startswith("__")
                                and fi.name.endswith("__"))):
                fi.roots.add("main")
        for mod in self.modules:
            for node in ast.iter_child_nodes(mod.tree):
                for call in ast.walk(node):
                    if isinstance(call, ast.Call) and not isinstance(
                            node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                        for fi in self.resolve_call(mod, call):
                            fi.roots.add("main")
        # private defs nobody spawns and nobody calls are still client
        # entry points (helpers imported elsewhere): give them main
        called: Set[FuncInfo] = set()
        for edges in self.call_edges.values():
            called.update(edges)
        for fi in self.functions:
            if not fi.roots and fi not in called and not fi.spawn_only:
                fi.roots.add("main")
        # fixpoint: roots flow caller -> callee
        changed = True
        while changed:
            changed = False
            for fi, edges in self.call_edges.items():
                for callee in edges:
                    missing = fi.roots - callee.roots
                    if missing:
                        callee.roots |= missing
                        changed = True

    # -- lock facts --------------------------------------------------------
    def resolve_lock(self, mod, expr: ast.AST,
                     fi: Optional[FuncInfo]) -> Optional[LockId]:
        """LockId for a ``with <expr>:`` context (or a wait/acquire
        receiver). Unknown expressions resolve to a declared lock when the
        attribute name is unambiguous across the program."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            cls = mod.enclosing_class(expr)
            if cls:
                lid = LockId("cls", mod.path, cls, expr.attr)
                if lid in self.locks:
                    return lid
                # inherited / mixin attr: fall through to unique-name match
        if isinstance(expr, ast.Name):
            for scope, owner in (("mod", ""),):
                lid = LockId(scope, mod.path, owner, expr.id)
                if lid in self.locks:
                    return lid
            if fi is not None:
                lid = LockId("loc", mod.path, fi.qualname, expr.id)
                if lid in self.locks:
                    return lid
        # unique attribute-name match anywhere in the program
        leaf = expr.attr if isinstance(expr, ast.Attribute) else (
            expr.id if isinstance(expr, ast.Name) else None)
        if leaf:
            matches = [lid for lid in self.locks if lid.name == leaf]
            if len(matches) == 1:
                return matches[0]
        return None

    def lock_kind(self, lid: LockId) -> str:
        return self.locks.get(lid, "?")

    def held_regions(self, fi: FuncInfo) -> Iterator[
            Tuple[ast.AST, FrozenSet[LockId], Optional[LockId]]]:
        """(statement, held locks incl. entry-held, innermost lock) for
        every node in the function body. The with-stack part is static per
        function, so it is computed once and cached; only the entry-held
        union varies (the fixpoint passes re-walk every function)."""
        cache = getattr(self, "_region_cache", None)
        if cache is None:
            cache = self._region_cache = {}
        regions = cache.get(fi)
        if regions is None:
            regions = cache[fi] = list(self._walk_regions(fi))
        entry = fi.entry_held or frozenset()
        if not entry:
            yield from regions
            return
        for node, held, inner in regions:
            yield node, entry | held, inner

    def _walk_regions(self, fi: FuncInfo) -> Iterator[
            Tuple[ast.AST, FrozenSet[LockId], Optional[LockId]]]:
        stack: List[Tuple[ast.AST, Tuple[LockId, ...]]] = \
            [(fi.node, ())]
        while stack:
            node, held = stack.pop()
            if node is not fi.node and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
                continue    # nested scope: analyzed via its own entry-held
            if isinstance(node, ast.With):
                # yield the With itself under the OUTER held set — the
                # order-edge builder reads `inner` here to record direct
                # `with A: with B:` nesting
                yield node, frozenset(held), (held[-1] if held else None)
                new = list(held)
                for item in node.items:
                    lid = self.resolve_lock(fi.module, item.context_expr, fi)
                    if lid is not None:
                        new.append(lid)
                for child in node.body:
                    stack.append((child, tuple(new)))
                for item in node.items:
                    stack.append((item.context_expr, held))
                continue
            yield node, frozenset(held), (held[-1] if held else None)
            for child in ast.iter_child_nodes(node):
                stack.append((child, held))

    def _own_with_locks(self, fi: FuncInfo) -> Set[LockId]:
        out: Set[LockId] = set()
        for node in _own_nodes(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = self.resolve_lock(fi.module, item.context_expr, fi)
                    if lid is not None:
                        out.add(lid)
        return out

    def _compute_entry_held(self) -> None:
        """entry_held(f) = intersection over call sites of the locks held
        there. Only true entry points — spawn/signal/executor targets and
        functions with NO in-program callers — are seeded with the empty
        set; a public method whose every call site holds the engine lock
        is (for gating purposes) guarded by it, which is exactly the
        layered engine->scheduler->allocator design this tree uses."""
        called: Set[FuncInfo] = set()
        for edges in self.call_edges.values():
            called.update(edges)
        for fi in self.functions:
            if fi.spawn_only or fi not in called:
                fi.entry_held = frozenset()
        for _ in range(len(self.functions)):
            changed = False
            for caller in self.functions:
                entry = caller.entry_held
                if entry is None:
                    continue
                for node, held, _inner in self.held_regions(caller):
                    if not isinstance(node, ast.Call):
                        continue
                    for callee in self.resolve_call(caller.module, node):
                        new = frozenset(held)
                        cur = callee.entry_held
                        nxt = new if cur is None else (cur & new)
                        if nxt != cur:
                            callee.entry_held = nxt
                            changed = True
            if not changed:
                break
        for fi in self.functions:
            if fi.entry_held is None:
                fi.entry_held = frozenset()

    def _compute_acquires(self) -> None:
        self.acquires: Dict[FuncInfo, Set[LockId]] = {
            fi: self._own_with_locks(fi) for fi in self.functions}
        changed = True
        while changed:
            changed = False
            for fi, edges in self.call_edges.items():
                for callee in edges:
                    extra = self.acquires[callee] - self.acquires[fi]
                    if extra:
                        self.acquires[fi] |= extra
                        changed = True

    def _build_order_edges(self) -> None:
        for fi in self.functions:
            for node, held, inner in self.held_regions(fi):
                if inner is None:
                    continue
                if isinstance(node, ast.With):
                    continue
                if isinstance(node, ast.Call):
                    for callee in self.resolve_call(fi.module, node):
                        for lid in self.acquires[callee]:
                            if lid not in held:
                                self.order_edges.setdefault(
                                    (inner, lid),
                                    (fi.module.path, node.lineno,
                                     callee.qualname))
                            elif lid == inner:
                                # re-acquisition of the held lock via a call
                                self.order_edges.setdefault(
                                    (inner, inner),
                                    (fi.module.path, node.lineno,
                                     callee.qualname))
            # direct nesting: with A: ... with B:
            for node, held, inner in self.held_regions(fi):
                if isinstance(node, ast.With) and inner is not None:
                    for item in node.items:
                        lid = self.resolve_lock(fi.module, item.context_expr,
                                                fi)
                        if lid is not None and lid != inner:
                            self.order_edges.setdefault(
                                (inner, lid),
                                (fi.module.path, node.lineno, "with"))

    def lock_cycles(self) -> List[List[Tuple[LockId, LockId]]]:
        """Elementary cycles in the lock-order graph. Self-edges count only
        for non-reentrant kinds. Deduplicated by node set."""
        graph: Dict[LockId, Set[LockId]] = {}
        for (a, b) in self.order_edges:
            if a == b:
                continue
            graph.setdefault(a, set()).add(b)
        cycles: List[List[Tuple[LockId, LockId]]] = []
        seen: Set[FrozenSet[LockId]] = set()
        for (a, b) in sorted(self.order_edges,
                             key=lambda e: (e[0].key, e[1].key)):
            if a == b:
                if self.lock_kind(a) in _NONREENTRANT or \
                        self.lock_kind(a) == "?":
                    if frozenset((a,)) not in seen:
                        seen.add(frozenset((a,)))
                        cycles.append([(a, a)])
                continue
            path = self._find_path(graph, b, a)
            if path is not None:
                nodes = frozenset([a] + path)
                if nodes not in seen:
                    seen.add(nodes)
                    edges = [(a, b)]
                    cur = b
                    for nxt in path[1:]:
                        edges.append((cur, nxt))
                        cur = nxt
                    cycles.append(edges)
        return cycles

    @staticmethod
    def _find_path(graph: Dict[LockId, Set[LockId]], src: LockId,
                   dst: LockId) -> Optional[List[LockId]]:
        stack = [(src, [src])]
        visited = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in sorted(graph.get(node, ()), key=lambda l: l.key):
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- census (the report CLI + metrics read these) ----------------------
    def root_census(self) -> Dict[str, int]:
        """root label -> number of functions reachable on it."""
        out: Dict[str, int] = {}
        for fi in self.functions:
            for r in fi.roots:
                out[r] = out.get(r, 0) + 1
        return out


def _walk_defs(tree: ast.Module) -> Iterator[Tuple[FunctionNode,
                                                   Optional[str]]]:
    """(def node, enclosing class name) for every function/lambda."""
    stack: List[Tuple[ast.AST, Optional[str]]] = [(tree, None)]
    while stack:
        node, cls = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                stack.append((child, child.name))
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                yield child, cls
                stack.append((child, cls))
            else:
                stack.append((child, cls))


def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs/lambdas."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _ann_leaf(mod, ann: Optional[ast.AST]) -> Optional[str]:
    """Class-name leaf of a type annotation (handles string annotations)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.rpartition(".")[2].strip("'\" ")
    return (mod.dotted(ann) or "").rpartition(".")[2] or None


def _param_type(mod, fn: ast.AST, name: str) -> Optional[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return None
    for a in list(args.args) + list(args.kwonlyargs):
        if a.arg == name:
            return _ann_leaf(mod, a.annotation)
    return None


def _thread_like_recv(mod, recv: ast.AST) -> bool:
    """Receiver spelled like a Thread/lock handle (``self._thread``, a
    local assigned from ``threading.Thread``)."""
    text = mod.dotted(recv) or ""
    leaf = text.rpartition(".")[2].lower()
    if "thread" in leaf or "lock" in leaf or "_cond" in leaf or \
            leaf in ("_t", "watchdog_t", "timer"):
        return True
    fn = mod.enclosing_function(recv)
    if fn is None or "." in text:
        return False
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and (mod.dotted(n.value.func) or "") in _THREAD_CTORS:
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == text:
                    return True
    return False


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None
