"""tpusync rules — the five concurrency checks.

Each rule sees the whole :class:`~tools.tpusync.threadgraph.Program` and
yields Findings whose messages always name the **function**, the **lock**
(held, missing, or cycling) and the **thread roots** involved — a finding
you cannot act on without re-deriving the interleaving is a finding that
gets baselined instead of fixed.

False-positive posture: every heuristic here errs conservative (flag), and
the escape hatch is an inline ``# tpusync: disable=<rule>`` with a comment
saying *why* the pattern is safe — the suppression then documents the
invariant the type system can't."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import Finding, Rule, register
from .threadgraph import FuncInfo, LockId, Program, _NONREENTRANT

_MUTATORS = {"append", "extend", "add", "remove", "discard", "pop",
             "popleft", "appendleft", "clear", "update", "insert",
             "setdefault", "rotate"}
_INIT_FUNCS = {"__init__", "__post_init__", "__new__"}
_BLOCKING_DOTTED_PREFIX = ("shutil.", "subprocess.")
_BLOCKING_DOTTED = {"time.sleep", "os.makedirs", "os.replace", "os.rename",
                    "os.remove", "os.fsync", "jax.block_until_ready"}
_CALLBACK_SUFFIX = ("_callback", "_hook")


def _roots_str(roots: Set[str]) -> str:
    return ", ".join(sorted(roots)) or "∅"


def _held_str(held) -> str:
    return ", ".join(sorted(l.display for l in held)) or "nothing"


@register
class UnguardedSharedWrite(Rule):
    name = "unguarded-shared-write"
    description = ("attribute written from ≥2 thread roots with no common "
                   "lock (or without its declared guarded-by lock)")

    def check(self, program: Program) -> Iterator[Finding]:
        # (module path, owner class, attr) -> write sites
        writes: Dict[Tuple[str, str, str],
                     List[Tuple[FuncInfo, int, int, frozenset]]] = {}
        for fi in program.functions:
            in_init = fi.name in _INIT_FUNCS
            globs = _global_decls(fi.node)
            for node, held, _ in program.held_regions(fi):
                for owner, attr, line, col in _write_targets(
                        fi, node, globs):
                    if in_init and owner:    # construction happens-before
                        continue
                    # a suppressed site leaves the race set entirely —
                    # the remaining sites are judged on their own
                    if fi.module.suppressed(self.name, line):
                        continue
                    key = (fi.module.path, owner, attr)
                    writes.setdefault(key, []).append(
                        (fi, line, col, held))
        for (path, owner, attr), sites in sorted(writes.items()):
            mod = next(m for m in program.modules if m.path == path)
            display = f"{owner}.{attr}" if owner else attr
            guard = mod.guarded_by.get((owner, attr))
            if guard is not None:
                required = _resolve_guard(program, mod, owner, guard)
                for fi, line, col, held in sites:
                    if required is not None and required in held:
                        continue
                    yield Finding(
                        self.name, path, line, col,
                        f"write to '{display}' in {fi.qualname} (roots: "
                        f"{_roots_str(fi.roots)}) without its declared "
                        f"guard '{guard}' (# tpusync: guarded-by); holds: "
                        f"{_held_str(held)}")
                continue
            roots: Set[str] = set()
            for fi, _, _, _ in sites:
                roots |= fi.roots
            if len(roots) < 2:
                continue
            common = None
            for _, _, _, held in sites:
                common = held if common is None else (common & held)
            if common:
                continue
            # anchor on the least-guarded, earliest site; list the rest
            anchor = min(sites, key=lambda s: (len(s[3]), s[1]))
            detail = "; ".join(
                f"{fi.qualname} ({p}:{ln}, roots: {_roots_str(fi.roots)}, "
                f"holds: {_held_str(held)})"
                for fi, ln, _, held in sites[:4]
                for p in (fi.module.path,))
            if len(sites) > 4:
                detail += f"; +{len(sites) - 4} more"
            candidates = sorted(
                l.display for l in program.locks
                if l.scope == "cls" and l.owner == owner
                and l.module == path) if owner else []
            hint = (f"; candidate guard(s): {', '.join(candidates)}"
                    if candidates else "")
            yield Finding(
                self.name, path, anchor[1], anchor[2],
                f"shared attribute '{display}' written from "
                f"{len(roots)} roots ({_roots_str(roots)}) with no common "
                f"lock — sites: {detail}{hint}")


@register
class LockOrderInversion(Rule):
    name = "lock-order-inversion"
    description = ("cycle in the whole-program lock-acquisition graph "
                   "(potential deadlock), incl. non-reentrant re-acquire")

    def check(self, program: Program) -> Iterator[Finding]:
        for cycle in program.lock_cycles():
            a, b = cycle[0]
            path, line, via = program.order_edges[(a, b)]
            if a == b:
                kind = program.lock_kind(a)
                yield Finding(
                    self.name, path, line, 0,
                    f"non-reentrant {kind} '{a.display}' may be "
                    f"re-acquired while already held (via {via} at "
                    f"{path}:{line}) — self-deadlock on the same thread")
                continue
            hops = []
            for (x, y) in cycle:
                p, ln, v = program.order_edges.get((x, y), (path, line, via))
                hops.append(f"{x.display} -> {y.display} "
                            f"({p}:{ln} via {v})")
            yield Finding(
                self.name, path, line, 0,
                f"lock-order cycle: {'; '.join(hops)} — two threads "
                f"taking these locks in opposite order deadlock")


@register
class BlockingUnderLock(Rule):
    name = "blocking-under-lock"
    description = ("sleep / join / block_until_ready / file IO / unbounded "
                   "queue.get while holding a lock")

    def check(self, program: Program) -> Iterator[Finding]:
        for fi in program.functions:
            for node, held, _ in program.held_regions(fi):
                if not held or not isinstance(node, ast.Call):
                    continue
                what = self._blocking_kind(program, fi, node, held)
                if what is None:
                    continue
                yield Finding(
                    self.name, fi.module.path, node.lineno, node.col_offset,
                    f"{what} in {fi.qualname} (roots: "
                    f"{_roots_str(fi.roots)}) while holding "
                    f"{_held_str(held)} — every thread contending for the "
                    f"lock stalls behind it")

    def _blocking_kind(self, program: Program, fi: FuncInfo,
                       node: ast.Call, held) -> Optional[str]:
        mod = fi.module
        dotted = mod.dotted(node.func) or ""
        if dotted in _BLOCKING_DOTTED or \
                dotted.startswith(_BLOCKING_DOTTED_PREFIX):
            return f"blocking call {dotted}()"
        if dotted == "open":
            return "file IO open()"
        if not isinstance(node.func, ast.Attribute):
            return None
        leaf = node.func.attr
        if leaf == "block_until_ready":
            return "device sync .block_until_ready()"
        if leaf == "join" and _thread_like(mod, node.func.value, fi):
            return "thread .join()"
        if leaf == "get" and _unbounded_get(node):
            return "unbounded queue .get()"
        if leaf == "wait" and not _has_timeout(node):
            recv = program.resolve_lock(mod, node.func.value, fi)
            others = set(held) - ({recv} if recv is not None else set())
            if recv is not None and not others:
                return None        # with cond: cond.wait() — the idiom
            if others:
                return (f"unbounded .wait() while also holding "
                        f"{_held_str(others)}")
            return "unbounded .wait()"
        return None


@register
class SignalUnsafeHandler(Rule):
    name = "signal-unsafe-handler"
    description = ("signal handler (or its call closure) acquiring "
                   "non-reentrant locks or doing IO/allocation")

    def check(self, program: Program) -> Iterator[Finding]:
        handlers: Dict[FuncInfo, str] = {}
        for label, fi, _site in program.spawns:
            if label.startswith("signal:"):
                handlers.setdefault(fi, label)
        for fi in program.functions:
            label = fi.module.thread_root_annotations.get(fi.node.lineno)
            if label and label.startswith("signal:"):
                handlers.setdefault(fi, label)
        for handler, label in sorted(handlers.items(),
                                     key=lambda kv: (kv[0].module.path,
                                                     kv[0].line)):
            closure = _call_closure(program, handler)
            lock_hits: List[str] = []
            io_hits: List[str] = []
            for g in closure:
                for lid in sorted(program._own_with_locks(g),
                                  key=lambda l: l.key):
                    kind = program.lock_kind(lid)
                    if kind in _NONREENTRANT or kind == "Semaphore":
                        lock_hits.append(
                            f"{kind} '{lid.display}' in {g.qualname} "
                            f"({g.module.path}:{g.line})")
                for node in _own_calls(g):
                    why = _alloc_io_kind(g.module, node)
                    if why is not None:
                        io_hits.append(f"{why} in {g.qualname} "
                                       f"({g.module.path}:{node.lineno})")
            path, line = handler.module.path, handler.line
            fn = handler.qualname
            for hit in lock_hits:
                yield Finding(
                    self.name, path, line, 0,
                    f"signal handler {fn} ({label}) reaches {hit} — if the "
                    f"interrupted main-thread frame already holds it, the "
                    f"handler deadlocks")
            if io_hits:
                sample = "; ".join(io_hits[:3])
                more = f"; +{len(io_hits) - 3} more" if len(io_hits) > 3 \
                    else ""
                yield Finding(
                    self.name, path, line, 0,
                    f"signal handler {fn} ({label}) allocates/does IO "
                    f"({sample}{more}) — handlers run atop an arbitrary "
                    f"interrupted frame; keep them to flag-sets and "
                    f"reentrant state")


@register
class CallbackUnderLock(Rule):
    name = "callback-under-lock"
    description = ("user/exporter callback invoked while holding an "
                   "internal lock")

    def check(self, program: Program) -> Iterator[Finding]:
        for fi in program.functions:
            for node, held, _ in program.held_regions(fi):
                if not held or not isinstance(node, ast.Call):
                    continue
                leaf = None
                if isinstance(node.func, ast.Attribute):
                    leaf = node.func.attr
                elif isinstance(node.func, ast.Name):
                    leaf = node.func.id
                if leaf is None or not _callback_name(leaf):
                    continue
                yield Finding(
                    self.name, fi.module.path, node.lineno,
                    node.col_offset,
                    f"callback '{leaf}' invoked in {fi.qualname} (roots: "
                    f"{_roots_str(fi.roots)}) while holding "
                    f"{_held_str(held)} — foreign code under an internal "
                    f"lock can re-enter or block it")


# -- shared helpers --------------------------------------------------------
def _callback_name(leaf: str) -> bool:
    return (leaf.startswith("on_") or leaf.endswith(_CALLBACK_SUFFIX)
            or leaf == "write_events")


def _global_decls(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _write_targets(fi: FuncInfo, node: ast.AST, globs: Set[str]
                   ) -> Iterator[Tuple[str, str, int, int]]:
    """(owner class or "", attr, line, col) for mutations in this stmt."""
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self" and fi.class_name:
                yield fi.class_name, tgt.attr, tgt.lineno, tgt.col_offset
            elif isinstance(tgt, ast.Name) and tgt.id in globs:
                yield "", tgt.id, tgt.lineno, tgt.col_offset
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in _MUTATORS:
        recv = node.func.value
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and fi.class_name:
            yield fi.class_name, recv.attr, node.lineno, node.col_offset
        elif isinstance(recv, ast.Name) and recv.id in globs:
            yield "", recv.id, node.lineno, node.col_offset


def _resolve_guard(program: Program, mod, owner: str,
                   guard: str) -> Optional[LockId]:
    name = guard.rpartition(".")[2]
    lid = LockId("cls", mod.path, owner, name)
    if lid in program.locks:
        return lid
    lid = LockId("mod", mod.path, "", name)
    if lid in program.locks:
        return lid
    matches = [l for l in program.locks if l.name == name]
    return matches[0] if len(matches) == 1 else None


def _thread_like(mod, recv: ast.AST, fi: FuncInfo) -> bool:
    text = mod.dotted(recv) or ""
    leaf = text.rpartition(".")[2].lower()
    if "thread" in leaf or leaf in ("_t", "worker", "_drain"):
        return True
    fn = fi.node
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and (mod.dotted(n.value.func) or "") in (
                    "threading.Thread", "Thread"):
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == text:
                    return True
    return False


def _has_timeout(node: ast.Call) -> bool:
    if any(kw.arg == "timeout" for kw in node.keywords):
        return True
    return len(node.args) >= 1 and not (
        isinstance(node.args[0], ast.Constant)
        and node.args[0].value is True)


def _unbounded_get(node: ast.Call) -> bool:
    """queue.get() with blocking semantics and no timeout. Zero-argument
    ``.get()`` is unambiguous (dict.get needs a key); ``get(True)`` /
    ``get(block=True)`` without a timeout also counts."""
    if any(kw.arg == "timeout" for kw in node.keywords):
        return False
    if not node.args and not node.keywords:
        return True
    if node.args and isinstance(node.args[0], ast.Constant) and \
            node.args[0].value is True and len(node.args) == 1:
        return True
    return any(kw.arg == "block" and isinstance(kw.value, ast.Constant)
               and kw.value.value is True for kw in node.keywords)


def _call_closure(program: Program, start: FuncInfo) -> List[FuncInfo]:
    seen = {start}
    order = [start]
    frontier = [start]
    while frontier:
        cur = frontier.pop()
        for callee in program.call_edges.get(cur, ()):
            if callee not in seen:
                seen.add(callee)
                order.append(callee)
                frontier.append(callee)
    return order


def _own_calls(fi: FuncInfo) -> Iterator[ast.Call]:
    from .threadgraph import _own_nodes
    for node in _own_nodes(fi.node):
        if isinstance(node, ast.Call):
            yield node


def _alloc_io_kind(mod, node: ast.Call) -> Optional[str]:
    dotted = mod.dotted(node.func) or ""
    if dotted == "open":
        return "open()"
    if dotted.startswith(("os.makedirs", "os.replace", "os.rename",
                          "shutil.")):
        return f"{dotted}()"
    if dotted == "print":
        return "print()"
    if dotted.startswith("logging.") or \
            (isinstance(node.func, ast.Attribute)
             and (mod.dotted(node.func.value) or "").rpartition(".")[2]
             in ("logger", "log")):
        return f"logging call {dotted or node.func.attr}()"
    if dotted in ("threading.Thread", "Thread"):
        return "thread spawn"
    return None
