"""tpusync CLI — the host-concurrency gate.

Usage::

    # gate run (what scripts/sync.sh does): default scope vs the committed
    # baseline
    python -m tools.tpusync --baseline .tpusync-baseline.json

    python -m tools.tpusync deepspeed_tpu/serving --format json
    python -m tools.tpusync --baseline b.json --write-baseline
    python -m tools.tpusync --baseline b.json --prune-baseline

Same gate semantics as the other four analyzers (shared driver in
``tools/tpulint/baseline.py``): exit 0 clean or fully baselined, 1 new
findings or stale baseline entries, 2 usage error. ``--baseline`` defaults
to the committed ``.tpusync-baseline.json`` when it exists, so the bare
command is the gate.

Every run publishes ``tpusync/*`` metrics (findings by rule, per-root
function census, lock-graph size) into the process MetricsRegistry;
``--metrics-jsonl`` dumps them for the ``report`` CLI's ``== sync ==``
section.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from tools.tpulint import baseline as baseline_mod
from tools.tpulint.core import iter_python_files

from .core import (DEFAULT_SCOPE, RULES, SyncModule, analyze_paths,
                   build_program)

DEFAULT_BASELINE = ".tpusync-baseline.json"


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusync",
        description="Host-concurrency static analysis: thread-root "
                    "reachability, guarded-by discipline, lock-order "
                    "cycles, blocking/callbacks under locks, signal-handler "
                    "safety.")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories to analyze (default: the "
                             "host orchestration scope — serving/, "
                             "observability/, launcher/, runtime "
                             "session+checkpoint)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", metavar="FILE", default=None,
                        help=f"JSON baseline of accepted findings (default: "
                             f"{DEFAULT_BASELINE} when it exists)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write the current findings to --baseline and "
                             "exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop stale baseline entries and ratchet "
                             "budgets down to current counts, then exit 0")
    parser.add_argument("--select", metavar="RULES", default=None,
                        help="comma-separated rule names to run "
                             "(default: all)")
    parser.add_argument("--root", metavar="DIR", default=None,
                        help="directory finding paths are made relative to "
                             "(default: cwd)")
    parser.add_argument("--metrics-jsonl", metavar="FILE", default=None,
                        help="also dump the tpusync/* metrics to a JSONL "
                             "(readable by 'observability report')")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    return parser


def publish_metrics(program, findings) -> None:
    """tpusync/* metrics into the process registry. Import-guarded: the
    analyzer must run in a container with nothing but the stdlib."""
    try:
        from deepspeed_tpu.observability import get_registry
    except ImportError:
        return
    reg = get_registry()
    counter = reg.counter("tpusync/findings",
                          "concurrency findings by rule")
    for f in findings:
        counter.inc(1, rule=f.rule)
    reg.gauge("tpusync/functions_total",
              "functions in the thread-root graph").set(
        len(program.functions))
    root_gauge = reg.gauge("tpusync/root_functions",
                           "functions reachable per thread root")
    for root, n in sorted(program.root_census().items()):
        root_gauge.set(n, root=root)
    reg.gauge("tpusync/lock_graph_locks",
              "declared locks in the whole-program model").set(
        len(program.locks))
    reg.gauge("tpusync/lock_graph_edges",
              "lock-order edges (A held while acquiring B)").set(
        len(program.order_edges))


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from . import rules as _rules  # noqa: F401

        for rule in RULES:
            print(f"{rule.name}: {rule.description}")
        return 0

    select = None
    if args.select:
        from . import rules as _rules  # noqa: F401

        select = {r.strip() for r in args.select.split(",") if r.strip()}
        known = {r.name for r in RULES}
        unknown = select - known
        if unknown:
            print(f"tpusync: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    paths = args.paths or [p for p in DEFAULT_SCOPE if os.path.exists(p)]
    missing = [p for p in (args.paths or []) if not os.path.exists(p)]
    if missing:
        print(f"tpusync: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    if not paths:
        print("tpusync: nothing to analyze", file=sys.stderr)
        return 2

    findings = analyze_paths(paths, root=args.root, select=select)

    # the census/metrics view wants the model, not just the diagnostics
    root = args.root or os.getcwd()
    modules = []
    for fpath in iter_python_files(paths):
        rel = os.path.relpath(fpath, root).replace(os.sep, "/")
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                modules.append(SyncModule(rel, fh.read()))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
    program = build_program(modules)
    publish_metrics(program, findings)

    if args.metrics_jsonl:
        from deepspeed_tpu.observability import get_registry

        get_registry().dump_jsonl(args.metrics_jsonl,
                                  extra={"tool": "tpusync"})

    baseline_path = args.baseline
    if baseline_path is None and not (args.write_baseline
                                      or args.prune_baseline):
        if os.path.exists(DEFAULT_BASELINE):
            baseline_path = DEFAULT_BASELINE

    # Stale detection judges only keys this run could have produced (same
    # contract as tpulint): files under analyzed dirs count even when
    # deleted — a removed module is the most common source of rot.
    analyzed = {os.path.relpath(p, root).replace(os.sep, "/")
                for p in iter_python_files(paths)}
    dir_prefixes: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            rel = os.path.relpath(p, root).replace(os.sep, "/")
            dir_prefixes.append("" if rel == "." else rel.rstrip("/") + "/")

    def in_scope(key: str) -> bool:
        path, _, rule = key.rpartition("::")
        if select is not None and rule not in select:
            return False
        return path in analyzed or any(path.startswith(pref)
                                       for pref in dir_prefixes)

    return baseline_mod.gate_and_report(
        findings, tool="tpusync", fmt=args.format,
        baseline_path=baseline_path, write_baseline=args.write_baseline,
        prune_baseline=args.prune_baseline, in_scope=in_scope)


if __name__ == "__main__":
    sys.exit(main())
