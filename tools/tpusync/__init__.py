"""tpusync — host-concurrency static analysis (the fifth gate).

The quartet of static gates reasons about *programs* (tpulint: source,
tpuaudit: semantics, tpucost: cost, tpushard: layout); tpusync reasons about
*threads*: which functions run on which thread roots (main, spawned driver
threads, signal handlers, executor submits), which locks guard which shared
attributes, and where the hand-rolled host orchestration — the code
DeepSpeed delegates to torch.distributed's battle-tested plumbing — can
race or deadlock.

See ``docs/tpusync.md`` for the annotation vocabulary and rule semantics.
"""

from .core import (Finding, RULES, SyncModule, analyze_paths, analyze_source,
                   build_program)

__all__ = ["Finding", "RULES", "SyncModule", "analyze_paths",
           "analyze_source", "build_program"]
