"""tpucost baseline — committed cost vectors with per-metric tolerance bands.

Where tpulint/tpuaudit budget finding COUNTS, tpucost budgets metric VALUES:
the committed ``.tpucost-baseline.json`` records each entry's cost vector,
and the gate compares the current vector against it per metric:

* **over the band** (``current > baseline * (1 + tol)``) → a regression
  finding naming the entry, the metric, the delta — and, when the entry was
  compiled both times, the HLO op classes whose counts grew (the "what got
  fatter" attribution);
* **under the band** → the same stale-rot semantics as the other two
  analyzers: the improvement passed, but the lingering budget would silently
  re-admit a regression up to the old value, so the gate ERRORS until
  ``--prune-baseline`` ratchets it down;
* **within the band** → clean.

Tolerances are per metric: deterministic compiler outputs (flops, argument
bytes, collective payload) gate exactly; layout/fusion-sensitive ones (peak
HBM ±2%) and text-shaped ones (op counts, program size ±10%, which drift
with unrelated source-location metadata) get bands; ``ENTRY_TOLERANCES``
widens individual (entry, metric) cells whose host-compile measurement is
box-dependent. The report/exit tail is
``tools.tpulint.baseline.render_report`` — shared by all the analyzers.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..tpulint.baseline import BASELINE_VERSION

# relative tolerance per gated metric; metrics absent here ride along in the
# vector (report/diff display) but do not gate
TOLERANCES: Dict[str, float] = {
    "flops": 0.0,
    "transcendentals": 0.0,
    "bytes_accessed": 0.02,
    "collective_bytes": 0.0,
    "peak_hbm_bytes": 0.02,
    "temp_hbm_bytes": 0.02,
    "argument_hbm_bytes": 0.0,
    "output_hbm_bytes": 0.0,
    "jaxpr_eqns": 0.10,
    "hlo_op_count": 0.10,
    "program_bytes": 0.10,
}

# per-(entry, metric) band overrides, consulted before TOLERANCES. The XLA
# CPU backend sizes temp/scratch allocations from the HOST's concurrency
# (its intra-op thread pool scales with core count), so a program's
# temp_hbm_bytes — and with it peak_hbm_bytes — is stable on any one box
# but drifts several percent BETWEEN boxes of different core counts (a
# 1-core runner reproducibly measures prefill ~5-6% over the multi-core
# baseline). The drift is a host-compile artifact, not a program
# regression: real-TPU memory analysis has no host thread pool in it.
ENTRY_TOLERANCES: Dict[Tuple[str, str], float] = {
    ("inference/prefill", "peak_hbm_bytes"): 0.08,
    ("inference/prefill", "temp_hbm_bytes"): 0.08,
}


def tolerance(entry: str, metric: str) -> float:
    return ENTRY_TOLERANCES.get((entry, metric), TOLERANCES[metric])


_EPS = 1e-9


@dataclasses.dataclass(frozen=True)
class CostFinding:
    """One gate diagnostic; ``key`` (entry::metric) mirrors the other
    analyzers' baseline buckets."""

    entry: str
    metric: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.entry}::{self.metric}"

    def render(self) -> str:
        return f"{self.entry}: {self.metric}: {self.message}"

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return f"{int(v):,}"
    return f"{v:.6g}"


def _delta_pct(base: float, cur: float) -> str:
    if base == 0:
        return "+inf%"
    return f"{(cur - base) / base:+.2%}"


def grown_op_classes(base_ops: Dict[str, int], cur_ops: Dict[str, int],
                     top: int = 4) -> List[Tuple[str, int]]:
    """HLO op classes whose counts grew, largest growth first — the
    attribution attached to a regression finding."""
    deltas = [(op, cur_ops.get(op, 0) - base_ops.get(op, 0))
              for op in set(base_ops) | set(cur_ops)]
    grown = [(op, d) for op, d in deltas if d > 0]
    grown.sort(key=lambda t: (-t[1], t[0]))
    return grown[:top]


def entry_record(vector) -> Dict[str, Any]:
    """What the baseline stores per entry."""
    return {"metrics": {k: float(v) for k, v in sorted(vector.metrics.items())
                        if k in TOLERANCES},
            "hlo_ops": dict(vector.hlo_ops),
            "collective_bytes_by_axis": dict(
                vector.collectives.get("by_axis", {})),
            "program_hash": vector.program_hash}


def load(path: str) -> Dict[str, Dict[str, Any]]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{data.get('version')!r}")
    return data.get("entries", {})


def write(path: str, entries: Dict[str, Dict[str, Any]]) -> None:
    payload = {"version": BASELINE_VERSION, "tool": "tpucost",
               "entries": {k: entries[k] for k in sorted(entries)}}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def records_of(vectors: Sequence) -> Dict[str, Dict[str, Any]]:
    return {v.entry: entry_record(v) for v in vectors}


def compare(vectors: Sequence, baseline: Dict[str, Dict[str, Any]],
            errors: Optional[Dict[str, str]] = None,
            in_scope=None) -> Tuple[List[CostFinding], List[str]]:
    """Current vectors vs baseline → (regression findings, stale keys).
    ``errors`` (entry → trace/compile failure) gate unconditionally: a
    program that stopped building host-side is a regression, not a skip.
    ``in_scope`` limits staleness to keys this run could have produced
    (partial --entries runs must not condemn what they never measured)."""
    findings: List[CostFinding] = []
    stale: List[str] = []
    current = {v.entry: v for v in vectors}

    for name, msg in sorted((errors or {}).items()):
        findings.append(CostFinding(name, "trace-error",
                                    f"entry failed to trace/compile "
                                    f"host-side: {msg}"))

    for v in vectors:
        base = baseline.get(v.entry)
        if base is None:
            findings.append(CostFinding(
                v.entry, "unbaselined",
                "entry has no committed cost vector — review the "
                "== cost == numbers and run --write-baseline"))
            continue
        base_metrics = base.get("metrics", {})
        for metric in TOLERANCES:
            tol = tolerance(v.entry, metric)
            cur = v.metrics.get(metric)
            key = f"{v.entry}::{metric}"
            if cur is None:
                if metric in base_metrics and (in_scope is None
                                               or in_scope(key)):
                    stale.append(key)   # e.g. compiled -> uncompiled entry
                continue
            if metric not in base_metrics:
                findings.append(CostFinding(
                    v.entry, metric,
                    f"metric is not in the baseline (current "
                    f"{_fmt(cur)}) — run --write-baseline"))
                continue
            b = float(base_metrics[metric])
            if cur > b * (1 + tol) + _EPS:
                attribution = ""
                grown = grown_op_classes(base.get("hlo_ops", {}), v.hlo_ops)
                if grown and v.hlo_ops:
                    attribution = ("; grown HLO op classes: " + ", ".join(
                        f"{op} +{d}" for op, d in grown))
                band = f" (band ±{tol:.0%})" if tol else ""
                findings.append(CostFinding(
                    v.entry, metric,
                    f"{_fmt(b)} -> {_fmt(cur)} "
                    f"({_delta_pct(b, cur)}){band}{attribution}"))
            elif cur < b * (1 - tol) - _EPS and (in_scope is None
                                                 or in_scope(key)):
                stale.append(key)

    for name, base in baseline.items():
        if name in current or name in (errors or {}):
            continue
        for metric in base.get("metrics", {}):
            key = f"{name}::{metric}"
            if in_scope is None or in_scope(key):
                stale.append(key)
    return findings, sorted(stale)


def pruned(vectors: Sequence, baseline: Dict[str, Dict[str, Any]],
           in_scope=None) -> Dict[str, Dict[str, Any]]:
    """Baseline with vanished entries/metrics dropped and surviving values
    ratcheted DOWN to current (never up — a regression still fails after a
    prune, exactly like the count-baseline semantics). Out-of-scope entries
    pass through untouched; the CLI refuses to prune at all while entries
    fail to build."""
    current = {v.entry: v for v in vectors}
    out: Dict[str, Dict[str, Any]] = {}
    for name, base in baseline.items():
        v = current.get(name)
        if v is None:
            # vanished entry: drop its in-scope metrics, keep the rest
            kept = {m: b for m, b in base.get("metrics", {}).items()
                    if in_scope is not None
                    and not in_scope(f"{name}::{m}")}
            if kept:
                rec = dict(base)
                rec["metrics"] = kept
                out[name] = rec
            continue
        new_metrics: Dict[str, float] = {}
        regressed = False
        for metric, b in base.get("metrics", {}).items():
            cur = v.metrics.get(metric)
            key = f"{name}::{metric}"
            if in_scope is not None and not in_scope(key):
                new_metrics[metric] = float(b)
                continue
            if cur is None:
                continue                        # metric vanished: drop
            new_metrics[metric] = min(float(b), float(cur))
            if float(cur) > float(b):
                regressed = True
        rec = entry_record(v)
        rec["metrics"] = new_metrics
        if regressed:
            # the metrics kept an old (lower) budget — keep the op census
            # they describe so regression attribution stays coherent
            rec["hlo_ops"] = base.get("hlo_ops", rec["hlo_ops"])
            rec["program_hash"] = base.get("program_hash",
                                           rec["program_hash"])
        out[name] = rec
    return out
