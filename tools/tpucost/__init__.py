"""tpucost — static program-cost analyzer and CI perf-regression gate.

The third analyzer in the lint/audit/cost/shard/sync quintet. tpulint reads SOURCE,
tpuaudit reads the PROGRAM's semantics (collectives, donation, dtypes);
tpucost reads the program's COST: it AOT-compiles every entry in the
tpuaudit registry host-side and extracts XLA's own cost and memory analysis
— flops, bytes accessed, peak/temp/argument HBM, collective payload bytes
per mesh axis, op counts, program size — then derives an analytic roofline
bound (predicted step time, MFU ceiling). Gated in CI against a committed
``.tpucost-baseline.json`` with per-metric tolerance bands, so a program
that silently got fatter (a dropped donation, an undeclared reshard, a
dtype widening) fails the PR with the chip tunnel down, and the autotuner
gets a measured cost vector instead of its static tables.
"""

from .baseline import TOLERANCES, CostFinding
from .core import (CostVector, cost_entry, publish_vectors,
                   registry_cost_vector, run_cost)
from .extract import (collective_census, cost_analysis_dict, hlo_op_census,
                      memory_analysis_dict, program_hash)
from .roofline import RooflineBound, roofline

__all__ = [
    "TOLERANCES", "CostFinding", "CostVector", "cost_entry",
    "publish_vectors", "registry_cost_vector", "run_cost",
    "collective_census", "cost_analysis_dict", "hlo_op_census",
    "memory_analysis_dict", "program_hash", "RooflineBound", "roofline",
]
