"""Analytic roofline bound over a static cost vector.

Given what XLA says one program execution costs — flops, HBM bytes touched,
collective payload bytes — the fastest the chip could possibly run it is the
slowest of the three pipes, assuming perfect overlap of the other two::

    t_bound = max(flops / peak_flops,  bytes / hbm_bw,  coll_bytes / ici_bw)

``mfu_ceiling = t_compute / t_bound`` is then the hard upper bound on MFU for
this program on this chip generation: a bandwidth-bound program cannot reach
it regardless of kernel quality, so a *drop* in the ceiling is a program-
level perf regression visible with zero TPU time. Platform constants come
from the autotuner's cost model (``cost_model.peak_flops_for`` /
``hbm_bw_for`` / ``ICI_BW``) so the static gate, the bench MFU math and the
tuner all share one denominator. On CPU runs the device kind is unknown and
the v5e-class defaults apply — deliberately: the ceiling is a property of
the PROGRAM, reported against a real chip's pipes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class RooflineBound:
    predicted_step_s: float      # lower bound on one program execution
    mfu_ceiling: float           # hard MFU upper bound (0..1)
    bound: str                   # "compute" | "hbm" | "ici" — the slow pipe
    peak_flops: float
    hbm_bw: float
    ici_bw: float
    predicted_tokens_per_sec: Optional[float] = None  # when tokens/step known


def roofline(flops: float, bytes_accessed: float, collective_bytes: float,
             device_kind: Optional[str] = None,
             tokens_per_step: Optional[float] = None,
             ici_bw: Optional[float] = None) -> RooflineBound:
    # imported lazily: pulling in deepspeed_tpu initializes jax, and the CLI
    # must set the virtual-device XLA flags first
    from deepspeed_tpu.autotuning.cost_model import (ICI_BW, hbm_bw_for,
                                                     peak_flops_for)

    ici_bw = ICI_BW if ici_bw is None else ici_bw
    peak = peak_flops_for(device_kind)
    bw = hbm_bw_for(device_kind)
    t_compute = flops / peak
    t_hbm = bytes_accessed / bw
    t_ici = collective_bytes / ici_bw
    t_bound = max(t_compute, t_hbm, t_ici)
    bound = ("compute" if t_bound == t_compute
             else "hbm" if t_bound == t_hbm else "ici")
    mfu = t_compute / t_bound if t_bound > 0 else 0.0
    tps = (tokens_per_step / t_bound
           if tokens_per_step and t_bound > 0 else None)
    return RooflineBound(predicted_step_s=t_bound, mfu_ceiling=mfu,
                         bound=bound, peak_flops=peak, hbm_bw=bw,
                         ici_bw=ici_bw, predicted_tokens_per_sec=tps)
