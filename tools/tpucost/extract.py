"""Compiled-artifact metric extraction — the ONE place that parses XLA's
cost/memory analysis and HLO text into plain dicts.

Everything downstream of a ``jax.stages.Lowered``/``Compiled`` pair reads
through these helpers: the tpucost analyzer itself, the flops profiler
(``deepspeed_tpu/profiling/flops_profiler.py``), and the on-chip offload
validator (``scripts/validate_offload_tpu.py``). XLA's dict keys ("bytes
accessed", per-operand "bytes accessed3{}" subkeys, list-vs-dict returns
across jax versions) and ``CompiledMemoryStats`` attribute spellings are
quirky enough that two call sites parsing them independently WILL disagree;
this module is the single implementation.

Stdlib + re only at import time; jax objects are consumed duck-typed, so the
module also parses HLO text handed to it directly (tests, stored programs).
"""

from __future__ import annotations

import hashlib
import re
from collections import Counter
from typing import Any, Dict, Optional

# -- XLA cost analysis -------------------------------------------------------


def cost_analysis_dict(stage: Any) -> Dict[str, float]:
    """Whole-program scalars from ``stage.cost_analysis()`` where ``stage``
    is a ``Compiled`` (post-optimization — exact for what runs) or a
    ``Lowered`` (pre-partitioning — the fallback for entries whose compile
    is disabled, e.g. the 1F1B pipeline programs that crash CPU GSPMD).
    Returns {} when the backend exposes no analysis."""
    try:
        cost = stage.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    return {
        "flops": max(float(cost.get("flops", 0.0)), 0.0),
        "transcendentals": max(float(cost.get("transcendentals", 0.0)), 0.0),
        # the plain key is the total; "bytes accessedN{}" operand subkeys
        # are deliberately not summed (they double-count the total)
        "bytes_accessed": max(float(cost.get("bytes accessed", 0.0)), 0.0),
    }


def memory_analysis_dict(compiled: Any) -> Dict[str, float]:
    """``compiled.memory_analysis()`` → plain dict. ``peak_hbm_bytes`` is the
    buffer-donation-aware device residency bound XLA budgets for:
    arguments + outputs + temps − aliased (donated) bytes. Returns {} when
    the stage has no memory analysis (None, or a backend without it)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}

    def grab(attr: str) -> float:
        return float(getattr(ma, attr, 0) or 0)

    out = {
        "argument_hbm_bytes": grab("argument_size_in_bytes"),
        "output_hbm_bytes": grab("output_size_in_bytes"),
        "temp_hbm_bytes": grab("temp_size_in_bytes"),
        "alias_hbm_bytes": grab("alias_size_in_bytes"),
        "generated_code_bytes": grab("generated_code_size_in_bytes"),
    }
    out["peak_hbm_bytes"] = (out["argument_hbm_bytes"]
                             + out["output_hbm_bytes"]
                             + out["temp_hbm_bytes"]
                             - out["alias_hbm_bytes"])
    return out


def program_hash(text: str) -> str:
    """Stable identity of one compiled/lowered program (autotuner provenance,
    baseline diff display)."""
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


# -- HLO text parsing --------------------------------------------------------

# post-optimization HLO op line: `  %name = f32[2,4]{1,0} opcode(...)` or
# `  %name = (f32[...], s32[...]) opcode(...)`; the opcode is the last
# bare token before the open paren
_HLO_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\(?[^=]*?)\s([\w\-]+)\(",
    re.MULTILINE)

# an HLO shape token: dtype[dims]; dims empty for scalars
_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+(?:[a-z][0-9a-z]*)?|pred)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# the canonical collective-kind names (tpuaudit's scanner owns the list)
from ..tpuaudit.registry import COLLECTIVE_KINDS as COLLECTIVE_OPS  # noqa: E402


def _shape_bytes(type_text: str) -> int:
    """Total bytes of every dtype[dims] shape token in an HLO type string
    (handles tuple types by summing the elements)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_text):
        itemsize = _DTYPE_BYTES.get(dtype)
        if itemsize is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * itemsize
    return total


def hlo_op_census(hlo_text: str) -> Dict[str, int]:
    """Opcode → occurrence count over a post-optimization HLO module. The
    paired -start/-done halves of async collectives count as ONE op (the
    -done is bookkeeping, and splitting differs across XLA versions)."""
    census: Counter = Counter()
    for _, opcode in _HLO_OP_RE.findall(hlo_text):
        if opcode.endswith("-done"):
            continue
        census[opcode[:-6] if opcode.endswith("-start") else opcode] += 1
    return dict(sorted(census.items()))


_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(?:\{\{([0-9, ]*)\}|\[(\d+),(\d+)\]<=)")


def _group_size(op_line: str) -> Optional[int]:
    """Participants per replica group of one collective op line: the literal
    format ``{{0,1},{2,3}}`` (ids in the first group) or the iota v2 format
    ``[groups,size]<=[...]``."""
    m = _REPLICA_GROUPS_RE.search(op_line)
    if not m:
        return None
    if m.group(1) is not None:
        return len([x for x in m.group(1).split(",") if x.strip()])
    return int(m.group(3))


def _axis_of_group(group_size: Optional[int],
                   axis_sizes: Optional[Dict[str, int]]) -> str:
    """Attribute a collective to the mesh axis whose extent matches its
    replica-group size — exact when one non-trivial axis matches; a group
    spanning the whole (multi-axis) mesh is "mesh"; anything else is
    "unattributed" rather than a guess."""
    if not axis_sizes or not group_size or group_size <= 1:
        return "unattributed"
    nontrivial = {a: s for a, s in axis_sizes.items() if s > 1}
    matches = [a for a, s in nontrivial.items() if s == group_size]
    if len(matches) == 1:
        return matches[0]
    total = 1
    for s in nontrivial.values():
        total *= s
    if group_size == total and len(nontrivial) > 1:
        return "mesh"
    return "unattributed"


def collective_census(hlo_text: str,
                      axis_sizes: Optional[Dict[str, int]] = None
                      ) -> Dict[str, Any]:
    """Collective ops in a post-optimization HLO module with their output
    bytes, attributed to mesh axes by replica-group extent. Returns::

        {"total_bytes": float,
         "by_kind": {kind: {"count": int, "bytes": float}},
         "by_axis": {axis: float}}

    Bytes are the op's OUTPUT shape bytes — the payload a step pays ICI/HBM
    for, and the quantity that grows when GSPMD inserts a reshard. The
    -start half of async pairs is counted, the -done skipped."""
    by_kind: Dict[str, Dict[str, float]] = {}
    by_axis: Dict[str, float] = {}
    total = 0.0
    for m in _HLO_OP_RE.finditer(hlo_text):
        type_text, opcode = m.group(1), m.group(2)
        if opcode.endswith("-done"):
            continue
        kind = opcode[:-6] if opcode.endswith("-start") else opcode
        if kind not in COLLECTIVE_OPS:
            continue
        line = hlo_text[m.start():hlo_text.find("\n", m.start())]
        nbytes = float(_shape_bytes(type_text))
        total += nbytes
        k = by_kind.setdefault(kind, {"count": 0, "bytes": 0.0})
        k["count"] += 1
        k["bytes"] += nbytes
        axis = _axis_of_group(_group_size(line), axis_sizes)
        by_axis[axis] = by_axis.get(axis, 0.0) + nbytes
    return {"total_bytes": total,
            "by_kind": dict(sorted(by_kind.items())),
            "by_axis": dict(sorted(by_axis.items()))}


# StableHLO spelling, for entries analyzed pre-compile (compile=False): op
# name with underscores, result type trailing as `-> tensor<2x4xf32>` (or a
# tuple of tensors). Byte counts here are the UNPARTITIONED global shapes —
# comparable run-to-run, not comparable to a compiled census.
_STABLEHLO_COLL_RE = re.compile(
    r'stablehlo\.(all_gather|all_reduce|reduce_scatter|all_to_all|'
    r'collective_permute|collective_broadcast)\b[^\n]*')
_TENSOR_RE = re.compile(r"tensor<([0-9x]*)x?([a-z][0-9a-z]*)>")


def stablehlo_collective_census(stablehlo_text: str) -> Dict[str, Any]:
    """Best-effort collective census over StableHLO (the compile=False
    path). Counts are exact; bytes are parsed from the op's trailing result
    type when present on the line (0 otherwise). No axis attribution — the
    pre-partitioning module has no replica groups to read."""
    by_kind: Dict[str, Dict[str, float]] = {}
    total = 0.0
    for m in _STABLEHLO_COLL_RE.finditer(stablehlo_text):
        kind = m.group(1).replace("_", "-")
        line = m.group(0)
        nbytes = 0.0
        arrow = line.rfind("->")
        if arrow != -1:
            for dims, dtype in _TENSOR_RE.findall(line[arrow:]):
                itemsize = _DTYPE_BYTES.get(
                    {"i1": "pred"}.get(dtype, dtype.replace("i", "s", 1)
                                       if dtype.startswith("i") else dtype))
                if itemsize is None:
                    continue
                n = 1
                for d in dims.split("x"):
                    if d:
                        n *= int(d)
                nbytes += n * itemsize
        total += nbytes
        k = by_kind.setdefault(kind, {"count": 0, "bytes": 0.0})
        k["count"] += 1
        k["bytes"] += nbytes
    return {"total_bytes": total, "by_kind": dict(sorted(by_kind.items())),
            "by_axis": {}}
