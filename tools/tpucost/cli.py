"""tpucost CLI — the static perf gate.

Usage::

    # gate run (what CI does): selftest engines vs the committed baseline
    python -m tools.tpucost --config tools/tpuaudit/selftest_config.json

    python -m tools.tpucost --config cost.json --format json
    python -m tools.tpucost --config cost.json --baseline b.json --write-baseline
    python -m tools.tpucost --config cost.json --diff          # full delta table

Shares the tpuaudit registry + harness (one ``--config`` builds the engines
for both analyzers) and the tpulint/tpuaudit gate semantics: exit 0 clean,
1 regression findings or stale baseline entries, 2 usage error.
``--baseline`` defaults to the committed ``.tpucost-baseline.json`` when it
exists, so the bare gate command needs no flags. ``--devices`` defaults to
8 — the tier-1 virtual-mesh width — because the vectors (per-device shard
sizes, collective payloads) are a function of the mesh, and the committed
baseline is pinned to the CI mesh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ..tpulint.baseline import render_report
from . import baseline as baseline_mod

DEFAULT_BASELINE = ".tpucost-baseline.json"


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpucost",
        description="Static program-cost analyzer: AOT-compiles the "
                    "registered entry points host-side (no TPU) and gates "
                    "their XLA cost/memory/collective vectors against a "
                    "committed baseline with per-metric tolerance bands.")
    parser.add_argument("--config", metavar="FILE", default=None,
                        help="JSON harness config (same file tpuaudit uses); "
                             "builds the engines so they register their "
                             "entry points")
    parser.add_argument("--entries", metavar="NAMES", default=None,
                        help="comma-separated entry-point names "
                             "(default: every registered entry)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", metavar="FILE", default=None,
                        help=f"baseline of committed cost vectors (default: "
                             f"{DEFAULT_BASELINE} when it exists)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write the current vectors to --baseline and "
                             "exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop vanished entries/metrics and ratchet "
                             "surviving values down to current, then exit 0")
    parser.add_argument("--diff", action="store_true",
                        help="print the full per-entry metric delta table "
                             "vs the baseline (not just over-band metrics)")
    parser.add_argument("--device-kind", metavar="KIND", default=None,
                        help="chip generation for the roofline denominators "
                             "(e.g. 'v5e', 'v5p'; default: v5e-class)")
    parser.add_argument("--metrics-jsonl", metavar="FILE", default=None,
                        help="also dump the tpucost/* gauges to a metrics "
                             "JSONL (readable by 'observability report')")
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU device count (default 8, the "
                             "tier-1 mesh; must run before jax imports)")
    parser.add_argument("--list-entries", action="store_true",
                        help="print the registered entry points and exit")
    return parser


def _table(vectors) -> str:
    headers = ["entry", "flops", "bytes", "peak_hbm", "coll_B", "ops",
               "pred_ms", "mfu_ceil", "bound"]
    rows = []
    for v in vectors:
        m = v.metrics
        rows.append([
            v.entry + ("" if v.compiled else " *"),
            f"{m.get('flops', 0):,.0f}",
            f"{m.get('bytes_accessed', 0):,.0f}",
            f"{m.get('peak_hbm_bytes', 0):,.0f}" if "peak_hbm_bytes" in m
            else "-",
            f"{m.get('collective_bytes', 0):,.0f}",
            f"{int(m.get('hlo_op_count', 0))}",
            f"{v.predicted_step_s * 1e3:.4f}",
            f"{v.mfu_ceiling:.3f}",
            v.bound,
        ])
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*r) for r in rows]
    if any(not v.compiled for v in vectors):
        lines.append("* pre-partitioning analysis (entry registered "
                     "compile=False); no memory metrics")
    return "\n".join(lines)


def _diff_table(vectors, known) -> str:
    lines = ["== diff vs baseline =="]
    for v in vectors:
        base = known.get(v.entry)
        if base is None:
            lines.append(f"{v.entry}: NEW (not in baseline)")
            continue
        base_metrics = base.get("metrics", {})
        changed = []
        for metric in sorted(set(base_metrics) | set(
                m for m in v.metrics if m in baseline_mod.TOLERANCES)):
            b, c = base_metrics.get(metric), v.metrics.get(metric)
            if b is None or c is None or b != c:
                b_s = baseline_mod._fmt(float(b)) if b is not None else "-"
                c_s = baseline_mod._fmt(float(c)) if c is not None else "-"
                pct = (baseline_mod._delta_pct(float(b), float(c))
                       if b is not None and c is not None else "")
                changed.append(f"  {metric}: {b_s} -> {c_s} {pct}".rstrip())
        if changed:
            lines.append(f"{v.entry}:")
            lines.extend(changed)
            grown = baseline_mod.grown_op_classes(
                base.get("hlo_ops", {}), v.hlo_ops, top=6)
            if grown:
                lines.append("  grown HLO op classes: " + ", ".join(
                    f"{op} +{d}" for op, d in grown))
        else:
            lines.append(f"{v.entry}: unchanged")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    # the persistent XLA compile cache must stay OFF for the whole process:
    # executables deserialized from it drop their donation-aliasing stats
    # (alias_size_in_bytes=0), which flips peak_hbm_bytes run-to-run for
    # programs near the cache's min-compile-time threshold. Host compiles of
    # the selftest programs are ~1 s each — determinism is worth more here.
    os.environ["DSTPU_COMPILE_CACHE"] = "0"

    from ..tpuaudit.cli import _setup_platform

    _setup_platform(args.devices)

    from ..tpuaudit.registry import get_entry_points

    if args.config:
        from ..tpuaudit import harness

        try:
            harness.build_from_config(harness.load_config(args.config))
        except (OSError, json.JSONDecodeError, ValueError, KeyError) as e:
            print(f"tpucost: bad --config {args.config}: {e}",
                  file=sys.stderr)
            return 2

    try:
        names = ([n.strip() for n in args.entries.split(",") if n.strip()]
                 if args.entries else None)
        entries = get_entry_points(names)
    except KeyError as e:
        print(f"tpucost: {e.args[0]}", file=sys.stderr)
        return 2

    if args.list_entries:
        for ep in entries:
            print(f"{ep.name}: compile={ep.compile} tags={ep.tags}")
        return 0
    if not entries:
        print("tpucost: no entry points registered (pass --config, or "
              "construct the engines in-process first)", file=sys.stderr)
        return 2

    from .core import run_cost

    vectors, errors = run_cost(entries, device_kind=args.device_kind)

    if args.metrics_jsonl:
        from deepspeed_tpu.observability import get_registry

        get_registry().dump_jsonl(args.metrics_jsonl, extra={"tool": "tpucost"})

    baseline_path = args.baseline
    if baseline_path is None and not (args.write_baseline
                                      or args.prune_baseline):
        if os.path.exists(DEFAULT_BASELINE):
            baseline_path = DEFAULT_BASELINE

    if (args.write_baseline or args.prune_baseline) and not baseline_path:
        print("tpucost: --write-baseline/--prune-baseline require "
              "--baseline FILE", file=sys.stderr)
        return 2

    if args.write_baseline:
        if errors:
            for name, msg in sorted(errors.items()):
                print(f"tpucost: {name}: {msg}", file=sys.stderr)
            print("tpucost: refusing to write a baseline while entries fail "
                  "to build", file=sys.stderr)
            return 2
        records = baseline_mod.records_of(vectors)
        if names is not None and os.path.exists(baseline_path):
            # a partial --entries write must not destroy the other entries'
            # committed budgets: merge into the existing baseline
            try:
                records = {**baseline_mod.load(baseline_path), **records}
            except (ValueError, json.JSONDecodeError) as e:
                print(f"tpucost: bad baseline {baseline_path}: {e}",
                      file=sys.stderr)
                return 2
        baseline_mod.write(baseline_path, records)
        print(f"tpucost: wrote {len(vectors)} cost vector(s) to "
              f"{baseline_path} ({len(records)} total)")
        return 0

    # partial runs (--entries) must not condemn keys they never measured
    def in_scope(key: str) -> bool:
        entry, _, _ = key.rpartition("::")
        return names is None or entry in names

    known = {}
    stale: List[str] = []
    findings: List[baseline_mod.CostFinding] = []
    if baseline_path and not os.path.exists(baseline_path):
        if args.prune_baseline:
            print(f"tpucost: cannot prune: baseline {baseline_path} not "
                  "found", file=sys.stderr)
            return 2
        print(f"tpucost: warning: baseline {baseline_path} not found; "
              "reporting without gating", file=sys.stderr)
        baseline_path = None
    if baseline_path:
        try:
            known = baseline_mod.load(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"tpucost: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        if args.prune_baseline:
            if errors:
                # same contract as --write-baseline: a prune that silently
                # skips a broken entry looks like a successful ratchet
                for name, msg in sorted(errors.items()):
                    print(f"tpucost: {name}: {msg}", file=sys.stderr)
                print("tpucost: refusing to prune while entries fail to "
                      "build", file=sys.stderr)
                return 2
            out = baseline_mod.pruned(vectors, known, in_scope=in_scope)
            baseline_mod.write(baseline_path, out)
            print(f"tpucost: pruned baseline {baseline_path}: "
                  f"{len(known)} -> {len(out)} entries")
            return 0
        findings, stale = baseline_mod.compare(vectors, known, errors=errors,
                                               in_scope=in_scope)
    else:
        findings = [baseline_mod.CostFinding(
            name, "trace-error", f"entry failed to trace/compile "
            f"host-side: {msg}") for name, msg in sorted(errors.items())]

    if args.format == "json":
        return render_report(
            findings, stale, tool="tpucost", fmt="json",
            baseline_path=baseline_path, total=len(vectors),
            stale_note=("is outside the tolerance band on the improving "
                        "side — run --prune-baseline"),
            extra_json={"entries": {v.entry: v.to_json() for v in vectors}})

    print("== cost ==")
    print(_table(vectors))
    if args.diff and known:
        print()
        print(_diff_table(vectors, known))
    print()
    return render_report(
        findings, stale, tool="tpucost", fmt="text",
        baseline_path=baseline_path, total=len(vectors),
        stale_note=("is outside the tolerance band on the improving side "
                    "— run --prune-baseline"))


if __name__ == "__main__":
    sys.exit(main())
