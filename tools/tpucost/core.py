"""tpucost core — per-entry cost vectors from host-side compilation.

For every entry in the tpuaudit registry the driver traces + lowers (+
compiles, host-only — the same ``trace_entry`` front half tpuaudit uses) and
extracts a **cost vector**: XLA's own cost analysis (flops, transcendentals,
bytes accessed), memory analysis (argument/output/temp/peak HBM), a
collective-bytes census per mesh axis, jaxpr/HLO op counts and program size
— then derives the analytic roofline bound (predicted step time, MFU
ceiling). No TPU, no device math: the whole vector exists at trace time,
which is what lets CI gate program-level perf with the chip tunnel down.

Entries registered with ``compile=False`` (the 1F1B pipeline programs, whose
host compile hard-crashes CPU GSPMD) fall back to the PRE-partitioning
analyses: ``Lowered.cost_analysis`` and a StableHLO collective census.
Their vectors carry no memory metrics — the gate only judges the metrics a
vector actually has.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Dict, List, Optional, Sequence

from ..tpuaudit.core import iter_eqns_of, resolve_mesh, trace_entry
from ..tpuaudit.registry import EntryPoint, StaleEntryError
from . import extract
from .roofline import roofline

__all__ = ["CostVector", "cost_entry", "run_cost", "registry_cost_vector",
           "measured_join", "publish_vectors"]


@dataclasses.dataclass
class CostVector:
    """Everything the gate, the report CLI and the autotuner read about one
    program. ``metrics`` holds only the scalars that exist for this entry
    (uncompiled entries have no memory metrics)."""

    entry: str
    metrics: Dict[str, float]
    hlo_ops: Dict[str, int]
    collectives: Dict[str, Any]      # {"total_bytes", "by_kind", "by_axis"}
    program_hash: str
    compiled: bool
    predicted_step_s: float
    mfu_ceiling: float
    bound: str
    predicted_tokens_per_sec: Optional[float] = None
    tags: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _jaxpr_eqn_count(closed_jaxpr) -> int:
    return sum(1 for _ in iter_eqns_of(closed_jaxpr))


@contextlib.contextmanager
def _fresh_compiles():
    """Disable jax's persistent compilation cache for the duration: an
    executable LOADED from the cache reports alias_size_in_bytes=0 (the
    deserialized artifact drops its donation-aliasing stats), which made
    peak_hbm_bytes flip run-to-run for programs near the cache's
    min-compile-time threshold. The gate needs the numbers of a real
    compile, and these programs compile in ~1 s host-side."""
    import jax

    prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)


def cost_entry(ep: EntryPoint, device_kind: Optional[str] = None,
               do_compile: Optional[bool] = None) -> CostVector:
    """Build one entry's cost vector. Honors ``ep.compile`` unless
    overridden; raises on trace failure (``run_cost`` maps that to a gate
    finding) and propagates ``StaleEntryError`` (caller skips)."""
    with _fresh_compiles():
        traced, lowered, compiled, _, _ = trace_entry(ep, do_compile)

    if compiled is not None:
        text = compiled.as_text()
        metrics = extract.cost_analysis_dict(compiled)
        metrics.update(extract.memory_analysis_dict(compiled))
        mesh = resolve_mesh(ep)
        axis_sizes = ({str(a): int(s) for a, s in mesh.shape.items()}
                      if mesh is not None else None)
        coll = extract.collective_census(text, axis_sizes)
    else:
        text = lowered.as_text()
        metrics = extract.cost_analysis_dict(lowered)
        coll = extract.stablehlo_collective_census(text)
    metrics.pop("generated_code_bytes", None)   # 0 on CPU; size is the text
    metrics["collective_bytes"] = coll["total_bytes"]
    metrics["jaxpr_eqns"] = float(_jaxpr_eqn_count(traced.jaxpr))
    hlo_ops = extract.hlo_op_census(text) if compiled is not None else {}
    metrics["hlo_op_count"] = float(sum(hlo_ops.values()))
    metrics["program_bytes"] = float(len(text))

    tokens = ep.tags.get("tokens_per_step")
    bound = roofline(metrics.get("flops", 0.0),
                     metrics.get("bytes_accessed", 0.0),
                     coll["total_bytes"], device_kind=device_kind,
                     tokens_per_step=tokens)
    return CostVector(
        entry=ep.name, metrics=metrics, hlo_ops=hlo_ops, collectives=coll,
        program_hash=extract.program_hash(text),
        compiled=compiled is not None,
        predicted_step_s=bound.predicted_step_s,
        mfu_ceiling=bound.mfu_ceiling, bound=bound.bound,
        predicted_tokens_per_sec=bound.predicted_tokens_per_sec,
        tags=dict(ep.tags))


def run_cost(entries: Sequence[EntryPoint],
             device_kind: Optional[str] = None,
             publish_metrics: bool = True
             ) -> tuple:
    """Cost every entry. Returns ``(vectors, errors)`` where ``errors`` maps
    entry name → exception string for entries that failed to trace/compile
    (the CLI gates on those — a program that stopped compiling host-side is
    a regression, not a skip). Stale entries (torn-down engines) are
    silently dropped, mirroring tpuaudit."""
    vectors: List[CostVector] = []
    errors: Dict[str, str] = {}
    for ep in entries:
        try:
            vectors.append(cost_entry(ep, device_kind=device_kind))
        except StaleEntryError:
            continue
        except Exception as e:                      # noqa: BLE001
            errors[ep.name] = f"{type(e).__name__}: {str(e)[:300]}"
    vectors.sort(key=lambda v: v.entry)
    if publish_metrics:
        publish_vectors(vectors)
    return vectors, errors


def registry_cost_vector(name: str, **kwargs) -> Optional[CostVector]:
    """Cost vector for ONE registered entry, or None when the entry is
    absent/stale/untraceable — the autotuner's discovery hook (it must
    degrade to its static tables, never raise)."""
    from ..tpuaudit.registry import get_entry_points

    try:
        ep = get_entry_points([name])[0]
    except KeyError:
        return None
    try:
        return cost_entry(ep, **kwargs)
    except Exception:                               # noqa: BLE001
        return None


def measured_join(entry: str, measured_step_s: float,
                  device_kind: Optional[str] = None) -> Optional[dict]:
    """Pair ONE measured per-invocation device time (seconds, from a
    profiler capture window) with this entry's roofline prediction — the
    join half of the measured-vs-predicted loop. Returns the comparison
    columns (``predicted_step_ms``, ``mfu_ceiling``, ``bound``,
    ``model_error`` = measured/predicted, and ``measured_mfu`` when the
    vector has flops) or None when the entry can't be costed — the
    profiler treats that as a missing column, never an error."""
    if measured_step_s <= 0:
        return None
    v = registry_cost_vector(entry, device_kind=device_kind)
    if v is None:
        return None
    out: Dict[str, Any] = {
        "predicted_step_ms": round(v.predicted_step_s * 1e3, 4),
        "mfu_ceiling": round(v.mfu_ceiling, 4),
        "bound": v.bound,
    }
    if v.predicted_step_s > 0:
        out["model_error"] = round(measured_step_s / v.predicted_step_s, 4)
    flops = v.metrics.get("flops", 0.0)
    if flops > 0:
        try:
            from deepspeed_tpu.autotuning.cost_model import peak_flops_for

            peak = peak_flops_for(device_kind)
        except Exception:                           # noqa: BLE001
            peak = 0.0
        if peak > 0:
            out["measured_mfu"] = round(
                flops / (measured_step_s * peak), 6)
    return out


# gauges published per entry (the report CLI's == cost == section reads
# exactly these back out of a metrics JSONL)
PUBLISHED_METRICS = ("flops", "bytes_accessed", "peak_hbm_bytes",
                     "collective_bytes", "program_bytes")


def publish_vectors(vectors: Sequence[CostVector]) -> None:
    """Publish ``tpucost/<entry>/<metric>`` gauges into the observability
    MetricsRegistry so cost vectors ride the same JSONL/report pipeline as
    goodput and serving metrics."""
    try:
        from deepspeed_tpu.observability import get_registry
    except ImportError:
        return
    reg = get_registry()
    for v in vectors:
        for metric in PUBLISHED_METRICS:
            if metric in v.metrics:
                reg.gauge(f"tpucost/{v.entry}/{metric}").set(v.metrics[metric])
        reg.gauge(f"tpucost/{v.entry}/predicted_step_ms").set(
            v.predicted_step_s * 1e3, bound=v.bound)
        reg.gauge(f"tpucost/{v.entry}/mfu_ceiling").set(v.mfu_ceiling)
        if v.predicted_tokens_per_sec is not None:
            reg.gauge(f"tpucost/{v.entry}/predicted_tokens_per_sec").set(
                v.predicted_tokens_per_sec)
