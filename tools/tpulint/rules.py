"""The eight tpulint rules.

Each rule is small and heuristic by design: the goal is catching the silent
TPU performance/correctness failure modes (host syncs, trace-time side
effects, missed donation, phantom mesh axes, removed APIs, PRNG reuse) at
review time, with inline suppressions as the escape hatch for intentional
cases.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from .core import Finding, ModuleInfo, Rule, RunContext, own_nodes, register
from .jitgraph import JitGraph

# ---------------------------------------------------------------------------
# shared helpers


def collect_declared_axes(module: ModuleInfo) -> Set[str]:
    """Mesh axis names this module declares.

    Sources: ``FOO_AXIS = "foo"`` constants and ``*AXES`` string tuples
    (parallel/mesh.py idiom), plus literal axis tuples / ``axis_names=``
    passed to a ``Mesh(...)`` constructor (test-fixture idiom).
    """
    axes: Set[str] = set()

    def strings_of(node: ast.AST) -> Iterator[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for elt in node.elts:
                yield from strings_of(elt)

    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and (
                        target.id.endswith("_AXIS") or target.id.endswith("AXES")):
                    axes.update(strings_of(node.value))
        elif isinstance(node, ast.Call):
            dotted = module.dotted(node.func) or ""
            if dotted.rpartition(".")[2] == "Mesh":
                if len(node.args) >= 2:
                    axes.update(strings_of(node.args[1]))
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes.update(strings_of(kw.value))
    return axes


def _call_args(node: ast.Call) -> Iterator[ast.AST]:
    yield from node.args
    for kw in node.keywords:
        yield kw.value


def _finding(rule: Rule, module: ModuleInfo, node: ast.AST, message: str) -> Finding:
    return Finding(rule.name, module.path, getattr(node, "lineno", 0),
                   getattr(node, "col_offset", 0), message)


# ---------------------------------------------------------------------------
# 1. host-sync-in-jit


@register
class HostSyncInJit(Rule):
    name = "host-sync-in-jit"
    description = ("device->host transfer or blocking sync reachable from a "
                   "jit-compiled function (forces a round-trip / trace error)")

    _SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
    _SYNC_DOTTED = {
        "numpy.asarray", "numpy.array", "numpy.copy",
        "jax.device_get", "jax.block_until_ready",
    }
    _CAST_BUILTINS = {"float", "int", "bool"}

    def check(self, module: ModuleInfo, jit: JitGraph,
              context: RunContext) -> Iterator[Finding]:
        for fn in jit.reachable:
            for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute) and func.attr in self._SYNC_ATTRS:
                    yield _finding(self, module, node,
                                   f".{func.attr}() blocks on device inside a "
                                   "jit-reachable function")
                    continue
                dotted = module.dotted(func)
                if dotted in self._SYNC_DOTTED:
                    yield _finding(self, module, node,
                                   f"{dotted}() pulls values to host inside a "
                                   "jit-reachable function")
                elif (isinstance(func, ast.Name)
                      and func.id in self._CAST_BUILTINS
                      and len(node.args) == 1
                      and not isinstance(node.args[0], ast.Constant)):
                    yield _finding(self, module, node,
                                   f"{func.id}() on a traced value concretizes "
                                   "(host sync or trace-time error) inside a "
                                   "jit-reachable function")


# ---------------------------------------------------------------------------
# 2. impure-jit


@register
class ImpureJit(Rule):
    name = "impure-jit"
    description = ("Python side effect inside a jit-compiled function — runs "
                   "once at trace time, not per step")

    _IMPURE_PREFIXES = ("time.", "random.", "numpy.random.")

    def check(self, module: ModuleInfo, jit: JitGraph,
              context: RunContext) -> Iterator[Finding]:
        for fn in jit.reachable:
            for node in own_nodes(fn):
                if isinstance(node, ast.Global):
                    yield _finding(self, module, node,
                                   "global statement inside a jit-reachable "
                                   "function (trace-time mutation)")
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Attribute):
                            yield _finding(
                                self, module, node,
                                f"attribute mutation '{ast.unparse(t)} = ...' "
                                "inside a jit-reachable function happens at "
                                "trace time only")
                elif isinstance(node, ast.Call):
                    dotted = module.dotted(node.func)
                    if dotted == "print":
                        yield _finding(self, module, node,
                                       "print() inside a jit-reachable function "
                                       "fires at trace time only — use "
                                       "jax.debug.print")
                    elif dotted and dotted.startswith(self._IMPURE_PREFIXES):
                        yield _finding(self, module, node,
                                       f"{dotted}() is host-side nondeterminism/"
                                       "clock inside a jit-reachable function "
                                       "(baked in at trace time)")


# ---------------------------------------------------------------------------
# 3. missing-donation


@register
class MissingDonation(Rule):
    name = "missing-donation"
    description = ("jitted step/update takes and returns a params/opt-state "
                   "pytree without donate_argnums — doubles peak HBM")

    _DONATABLE = {"params", "param", "opt_state", "opt_states", "state",
                  "optimizer_state", "scaler_state", "master_params"}

    def _donatable_roundtrip(self, fn: ast.AST) -> Optional[str]:
        """Name of a donatable parameter that the function also returns."""
        args = getattr(fn, "args", None)
        if args is None:
            return None
        names = {a.arg for a in
                 list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)}
        candidates = names & self._DONATABLE
        if not candidates:
            return None
        returned: Set[str] = set()
        for node in own_nodes(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                vals = node.value.elts if isinstance(node.value, ast.Tuple) \
                    else [node.value]
                for v in vals:
                    if isinstance(v, ast.Name):
                        returned.add(v.id)
        for cand in sorted(candidates):
            if cand in returned or f"new_{cand}" in returned:
                return cand
        return None

    def check(self, module: ModuleInfo, jit: JitGraph,
              context: RunContext) -> Iterator[Finding]:
        seen: Set[int] = set()
        # decorator form: @jax.jit def step(params, ...) -> ... return params'
        for fn in jit.roots:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            decs = [d for d in fn.decorator_list if jit._is_jit_expr(d)]
            if not decs or any(jit.binding_donates(d) for d in decs):
                continue
            cand = self._donatable_roundtrip(fn)
            if cand and id(fn) not in seen:
                seen.add(id(fn))
                yield _finding(self, module, fn,
                               f"jitted '{fn.name}' takes and returns "
                               f"'{cand}' without donate_argnums — old "
                               "buffers stay live (2x HBM)")
        # call-wrapping form: jax.jit(step) / jax.jit(lambda ...)
        for binding in jit.jit_bindings:
            if not isinstance(binding, ast.Call) or jit.binding_donates(binding):
                continue
            target = jit.binding_target(binding)
            if target is None or id(target) in seen:
                continue
            cand = self._donatable_roundtrip(target)
            if cand:
                seen.add(id(target))
                label = getattr(target, "name", "<lambda>")
                yield _finding(self, module, binding,
                               f"jax.jit('{label}') takes and returns "
                               f"'{cand}' without donate_argnums — old "
                               "buffers stay live (2x HBM)")


# ---------------------------------------------------------------------------
# 4. unknown-mesh-axis


@register
class UnknownMeshAxis(Rule):
    name = "unknown-mesh-axis"
    description = ("PartitionSpec/shard_map/collective references a mesh axis "
                   "name no mesh declares — shards nothing, silently")

    _COLLECTIVES = {
        "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "psum_scatter",
        "all_gather", "all_reduce", "reduce_scatter", "all_to_all", "broadcast",
        "send_next", "send_prev", "axis_index", "axis_size", "axis_rank",
    }

    def _strings_of(self, node: ast.AST) -> Iterator[ast.AST]:
        """Constant-string nodes, through one level of tuple/list/set nesting."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for elt in node.elts:
                yield from self._strings_of(elt)

    def check(self, module: ModuleInfo, jit: JitGraph,
              context: RunContext) -> Iterator[Finding]:
        declared = context.declared_axes
        if not declared:
            return  # nothing to validate against in this run
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = module.dotted(node.func) or ""
            leaf = dotted.rpartition(".")[2]
            if leaf == "PartitionSpec":
                for s in node.args:
                    for c in self._strings_of(s):
                        if c.value not in declared:
                            yield _finding(
                                self, module, c,
                                f"PartitionSpec axis '{c.value}' is not "
                                f"declared by any mesh (known: "
                                f"{', '.join(sorted(declared))})")
            elif leaf == "shard_map":
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        for c in self._strings_of(kw.value):
                            if c.value not in declared:
                                yield _finding(
                                    self, module, c,
                                    f"shard_map axis '{c.value}' is not "
                                    "declared by any mesh")
            if leaf in self._COLLECTIVES:
                for kw in node.keywords:
                    if kw.arg in {"axis", "axis_name"}:
                        for c in self._strings_of(kw.value):
                            if c.value not in declared:
                                yield _finding(
                                    self, module, c,
                                    f"collective {leaf}() names axis "
                                    f"'{c.value}' that no mesh declares")


# ---------------------------------------------------------------------------
# 5. deprecated-jax-api


@register
class DeprecatedJaxApi(Rule):
    name = "deprecated-jax-api"
    description = "JAX API that is deprecated/removed in current releases"

    _PREFIXES = ("jax.experimental.pjit", "jax.experimental.maps")
    _EXACT = {
        "jax.tree_map": "use jax.tree.map (or jax.tree_util.tree_map)",
        "jax.tree_multimap": "use jax.tree.map",
        "jax.experimental.pjit": "jit handles shardings; use jax.jit",
        "jax.experimental.maps": "removed; use jax.shard_map / jax.jit",
    }

    def _advice(self, dotted: str) -> str:
        for prefix in sorted(self._EXACT, key=len, reverse=True):
            if dotted == prefix or dotted.startswith(prefix + "."):
                return self._EXACT[prefix]
        return "migrate to the current API"

    def check(self, module: ModuleInfo, jit: JitGraph,
              context: RunContext) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith(self._PREFIXES):
                        yield _finding(self, module, node,
                                       f"import of deprecated '{a.name}' — "
                                       f"{self._advice(a.name)}")
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.module.startswith(self._PREFIXES):
                    yield _finding(self, module, node,
                                   f"import from deprecated '{node.module}' — "
                                   f"{self._advice(node.module)}")
            elif isinstance(node, ast.Attribute):
                # only the outermost attribute of a chain, once
                if isinstance(module.parents.get(node), ast.Attribute):
                    continue
                dotted = module.dotted(node)
                if dotted and (dotted in self._EXACT
                               or dotted.startswith(self._PREFIXES)):
                    yield _finding(self, module, node,
                                   f"deprecated '{dotted}' — "
                                   f"{self._advice(dotted)}")


# ---------------------------------------------------------------------------
# 6. wallclock-timing-without-sync


@register
class WallclockTimingWithoutSync(Rule):
    name = "wallclock-timing-without-sync"
    description = ("time.time()/time.perf_counter() delta measured around "
                   "dispatched work with no blocking fence between — async "
                   "dispatch means the delta times the enqueue, not the work")

    _CLOCKS = {"time.time", "time.perf_counter", "time.monotonic"}
    _SYNC_ATTRS = {"block_until_ready", "item", "tolist"}
    _SYNC_DOTTED = {"jax.block_until_ready", "jax.device_get",
                    "jax.effects_barrier", "numpy.asarray", "numpy.array"}
    _SYNC_BUILTINS = {"float", "int", "bool"}
    # calls that cannot enqueue device work — ignored when deciding whether
    # the timed interval contains anything worth fencing
    _BENIGN_DOTTED_PREFIXES = (
        "time.", "os.", "sys.", "json.", "math.", "logging.", "collections.",
        "itertools.", "functools.", "re.", "subprocess.", "argparse.",
    )
    _BENIGN_NAMES = {
        "print", "len", "range", "sorted", "min", "max", "sum", "abs",
        "round", "str", "repr", "open", "isinstance", "getattr", "hasattr",
        "setattr", "enumerate", "zip", "list", "dict", "set", "tuple",
        "next", "iter", "log_dist", "super", "type", "id", "format", "vars",
    }
    _BENIGN_ATTRS = {
        "append", "extend", "add", "update", "join", "format", "split",
        "strip", "items", "keys", "values", "get", "pop", "setdefault",
        "write", "flush", "read", "close", "info", "debug", "warning",
        "error", "exception", "mean", "startswith", "endswith", "copy",
        # AOT lowering/compilation runs synchronously on the host — timing
        # it needs no device fence
        "lower", "compile",
        # mesh context-manager factory (parallel/mesh.py ambient idiom)
        # dispatches nothing
        "ambient",
    }

    def _is_clock_call(self, module: ModuleInfo, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and module.dotted(node.func) in self._CLOCKS)

    def _classify(self, module: ModuleInfo, call: ast.Call,
                  syncing_defs: Set[str]) -> str:
        """'sync' | 'benign' | 'work' for one call in the timed interval."""
        func = call.func
        if isinstance(func, ast.Name) and func.id in syncing_defs:
            # locally-defined helper whose body fences — calling it syncs
            return "sync"
        if isinstance(func, ast.Attribute):
            if func.attr in self._SYNC_ATTRS:
                return "sync"
            if func.attr in self._BENIGN_ATTRS:
                return "benign"
        dotted = module.dotted(func)
        if dotted in self._SYNC_DOTTED:
            return "sync"
        if dotted in self._CLOCKS:
            return "benign"
        if isinstance(func, ast.Name):
            if (func.id in self._SYNC_BUILTINS and len(call.args) == 1
                    and not isinstance(call.args[0], ast.Constant)):
                return "sync"          # float(loss) materialises the array
            if func.id in self._BENIGN_NAMES:
                return "benign"
        if dotted and (dotted in self._BENIGN_NAMES
                       or dotted.startswith(self._BENIGN_DOTTED_PREFIXES)):
            return "benign"
        return "work"

    def _syncing_defs(self, module: ModuleInfo, scope: ast.AST) -> Set[str]:
        """Names of functions defined in this scope whose own body contains a
        blocking fence — calling them from a timed interval counts as sync."""
        out: Set[str] = set()
        for node in own_nodes(scope):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for inner in own_nodes(node):
                if not isinstance(inner, ast.Call):
                    continue
                func = inner.func
                if ((isinstance(func, ast.Attribute)
                     and func.attr in self._SYNC_ATTRS)
                        or module.dotted(func) in self._SYNC_DOTTED):
                    out.add(node.name)
                    break
        return out

    def _scan_scope(self, module: ModuleInfo, scope: ast.AST) -> Iterator[Finding]:
        nodes = list(own_nodes(scope))
        # clock-start assignments: name -> sorted start linenos
        starts: dict = {}
        for node in nodes:
            if (isinstance(node, ast.Assign)
                    and self._is_clock_call(module, node.value)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                starts.setdefault(node.targets[0].id, []).append(node.lineno)
        if not starts:
            return
        syncing_defs = self._syncing_defs(module, scope)
        calls = [n for n in nodes if isinstance(n, ast.Call)]
        for node in nodes:
            # delta = clock() - t0   (possibly nested, e.g. xs.append(...))
            if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
                    and self._is_clock_call(module, node.left)
                    and isinstance(node.right, ast.Name)
                    and node.right.id in starts):
                continue
            begin = max((ln for ln in starts[node.right.id]
                         if ln < node.lineno), default=None)
            if begin is None:
                continue
            between = [c for c in calls if begin < c.lineno <= node.lineno
                       and c is not node.left]
            kinds = [(self._classify(module, c, syncing_defs), c.lineno)
                     for c in between]
            work_lines = [ln for k, ln in kinds if k == "work"]
            sync_lines = [ln for k, ln in kinds if k == "sync"]
            # work dispatched AFTER the last fence is still unfenced at the
            # closing clock read — one early fence does not bless the rest
            if work_lines and (not sync_lines
                               or max(work_lines) > max(sync_lines)):
                yield _finding(
                    self, module, node,
                    f"wall-clock delta over '{node.right.id}' spans "
                    "dispatched calls with no fence (block_until_ready / "
                    "device_get / float()) before reading the clock — "
                    "under async dispatch this times the enqueue only")

    def check(self, module: ModuleInfo, jit: JitGraph,
              context: RunContext) -> Iterator[Finding]:
        # a module that never imports jax cannot dispatch async device work
        if not any(v == "jax" or v.startswith("jax.")
                   for v in module.aliases.values()):
            return
        scopes = [module.tree] + [f for f in jit.all_defs
                                  if isinstance(f, (ast.FunctionDef,
                                                    ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from self._scan_scope(module, scope)


# ---------------------------------------------------------------------------
# 7. hardcoded-partition-spec


@register
class HardcodedPartitionSpec(Rule):
    name = "hardcoded-partition-spec"
    description = ("PartitionSpec built from literal mesh-axis strings "
                   "outside the rule registry (parallel/rules.py) — layout "
                   "decisions the tpushard analyzer cannot see or audit")

    _EXEMPT_SUFFIXES = (
        # THE place mesh-axis placement is allowed to be spelled out: the
        # logical-axis rule registry itself, and the mesh module that
        # defines the axis vocabulary the registry maps onto
        "parallel/rules.py",
        "parallel/mesh.py",
    )

    def _is_test_path(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        base = norm.rpartition("/")[2]
        return ("/tests/" in norm or norm.startswith("tests/")
                or base.startswith("test_") or base.endswith("_test.py"))

    def _strings_of(self, node: ast.AST) -> Iterator[ast.AST]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for elt in node.elts:
                yield from self._strings_of(elt)

    def check(self, module: ModuleInfo, jit: JitGraph,
              context: RunContext) -> Iterator[Finding]:
        norm = module.path.replace("\\", "/")
        if norm.endswith(self._EXEMPT_SUFFIXES) or self._is_test_path(norm):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = module.dotted(node.func) or ""
            if dotted.rpartition(".")[2] != "PartitionSpec":
                continue
            literals = [c.value for arg in node.args
                        for c in self._strings_of(arg)]
            if literals:
                yield _finding(
                    self, module, node,
                    f"PartitionSpec({', '.join(repr(s) for s in literals)}) "
                    "hardcodes mesh axes outside parallel/rules.py — derive "
                    "the placement from the rule registry (or suppress if "
                    "this spec is genuinely not a parameter/output layout)")


# ---------------------------------------------------------------------------
# 8. key-reuse


@register
class KeyReuse(Rule):
    name = "key-reuse"
    description = ("a PRNGKey consumed by more than one call without split — "
                   "correlated randomness")

    _KEY_MAKERS = {"jax.random.PRNGKey", "jax.random.key"}

    def _scan_scope(self, module: ModuleInfo, scope: ast.AST) -> Iterator[Finding]:
        events = sorted(
            (n for n in own_nodes(scope) if isinstance(n, (ast.Assign, ast.Call))),
            key=lambda n: (n.lineno, n.col_offset))
        uses = {}  # var name -> consumption count
        for node in events:
            if isinstance(node, ast.Assign):
                if (isinstance(node.value, ast.Call)
                        and module.dotted(node.value.func) in self._KEY_MAKERS
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    uses[node.targets[0].id] = 0
                else:
                    # any rebinding kills tracking, including tuple unpacks
                    # like `key, sub = jax.random.split(key)`
                    for t in node.targets:
                        for name in ast.walk(t):
                            if isinstance(name, ast.Name):
                                uses.pop(name.id, None)
            else:  # Call: every argument position consumes
                for arg in _call_args(node):
                    if isinstance(arg, ast.Name) and arg.id in uses:
                        uses[arg.id] += 1
                        if uses[arg.id] == 2:
                            yield _finding(
                                self, module, node,
                                f"PRNGKey '{arg.id}' is consumed by a second "
                                "call without jax.random.split — both sites "
                                "draw identical randomness")

    def check(self, module: ModuleInfo, jit: JitGraph,
              context: RunContext) -> Iterator[Finding]:
        scopes = [module.tree] + [f for f in jit.all_defs
                                  if isinstance(f, (ast.FunctionDef,
                                                    ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from self._scan_scope(module, scope)
