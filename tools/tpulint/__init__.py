"""tpulint — JAX/TPU static analysis for the deepspeed_tpu tree.

Six rules catch the failure modes that are silent on TPU: host syncs inside
jit, trace-time side effects, missing buffer donation, undeclared mesh axes,
deprecated JAX APIs, and PRNG key reuse. See docs/tpulint.md.
"""

from .core import RULES, Finding, analyze_paths, analyze_source
from . import rules as _rules  # noqa: F401  (imports populate the registry)

__all__ = ["RULES", "Finding", "analyze_paths", "analyze_source"]
