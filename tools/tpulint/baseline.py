"""Baseline handling — committed debt doesn't block CI, new findings do.

The baseline stores per-(path, rule) finding COUNTS rather than line numbers,
so unrelated edits that shift lines don't invalidate it, while any net-new
violation in a file (count exceeds the recorded budget) fails the gate.
Fixing findings only ever lowers counts, which passes; regenerate with
``--write-baseline`` to ratchet the budget down.
"""

from __future__ import annotations

import collections
import json
from typing import Dict, List, Sequence

from .core import Finding

BASELINE_VERSION = 1


def counts_of(findings: Sequence[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = collections.Counter()
    for f in findings:
        counts[f.key] += 1
    return dict(sorted(counts.items()))


def load(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{data.get('version')!r}")
    return {str(k): int(v) for k, v in data.get("counts", {}).items()}


def write(path: str, findings: Sequence[Finding]) -> None:
    payload = {
        "version": BASELINE_VERSION,
        "tool": "tpulint",
        "counts": counts_of(findings),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def new_findings(findings: Sequence[Finding],
                 baseline: Dict[str, int]) -> List[Finding]:
    """Findings over budget. Within one (path, rule) bucket the LAST findings
    in line order are reported as new — a stable, if arbitrary, choice."""
    by_key: Dict[str, List[Finding]] = collections.defaultdict(list)
    for f in findings:
        by_key[f.key].append(f)
    out: List[Finding] = []
    for key, group in by_key.items():
        budget = baseline.get(key, 0)
        if len(group) > budget:
            out.extend(group[budget:])
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out
