"""Baseline handling — committed debt doesn't block CI, new findings do.

The baseline stores per-(path, rule) finding COUNTS rather than line numbers,
so unrelated edits that shift lines don't invalidate it, while any net-new
violation in a file (count exceeds the recorded budget) fails the gate.
Fixing findings only ever lowers counts, which passes — but the stale budget
then lingers, silently re-admitting regressions up to the old count. The gate
therefore ERRORS on stale keys (a baselined bucket that no longer produces
any finding); ``--prune-baseline`` drops stale keys and ratchets surviving
budgets down to the current counts.

tpuaudit shares these semantics (its keys are ``entry::check`` instead of
``path::rule``) via the ``tool=`` parameter.
"""

from __future__ import annotations

import collections
import json
from typing import Callable, Dict, List, Optional, Sequence

BASELINE_VERSION = 1


def counts_of(findings: Sequence) -> Dict[str, int]:
    counts: Dict[str, int] = collections.Counter()
    for f in findings:
        counts[f.key] += 1
    return dict(sorted(counts.items()))


def load(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{data.get('version')!r}")
    return {str(k): int(v) for k, v in data.get("counts", {}).items()}


def write(path: str, findings: Sequence, tool: str = "tpulint") -> None:
    payload = {
        "version": BASELINE_VERSION,
        "tool": tool,
        "counts": counts_of(findings),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def stale_keys(findings: Sequence, baseline: Dict[str, int],
               in_scope: Optional[Callable[[str], bool]] = None) -> List[str]:
    """Baseline keys with a positive budget but ZERO current findings — rot
    that would silently re-admit regressions. ``in_scope`` limits the check
    to keys this run could have produced (a partial run — subset of paths or
    ``--select``ed rules — must not condemn keys it never looked at)."""
    current = counts_of(findings)
    return sorted(k for k, budget in baseline.items()
                  if budget > 0 and current.get(k, 0) == 0
                  and (in_scope is None or in_scope(k)))


def pruned(findings: Sequence, baseline: Dict[str, int],
           in_scope: Optional[Callable[[str], bool]] = None) -> Dict[str, int]:
    """Baseline with stale keys dropped and surviving budgets clamped down to
    the current counts. Out-of-scope keys pass through untouched."""
    current = counts_of(findings)
    out: Dict[str, int] = {}
    for k, budget in baseline.items():
        if in_scope is not None and not in_scope(k):
            out[k] = budget
            continue
        n = current.get(k, 0)
        if n > 0:
            out[k] = min(budget, n)
    return dict(sorted(out.items()))


def write_counts(path: str, counts: Dict[str, int], tool: str = "tpulint") -> None:
    """Write an already-computed counts dict (the prune path)."""
    payload = {"version": BASELINE_VERSION, "tool": tool,
               "counts": dict(sorted(counts.items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def gate_and_report(findings: Sequence, *, tool: str, fmt: str,
                    baseline_path: Optional[str], write_baseline: bool,
                    prune_baseline: bool,
                    in_scope: Optional[Callable[[str], bool]] = None) -> int:
    """The shared CLI gate driver — baseline write/prune dispatch, over-budget
    diffing, stale-key detection, text/JSON rendering and the exit code. Both
    analyzers route their CLI tail through here so the gate semantics
    (including every stale/prune edge case) cannot drift between them.

    Findings only need ``key``/``render()``/``to_json()``. Exit status: 0
    clean (or fully baselined), 1 new findings or stale keys, 2 usage error.
    """
    import os
    import sys

    if (write_baseline or prune_baseline) and not baseline_path:
        print(f"{tool}: --write-baseline/--prune-baseline require "
              "--baseline FILE", file=sys.stderr)
        return 2

    if write_baseline:
        write(baseline_path, findings, tool=tool)
        print(f"{tool}: wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    gating: List = list(findings)
    stale: List[str] = []
    if baseline_path and not os.path.exists(baseline_path):
        if prune_baseline:
            print(f"{tool}: cannot prune: baseline {baseline_path} not found",
                  file=sys.stderr)
            return 2
        print(f"{tool}: warning: baseline {baseline_path} not found; "
              "gating on ALL findings", file=sys.stderr)
    if baseline_path and os.path.exists(baseline_path):
        try:
            known_counts = load(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"{tool}: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        if prune_baseline:
            out = pruned(findings, known_counts, in_scope=in_scope)
            write_counts(baseline_path, out, tool=tool)
            print(f"{tool}: pruned baseline {baseline_path}: "
                  f"{len(known_counts)} -> {len(out)} entries")
            return 0
        gating = new_findings(findings, known_counts)
        stale = stale_keys(findings, known_counts, in_scope=in_scope)

    return render_report(gating, stale, tool=tool, fmt=fmt,
                         baseline_path=baseline_path, total=len(findings))


def render_report(gating: Sequence, stale: Sequence[str], *, tool: str,
                  fmt: str, baseline_path: Optional[str], total: int,
                  stale_note: str = ("no longer produces findings — run "
                                     "--prune-baseline"),
                  extra_json: Optional[Dict] = None) -> int:
    """The shared report/exit tail — text/JSON rendering of over-budget
    findings + stale keys and the exit code. All four analyzers (tpulint,
    tpuaudit, tpucost, tpushard) end here, so ``scripts/check.sh`` composes
    identical gate semantics into one CI exit code. ``stale_note`` lets a
    value-gated tool (tpucost) phrase staleness in its own terms."""
    if fmt == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in gating],
            "stale_baseline_keys": list(stale),
            "total_findings": total,
            "new_findings": len(gating),
            **(extra_json or {}),
        }, indent=2))
    else:
        for f in gating:
            print(f.render())
        for key in stale:
            print(f"stale baseline entry: {key} {stale_note}")
        suffix = " (after baseline)" if baseline_path else ""
        print(f"{tool}: {len(gating)} new finding(s){suffix}, "
              f"{len(stale)} stale baseline key(s), {total} total")
    return 1 if (gating or stale) else 0


def new_findings(findings: Sequence,
                 baseline: Dict[str, int]) -> List:
    """Findings over budget. Within one bucket the LAST findings in input
    order are reported as new — a stable, if arbitrary, choice. Works for
    both tpulint Findings (path::rule keys) and tpuaudit Findings
    (entry::check keys)."""
    by_key: Dict[str, List] = collections.defaultdict(list)
    for f in findings:
        by_key[f.key].append(f)
    out: List = []
    for key, group in by_key.items():
        budget = baseline.get(key, 0)
        if len(group) > budget:
            out.extend(group[budget:])
    out.sort(key=lambda f: (f.key, getattr(f, "line", 0),
                            getattr(f, "col", 0)))
    return out
