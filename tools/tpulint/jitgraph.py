"""Jit-reachability analysis for one module.

Roots are functions bound to a jit transform either way this codebase spells
it:

* decorator form — ``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)``;
* call-wrapping form — ``jax.jit(step)``, ``jax.jit(lambda ...: ...)``,
  ``jax.jit(jax.value_and_grad(micro))`` (the dominant idiom here: see
  ``runtime/engine.py`` / ``runtime/param_offload.py``).

Reachability then closes over same-module calls by simple name and over
nested defs of reachable functions (a nested def inside a jitted function is
traced when called — treat it as inside the trace). This is deliberately a
per-module, name-based approximation: cheap, no imports executed, and wrong
only in the conservative direction rules care about (a helper only ever
called outside jit but *named* like one called inside may be over-flagged —
that is what inline suppressions are for).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import ModuleInfo, own_nodes

# transforms whose operand is (eventually) jit-compiled when the outer call
# is a jit binding: jax.jit(jax.value_and_grad(f)) makes f a root
_WRAPPER_ATTRS = {
    "grad", "value_and_grad", "vmap", "pmap", "remat", "checkpoint",
    "custom_vjp", "custom_jvp",
}
_JIT_DOTTED = {
    "jax.jit", "jit", "jax.pjit", "pjit",
    "jax.experimental.pjit.pjit",
}
# structured-control/SPMD combinators whose function-valued arguments are
# traced (device-side) wherever the combinator itself runs. Deliberately NOT
# including io_callback / pure_callback / debug.callback — those arguments
# run on HOST, where syncs and side effects are the whole point.
_COMBINATOR_ATTRS = {
    "scan", "cond", "while_loop", "switch", "fori_loop", "associative_scan",
    "map", "shard_map", "checkpoint", "remat", "custom_vjp", "custom_jvp",
    "defvjp", "defjvp", "vmap", "grad", "value_and_grad",
}

FunctionNode = ast.AST  # FunctionDef | AsyncFunctionDef | Lambda


class JitGraph:
    def __init__(self, module: ModuleInfo):
        self.module = module
        self.defs_by_name: Dict[str, List[ast.AST]] = {}
        self.all_defs: List[ast.AST] = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
                self.all_defs.append(node)
            elif isinstance(node, ast.Lambda):
                self.all_defs.append(node)
        self.jit_bindings: List[ast.AST] = []   # Call/decorator nodes binding jit
        self.roots: Set[ast.AST] = set()
        self._find_roots()
        self.reachable: Set[ast.AST] = self._close_over_calls(self.roots)

    # -- root discovery ----------------------------------------------------
    def _is_jit_expr(self, node: ast.AST) -> bool:
        """True for an expression that IS a jit transform (bare or partial)."""
        dotted = self.module.dotted(node)
        if dotted in _JIT_DOTTED:
            return True
        if isinstance(node, ast.Call):
            fd = self.module.dotted(node.func)
            if fd in _JIT_DOTTED:
                return True
            if fd in {"functools.partial", "partial"} and node.args and \
                    self.module.dotted(node.args[0]) in _JIT_DOTTED:
                return True
        return False

    def _mark_operand(self, arg: ast.AST) -> None:
        """Mark the function(s) an expression evaluates to as jit roots."""
        if isinstance(arg, ast.Lambda):
            self.roots.add(arg)
        elif isinstance(arg, ast.Name):
            for d in self.defs_by_name.get(arg.id, ()):
                self.roots.add(d)
        elif isinstance(arg, ast.Call):
            fd = self.module.dotted(arg.func) or ""
            if fd.rpartition(".")[2] in _WRAPPER_ATTRS and arg.args:
                self._mark_operand(arg.args[0])
            else:
                # factory idiom: jax.jit(make_step()) — mark functions the
                # factory returns (Return of a local def's name)
                for d in self.defs_by_name.get(fd, ()):
                    local = {n.name: n for n in ast.walk(d)
                             if isinstance(n, (ast.FunctionDef,
                                               ast.AsyncFunctionDef))}
                    for n in ast.walk(d):
                        if isinstance(n, ast.Return) and \
                                isinstance(n.value, ast.Name) and \
                                n.value.id in local:
                            self.roots.add(local[n.value.id])

    def _find_roots(self) -> None:
        for node in ast.walk(self.module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jit_expr(dec):
                        self.roots.add(node)
                        self.jit_bindings.append(dec)
            if isinstance(node, ast.Call):
                dotted = self.module.dotted(node.func)
                if dotted in _JIT_DOTTED:
                    self.jit_bindings.append(node)
                    if node.args:
                        self._mark_operand(node.args[0])
                elif (dotted or "").rpartition(".")[2] == "shard_map" \
                        and node.args:
                    # shard_map bodies are SPMD-traced (and jitted in every
                    # call site this tree has) — treat as roots
                    self._mark_operand(node.args[0])

    # -- closure -----------------------------------------------------------
    def _close_over_calls(self, roots: Set[ast.AST]) -> Set[ast.AST]:
        reachable = set(roots)
        work = list(roots)
        while work:
            fn = work.pop()
            for node in own_nodes(fn):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)) and node not in reachable:
                    # nested def inside a traced function: part of the trace
                    reachable.add(node)
                    work.append(node)
                elif isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Name):
                        for d in self.defs_by_name.get(node.func.id, ()):
                            if d not in reachable:
                                reachable.add(d)
                                work.append(d)
                    leaf = (self.module.dotted(node.func) or "") \
                        .rpartition(".")[2]
                    if leaf in _COMBINATOR_ATTRS:
                        # function-valued args to combinators are traced too
                        for arg in node.args:
                            if isinstance(arg, ast.Name):
                                for d in self.defs_by_name.get(arg.id, ()):
                                    if d not in reachable:
                                        reachable.add(d)
                                        work.append(d)
        return reachable

    # -- queries used by rules --------------------------------------------
    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.module.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return cur
            cur = self.module.parents.get(cur)
        return None

    def binding_donates(self, binding: ast.AST) -> bool:
        """Does a jit binding (Call or decorator expr) pass donate_*?"""
        if isinstance(binding, ast.Call):
            for kw in binding.keywords:
                if kw.arg and kw.arg.startswith("donate"):
                    return True
        return False

    def binding_target(self, binding: ast.AST) -> Optional[ast.AST]:
        """The function def a jit *call* binding wraps, when resolvable."""
        if not (isinstance(binding, ast.Call) and binding.args):
            return None
        arg = binding.args[0]
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            defs = self.defs_by_name.get(arg.id, ())
            return defs[-1] if defs else None
        return None
