"""tpulint CLI.

Usage::

    python -m tools.tpulint deepspeed_tpu/ --baseline .tpulint-baseline.json
    python -m tools.tpulint path/to/file.py --format json
    python -m tools.tpulint deepspeed_tpu/ --baseline b.json --write-baseline

Exit status: 0 clean (or all findings baselined), 1 new findings,
2 usage/internal error.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .core import RULES, analyze_paths, iter_python_files


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpulint",
        description="JAX/TPU static analysis: jit purity, host syncs, "
                    "donation, mesh-axis and PRNG hygiene.")
    parser.add_argument("paths", nargs="*", default=["deepspeed_tpu"],
                        help="files or directories to analyze "
                             "(default: deepspeed_tpu)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", metavar="FILE", default=None,
                        help="JSON baseline of accepted findings; only "
                             "findings over the baselined counts fail")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write the current findings to --baseline and "
                             "exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop stale baseline entries (keys that no "
                             "longer produce findings) and ratchet budgets "
                             "down to current counts, then exit 0")
    parser.add_argument("--select", metavar="RULES", default=None,
                        help="comma-separated rule names to run "
                             "(default: all)")
    parser.add_argument("--root", metavar="DIR", default=None,
                        help="directory finding paths are made relative to "
                             "(default: cwd)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in RULES:
            print(f"{rule.name}: {rule.description}")
        return 0

    select = None
    if args.select:
        select = {r.strip() for r in args.select.split(",") if r.strip()}
        known = {r.name for r in RULES}
        unknown = select - known
        if unknown:
            print(f"tpulint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"tpulint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    findings = analyze_paths(args.paths, root=args.root, select=select)

    # Stale detection must only judge keys THIS run could have produced: a
    # partial run (subset of paths, --select) says nothing about the rest.
    # A key whose file sits UNDER an analyzed directory counts even when the
    # file no longer exists — a deleted file is the most common source of
    # baseline rot, and its budget must not linger.
    root = args.root or os.getcwd()
    analyzed = {os.path.relpath(p, root).replace(os.sep, "/")
                for p in iter_python_files(args.paths)}
    dir_prefixes: List[str] = []
    for p in args.paths:
        if os.path.isdir(p):
            rel = os.path.relpath(p, root).replace(os.sep, "/")
            dir_prefixes.append("" if rel == "." else rel.rstrip("/") + "/")

    def in_scope(key: str) -> bool:
        path, _, rule = key.rpartition("::")
        if select is not None and rule not in select:
            return False
        return path in analyzed or any(path.startswith(pref)
                                       for pref in dir_prefixes)

    return baseline_mod.gate_and_report(
        findings, tool="tpulint", fmt=args.format,
        baseline_path=args.baseline, write_baseline=args.write_baseline,
        prune_baseline=args.prune_baseline, in_scope=in_scope)


if __name__ == "__main__":
    sys.exit(main())
