"""tpulint CLI.

Usage::

    python -m tools.tpulint deepspeed_tpu/ --baseline .tpulint-baseline.json
    python -m tools.tpulint path/to/file.py --format json
    python -m tools.tpulint deepspeed_tpu/ --baseline b.json --write-baseline

Exit status: 0 clean (or all findings baselined), 1 new findings,
2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .core import RULES, Finding, analyze_paths


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpulint",
        description="JAX/TPU static analysis: jit purity, host syncs, "
                    "donation, mesh-axis and PRNG hygiene.")
    parser.add_argument("paths", nargs="*", default=["deepspeed_tpu"],
                        help="files or directories to analyze "
                             "(default: deepspeed_tpu)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", metavar="FILE", default=None,
                        help="JSON baseline of accepted findings; only "
                             "findings over the baselined counts fail")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write the current findings to --baseline and "
                             "exit 0")
    parser.add_argument("--select", metavar="RULES", default=None,
                        help="comma-separated rule names to run "
                             "(default: all)")
    parser.add_argument("--root", metavar="DIR", default=None,
                        help="directory finding paths are made relative to "
                             "(default: cwd)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in RULES:
            print(f"{rule.name}: {rule.description}")
        return 0

    select = None
    if args.select:
        select = {r.strip() for r in args.select.split(",") if r.strip()}
        known = {r.name for r in RULES}
        unknown = select - known
        if unknown:
            print(f"tpulint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"tpulint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    findings = analyze_paths(args.paths, root=args.root, select=select)

    if args.write_baseline:
        if not args.baseline:
            print("tpulint: --write-baseline requires --baseline FILE",
                  file=sys.stderr)
            return 2
        baseline_mod.write(args.baseline, findings)
        print(f"tpulint: wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    gating: List[Finding] = findings
    if args.baseline and not os.path.exists(args.baseline):
        print(f"tpulint: warning: baseline {args.baseline} not found; "
              "gating on ALL findings", file=sys.stderr)
    if args.baseline and os.path.exists(args.baseline):
        try:
            known_counts = baseline_mod.load(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"tpulint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        gating = baseline_mod.new_findings(findings, known_counts)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in gating],
            "total_findings": len(findings),
            "new_findings": len(gating),
        }, indent=2))
    else:
        for f in gating:
            print(f.render())
        suffix = " (after baseline)" if args.baseline else ""
        print(f"tpulint: {len(gating)} new finding(s){suffix}, "
              f"{len(findings)} total")
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
