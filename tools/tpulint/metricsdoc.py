"""metricsdoc — the metric-name ↔ documentation drift gate.

Every ``registry.counter/gauge/histogram("name", ...)`` metric published in
the tree must appear in the ``docs/observability.md`` metric table. The
table has grown by hand for 13+ PRs; without a gate, a new metric (or a
renamed one) silently drifts out of the documentation and dashboards built
from the table go stale.

Mechanics:

* **Publish side** — a stdlib-AST walk over the source tree collects the
  FIRST argument of every ``.counter(`` / ``.gauge(`` / ``.histogram(``
  call when it is a string literal. f-strings and variables are skipped
  (unverifiable statically); literal names are the contract.
* **Doc side** — backtick code spans on markdown-table lines (``|``-rows)
  of the doc. Spans expand the table's established shorthands:
  ``a/{x,y}_z``-style brace alternation, ``{label=,...}`` annotations
  (stripped — labels are not part of the name), ``<stat>`` wildcard
  segments, and trailing ``*`` wildcards (``Train/Samples/*``).
* A published name missing from the table is a finding; the gate exits 1.
  ``scripts/lint.sh`` runs this after tpulint.

Usage::

    python -m tools.tpulint.metricsdoc [--doc docs/observability.md]
                                       [paths...]
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Tuple

DEFAULT_PATHS = ("deepspeed_tpu", "tools", "bench.py", "bench_infer.py",
                 "bench_moe.py", "bench_rlhf.py", "bench_zero.py",
                 "__graft_entry__.py")
DEFAULT_DOC = os.path.join("docs", "observability.md")
_METRIC_METHODS = ("counter", "gauge", "histogram")
_BACKTICK = re.compile(r"`([^`]+)`")


def collect_published(paths: List[str]) -> Dict[str, List[str]]:
    """name -> [file:line, ...] for every literal metric registration."""
    from .core import iter_python_files

    out: Dict[str, List[str]] = {}
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                name = node.args[0].value
                out.setdefault(name, []).append(f"{path}:{node.lineno}")
    return out


def _expand(token: str) -> List[str]:
    """Expand one doc token into its concrete alternatives: label braces
    (``{k=,...}``) are stripped, alternation braces (``{a,b}`` / ``{a|b}``)
    multiply out."""
    m = re.search(r"\{([^{}]*)\}", token)
    if m is None:
        return [token]
    inner = m.group(1)
    head, tail = token[:m.start()], token[m.end():]
    if "=" in inner:
        return _expand(head + tail)       # label annotation, not the name
    alts = [a for part in inner.split(",") for a in part.split("|")]
    out: List[str] = []
    for alt in alts:
        out.extend(_expand(head + alt.strip() + tail))
    return out


def doc_patterns(doc_path: str) -> List[Tuple[str, re.Pattern]]:
    """(doc token, compiled pattern) for every backtick span on a table
    row. ``<seg>`` matches one path segment; a trailing ``*`` matches the
    rest of the name."""
    patterns: List[Tuple[str, re.Pattern]] = []
    with open(doc_path, encoding="utf-8") as fh:
        for line in fh:
            if not line.lstrip().startswith("|"):
                continue
            for span in _BACKTICK.findall(line):
                span = span.strip()
                if "/" not in span or " " in span:
                    continue              # prose / file references
                for tok in _expand(span):
                    rx = "".join(
                        "[^/]+" if part.startswith("<") else
                        ".*" if part == "*" else re.escape(part)
                        for part in re.split(r"(<[^<>]*>|\*)", tok) if part)
                    patterns.append((span, re.compile(rx + r"\Z")))
    return patterns


def find_undocumented(paths: List[str], doc_path: str
                      ) -> List[Tuple[str, List[str]]]:
    published = collect_published(paths)
    patterns = doc_patterns(doc_path)
    missing = []
    for name in sorted(published):
        if not any(rx.fullmatch(name) for _, rx in patterns):
            missing.append((name, published[name]))
    return missing


def main(argv: List[str]) -> int:
    doc = DEFAULT_DOC
    paths: List[str] = []
    it = iter(argv)
    for arg in it:
        if arg == "--doc":
            doc = next(it, doc)
        elif arg in ("-h", "--help"):
            print("usage: python -m tools.tpulint.metricsdoc "
                  "[--doc docs/observability.md] [paths...]")
            return 0
        else:
            paths.append(arg)
    if not paths:
        paths = [p for p in DEFAULT_PATHS if os.path.exists(p)]
    if not os.path.exists(doc):
        print(f"metricsdoc: doc not found: {doc}", file=sys.stderr)
        return 2
    missing = find_undocumented(paths, doc)
    if not missing:
        print(f"metricsdoc: OK — every literal metric name is documented "
              f"in {doc}")
        return 0
    print(f"metricsdoc: {len(missing)} metric name(s) published but "
          f"missing from {doc}'s metric table:", file=sys.stderr)
    for name, sites in missing:
        print(f"  {name}  ({sites[0]}"
              + (f" +{len(sites) - 1}" if len(sites) > 1 else "") + ")",
              file=sys.stderr)
    print("add a table row (see docs/observability.md 'What gets recorded "
          "where') or rename the metric", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
