"""tpulint core — module model, suppression parsing, rule registry, driver.

The analyzer is stdlib-only (``ast`` + ``tokenize``-free line scanning): it
must run in CI containers that have nothing but the training deps installed.

Terminology used by rules:

* *dotted name* — the canonical dotted path of an expression after expanding
  import aliases, e.g. with ``import numpy as np``, ``np.asarray`` resolves
  to ``numpy.asarray``; with ``from jax import random as jr``, ``jr.split``
  resolves to ``jax.random.split``.
* *jit-reachable* — a function either jit-bound directly (decorator or
  ``jax.jit(fn)``-style wrapping) or called (by simple name, same module)
  from a jit-reachable function. See ``jitgraph.py``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

__all__ = [
    "Finding", "ModuleInfo", "Rule", "RULES", "register",
    "analyze_source", "analyze_paths", "iter_python_files", "own_nodes",
]

_SUPPRESS_RE = re.compile(
    r"#.*?tpulint:\s*disable="
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``key`` (path::rule) is the baseline bucket."""
    rule: str
    path: str
    line: int
    col: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.rule}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class ModuleInfo:
    """Parsed module plus the cross-cutting lookups every rule needs."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.aliases = self._collect_aliases(self.tree)
        self.suppressions = self._collect_suppressions(self.lines)
        # parent links let rules climb from any node to its enclosing scope
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    @staticmethod
    def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
        aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    @staticmethod
    def _collect_suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
        """Map 1-based line -> suppressed rule names.

        ``# tpulint: disable=rule-a,rule-b`` suppresses its own line; a
        comment-only line also suppresses the next line (for statements too
        long to carry a trailing comment).
        """
        out: Dict[int, Set[str]] = {}
        for i, text in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(i, set()).update(rules)
            if text.lstrip().startswith("#"):
                out.setdefault(i + 1, set()).update(rules)
        return out

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted path of a Name/Attribute chain, alias-expanded."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line, ())
        return rule in rules or "all" in rules


class Rule:
    """Base class; subclasses set ``name``/``description`` and implement
    ``check(module, jit, context) -> Iterator[Finding]``."""

    name: str = ""
    description: str = ""

    def check(self, module: ModuleInfo, jit, context: "RunContext") -> Iterator[Finding]:
        raise NotImplementedError


RULES: List[Rule] = []


def register(cls):
    RULES.append(cls())
    return cls


class RunContext:
    """State shared across all modules of one run (e.g. declared mesh axes)."""

    def __init__(self):
        self.declared_axes: Set[str] = set()


# canonical axis-declaration modules, seeded into every run (relative to
# --root) so linting a subtree still knows the full mesh vocabulary
AXIS_SOURCE_FILES = (
    "deepspeed_tpu/parallel/mesh.py",
    "deepspeed_tpu/parallel/topology.py",
)


def own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested function/lambda
    bodies (those are separate scopes, analyzed on their own when reachable).
    The nested def node itself IS yielded (decorators/defaults belong here).
    """
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in {"__pycache__", ".git", ".venv", "node_modules"})
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def analyze_source(source: str, path: str = "<string>",
                   context: Optional[RunContext] = None,
                   select: Optional[Set[str]] = None) -> List[Finding]:
    """Analyze one module's source. Standalone entry point used by tests;
    declared-axes come only from this module unless a context is passed."""
    from .jitgraph import JitGraph
    from .rules import collect_declared_axes

    context = context or RunContext()
    try:
        module = ModuleInfo(path, source)
    except SyntaxError as e:
        return [Finding("syntax-error", path, e.lineno or 0, e.offset or 0,
                        f"could not parse: {e.msg}")]
    context.declared_axes |= collect_declared_axes(module)
    jit = JitGraph(module)
    findings: List[Finding] = []
    for rule in RULES:
        if select and rule.name not in select:
            continue
        for f in rule.check(module, jit, context):
            if not module.suppressed(f.rule, f.line):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_paths(paths: Sequence[str], root: Optional[str] = None,
                  select: Optional[Set[str]] = None) -> List[Finding]:
    """Two-pass run over files/directories: first collect mesh-axis
    declarations everywhere, then apply the rules. ``root`` makes finding
    paths relative (stable baseline keys)."""
    from .jitgraph import JitGraph
    from .rules import collect_declared_axes

    root = root or os.getcwd()
    context = RunContext()
    for rel in AXIS_SOURCE_FILES:
        src = os.path.join(root, rel)
        if os.path.exists(src):
            try:
                with open(src, "r", encoding="utf-8") as fh:
                    context.declared_axes |= collect_declared_axes(
                        ModuleInfo(rel, fh.read()))
            except (SyntaxError, OSError):
                pass
    modules: List[ModuleInfo] = []
    findings: List[Finding] = []
    for fpath in iter_python_files(paths):
        rel = os.path.relpath(fpath, root).replace(os.sep, "/")
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                source = fh.read()
            modules.append(ModuleInfo(rel, source))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            msg = getattr(e, "msg", None) or str(e)
            findings.append(Finding("syntax-error", rel, getattr(e, "lineno", 0) or 0,
                                    getattr(e, "offset", 0) or 0,
                                    f"could not parse: {msg}"))
    for module in modules:
        context.declared_axes |= collect_declared_axes(module)
    for module in modules:
        jit = JitGraph(module)
        for rule in RULES:
            if select and rule.name not in select:
                continue
            for f in rule.check(module, jit, context):
                if not module.suppressed(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
