"""tpuaudit CLI — mirrors tpulint's gate semantics at the program level.

Usage::

    python -m tools.tpuaudit --config tools/tpuaudit/selftest_config.json \
        --baseline .tpuaudit-baseline.json
    python -m tools.tpuaudit --config audit.json --format json
    python -m tools.tpuaudit --config audit.json --baseline b.json --write-baseline

Exit status: 0 clean (or all findings baselined), 1 new findings or stale
baseline entries, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .core import run_audit


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpuaudit",
        description="JAX/TPU program-level audit: traces registered entry "
                    "points (jaxpr + StableHLO, no device execution) and "
                    "checks collectives, donation, callbacks, weak types "
                    "and baked constants.")
    parser.add_argument("--config", metavar="FILE", default=None,
                        help="JSON harness config; builds the train/pipeline/"
                             "inference engines so they register their entry "
                             "points (see tools/tpuaudit/harness.py)")
    parser.add_argument("--entries", metavar="NAMES", default=None,
                        help="comma-separated entry-point names to audit "
                             "(default: every registered entry)")
    parser.add_argument("--select", metavar="CHECKS", default=None,
                        help="comma-separated check names to run "
                             "(default: all)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", metavar="FILE", default=None,
                        help="JSON baseline of accepted findings; only "
                             "findings over the baselined counts fail, and "
                             "stale baseline entries error")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write the current findings to --baseline and "
                             "exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop stale baseline entries and ratchet "
                             "budgets down to current counts, then exit 0")
    parser.add_argument("--min-donation-bytes", type=int, default=None,
                        help="missed-donation reporting threshold (default "
                             "1MiB)")
    parser.add_argument("--max-const-bytes", type=int, default=None,
                        help="baked-constant reporting threshold (default "
                             "1MiB)")
    parser.add_argument("--no-compile", action="store_true",
                        help="skip host-side XLA compilation (faster, but "
                             "GSPMD-inserted collectives become invisible)")
    parser.add_argument("--devices", type=int, default=None,
                        help="virtual CPU device count (sets XLA_FLAGS; "
                             "must run before jax is imported)")
    parser.add_argument("--list-checks", action="store_true",
                        help="print the check registry and exit")
    parser.add_argument("--list-entries", action="store_true",
                        help="print the registered entry points and exit")
    return parser


def _setup_platform(devices: Optional[int]) -> None:
    """Force the CPU backend (the audit is host-only by design) before jax
    initializes; a no-op when jax is already imported (in-process callers)."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if devices and devices > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={devices}")


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    from .checks import CHECKS

    if args.list_checks:
        for check in CHECKS:
            print(f"{check.name}: {check.description}")
        return 0

    select = None
    if args.select:
        select = {c.strip() for c in args.select.split(",") if c.strip()}
        known = {c.name for c in CHECKS} | {"trace-error"}
        unknown = select - known
        if unknown:
            print(f"tpuaudit: unknown check(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    _setup_platform(args.devices)

    from .registry import get_entry_points

    if args.config:
        from . import harness

        try:
            harness.build_from_config(harness.load_config(args.config))
        except (OSError, json.JSONDecodeError, ValueError, KeyError) as e:
            print(f"tpuaudit: bad --config {args.config}: {e}",
                  file=sys.stderr)
            return 2

    try:
        names = ([n.strip() for n in args.entries.split(",") if n.strip()]
                 if args.entries else None)
        entries = get_entry_points(names)
    except KeyError as e:
        print(f"tpuaudit: {e.args[0]}", file=sys.stderr)
        return 2

    if args.list_entries:
        for ep in entries:
            exp = (sorted(ep.expected_collectives)
                   if ep.expected_collectives is not None else "unchecked")
            print(f"{ep.name}: expected_collectives={exp} "
                  f"donate={ep.donate_argnums} suppress={sorted(ep.suppress)}")
        return 0
    if not entries:
        print("tpuaudit: no entry points registered (pass --config, or "
              "construct the engines in-process first)", file=sys.stderr)
        return 2

    options = {}
    if args.min_donation_bytes is not None:
        options["min_donation_bytes"] = args.min_donation_bytes
    if args.max_const_bytes is not None:
        options["max_const_bytes"] = args.max_const_bytes
    if args.no_compile:
        options["compile"] = False

    findings = run_audit(entries, select=select, options=options)

    # Scope for stale-key detection: with no --entries filter, the whole
    # registry was audited — a baselined entry that is no longer registered
    # at all IS the rot this gate exists to catch, so every key is in scope.
    # An explicit --entries subset only judges those names.
    def in_scope(key: str) -> bool:
        entry, _, check = key.rpartition("::")
        if select is not None and check not in select:
            return False
        return names is None or entry in names

    return baseline_mod.gate_and_report(
        findings, tool="tpuaudit", fmt=args.format,
        baseline_path=args.baseline, write_baseline=args.write_baseline,
        prune_baseline=args.prune_baseline, in_scope=in_scope)


if __name__ == "__main__":
    sys.exit(main())
