"""tpuaudit checks — program-semantic diagnostics over a traced ``Program``.

Each check inspects what XLA will actually execute (avals, jaxpr equations,
StableHLO/compiled HLO text), never source text. All of them are findings an
AST linter structurally cannot produce.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Any, Dict, Iterator, List

import numpy as np

from .core import Finding, Program, collect_collectives

__all__ = ["Check", "CHECKS", "register"]

DEFAULT_MIN_DONATION_BYTES = 1 << 20   # ignore sub-MiB donation misses
DEFAULT_MAX_CONST_BYTES = 1 << 20      # flag baked constants over 1 MiB


class Check:
    name: str = ""
    description: str = ""

    def run(self, program: Program, options: Dict[str, Any]) -> Iterator[Finding]:
        raise NotImplementedError

    def _f(self, program: Program, message: str) -> Finding:
        return Finding(self.name, program.entry.name, message)


CHECKS: List[Check] = []


def register(cls):
    CHECKS.append(cls())
    return cls


def _npdtype(dt):
    """np.dtype or None for extended dtypes (typed PRNG keys etc.)."""
    try:
        return np.dtype(dt)
    except TypeError:
        return None


def _aval_key(aval):
    return (tuple(aval.shape), str(aval.dtype))


def _aval_bytes(aval) -> int:
    dt = _npdtype(getattr(aval, "dtype", None))
    if dt is None:
        return 0
    try:
        return int(math.prod(aval.shape)) * dt.itemsize
    except (TypeError, ValueError):
        return 0


def _mib(n: int) -> str:
    return f"{n / 2**20:.1f}MiB"


@register
class UnexpectedCollective(Check):
    """GSPMD silently inserts resharding collectives when shardings don't
    line up; an all-gather you didn't plan for is HBM + ICI you pay every
    step. Entries declare the kinds they expect; everything else fails."""

    name = "unexpected-collective"
    description = ("collective ops in the lowered/compiled program that the "
                   "entry point did not declare in expected_collectives")

    def run(self, program, options):
        expected = program.entry.expected_collectives
        if expected is None:          # entry opted out of collective auditing
            return
        found = collect_collectives(program.stablehlo, program.compiled_hlo)
        for kind in sorted(found):
            if kind not in expected:
                yield self._f(
                    program,
                    f"program contains {found[kind]}x {kind} but the entry "
                    f"point declares expected_collectives="
                    f"{sorted(expected)} — an undeclared reshard/collective "
                    "(check shardings or declare the collective)")


@register
class MissedDonation(Check):
    """Inputs that shape/dtype-match an output but were not donated: XLA must
    keep both buffers live, doubling HBM for that tensor (the train-state
    round-trip is the canonical case)."""

    name = "missed-donation"
    description = ("non-donated inputs whose shape+dtype matches an output "
                   "that no donated buffer already aliases")

    def run(self, program, options):
        threshold = int(options.get("min_donation_bytes",
                                    DEFAULT_MIN_DONATION_BYTES))
        out_pool = Counter(_aval_key(a) for a in program.out_avals)
        # donated inputs claim their aliases first
        for aval, donated in zip(program.in_avals, program.donated):
            if donated and out_pool[_aval_key(aval)] > 0:
                out_pool[_aval_key(aval)] -= 1
        by_arg: Dict[int, int] = {}
        for i, (aval, donated) in enumerate(zip(program.in_avals,
                                                program.donated)):
            if donated:
                continue
            key = _aval_key(aval)
            if out_pool[key] > 0:
                out_pool[key] -= 1
                arg = program.arg_of_input[i]
                by_arg[arg] = by_arg.get(arg, 0) + _aval_bytes(aval)
        for arg, nbytes in sorted(by_arg.items()):
            if nbytes >= threshold:
                yield self._f(
                    program,
                    f"argument {arg} holds {_mib(nbytes)} of leaves that "
                    "shape/dtype-match outputs but are not in donate_argnums "
                    "— the old and new buffers coexist in HBM (donate, or "
                    "suppress with the reason at the registration site)")


@register
class DeadDonation(Check):
    """Donated args that cannot alias any output: the donation frees nothing,
    silently — XLA just invalidates the buffer. Usually a stale
    donate_argnums after an output was dropped or re-shaped."""

    name = "dead-donation"
    description = ("donated arguments with no shape+dtype-compatible output "
                   "to alias")

    def run(self, program, options):
        out_pool = Counter(_aval_key(a) for a in program.out_avals)
        dead: Dict[int, List[str]] = {}
        live: Dict[int, int] = {}
        for i, (aval, donated) in enumerate(zip(program.in_avals,
                                                program.donated)):
            if not donated:
                continue
            arg = program.arg_of_input[i]
            key = _aval_key(aval)
            if out_pool[key] > 0:
                out_pool[key] -= 1
                live[arg] = live.get(arg, 0) + 1
            else:
                dead.setdefault(arg, []).append(program.in_labels[i])
        for arg, leaves in sorted(dead.items()):
            if live.get(arg):
                continue   # partially aliasing args are doing their job
            shown = ", ".join(leaves[:3]) + ("..." if len(leaves) > 3 else "")
            yield self._f(
                program,
                f"argument {arg} is donated but none of its {len(leaves)} "
                f"leaves ({shown}) matches any output shape+dtype — the "
                "donation aliases nothing and only invalidates the input")


@register
class HostCallback(Check):
    """pure_callback/io_callback/debug prints that survived into the lowered
    program stall the TPU pipeline on a host round-trip every invocation."""

    name = "host-callback-in-program"
    description = ("pure_callback / io_callback / debug_callback equations "
                   "in the traced program")

    def run(self, program, options):
        counts: Counter = Counter()
        for eqn in program.iter_eqns():
            name = eqn.primitive.name
            if "callback" in name:
                counts[name] += 1
        for prim, n in sorted(counts.items()):
            yield self._f(
                program,
                f"{n}x {prim} in the lowered program — each invocation is a "
                "device->host->device round-trip on the hot path (remove, or "
                "suppress at the registration site for intentional debugging)")


@register
class WeakTypeCapture(Check):
    """Python scalars traced as weak-typed args: the jit cache keys on
    (shape, dtype, weak_type), so any call site that sometimes passes a
    python float and sometimes an array/np scalar retraces the program — the
    classic steady-state-recompile the observability watchdog flags at
    runtime, caught statically here."""

    name = "weak-type-capture"
    description = "inputs traced as weak-typed scalars (python int/float args)"

    def run(self, program, options):
        for aval, label in zip(program.in_avals, program.in_labels):
            if getattr(aval, "weak_type", False):
                yield self._f(
                    program,
                    f"input {label} traced weak ({aval.dtype})"
                    " — pass jnp.asarray(x, dtype) at the call site so the "
                    "jit cache key is stable across python/numpy scalar types")


@register
class ImplicitPromotion(Check):
    """Dtype widening inside the program: any f64 means the program silently
    runs double precision (x64 leaked into a TPU-bound function); f64 avals
    also appear when python floats mix with x64-enabled tracing."""

    name = "implicit-promotion"
    description = "float64 values appearing anywhere in the traced program"

    def run(self, program, options):
        sites: Counter = Counter()
        for eqn in program.iter_eqns():
            for v in eqn.outvars:
                dt = _npdtype(getattr(getattr(v, "aval", None), "dtype", None))
                if dt is not None and dt == np.float64:
                    sites[eqn.primitive.name] += 1
        for aval, label in zip(program.in_avals, program.in_labels):
            if _npdtype(aval.dtype) == np.float64:
                yield self._f(
                    program,
                    f"input {label} is float64 — double precision on the "
                    "program boundary (cast at the call site)")
        if sites:
            top = ", ".join(f"{k} x{n}" for k, n in sites.most_common(3))
            yield self._f(
                program,
                f"{sum(sites.values())} float64 value(s) produced inside the "
                f"program ({top}) — f32/bf16 math is being promoted to "
                "double precision")


@register
class BakedConstant(Check):
    """Large arrays captured by closure become jaxpr constants: they are
    re-hashed on every jit cache lookup, baked into the executable, and
    re-transferred per compilation instead of living in donated/sharded
    argument buffers."""

    name = "baked-constant"
    description = "multi-MiB constants folded into the jaxpr (closure capture)"

    def run(self, program, options):
        threshold = int(options.get("max_const_bytes",
                                    DEFAULT_MAX_CONST_BYTES))
        for const in program.closed_jaxpr.consts:
            nbytes = int(getattr(const, "nbytes", 0) or 0)
            if nbytes > threshold:
                shape = tuple(getattr(const, "shape", ()))
                dtype = getattr(const, "dtype", "?")
                yield self._f(
                    program,
                    f"constant {shape} {dtype} ({_mib(nbytes)}) baked into "
                    "the jaxpr — pass it as an argument (sharded, donatable) "
                    "instead of closing over the array")
