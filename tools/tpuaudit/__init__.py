"""tpuaudit — trace-time program auditor for the jitted entry points.

Where tpulint reads SOURCE (AST), tpuaudit reads the PROGRAM: it traces
registered jitted callables abstractly (``jax.jit(...).trace`` on
``ShapeDtypeStruct`` trees — CPU-safe, no device execution), lowers them to
StableHLO, and optionally compiles them (still host-only) to see what GSPMD
actually inserted. The failure modes it covers structurally cannot appear in
an AST: resharding collectives, missed/dead buffer donation, host callbacks
that survived into the program, weak-type scalar capture, and multi-MiB
constants baked into the jaxpr.
"""

from .core import Finding, Program, audit_entry, run_audit
from .checks import CHECKS
from .registry import (EntryPoint, abstract_tree, clear_registry,
                       get_entry_points, register_entry_point)

__all__ = [
    "Finding", "Program", "audit_entry", "run_audit", "CHECKS",
    "EntryPoint", "abstract_tree", "clear_registry", "get_entry_points",
    "register_entry_point",
]
