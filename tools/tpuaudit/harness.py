"""Config-driven audit harness — builds engines so they self-register.

``python -m tools.tpuaudit --config audit.json`` drives this module: each
section constructs the corresponding engine (train / pipeline-parallel train /
inference) on the CPU mesh and calls its ``register_audit_entries`` hook; the
CLI then audits whatever landed in the registry. Engine construction
materialises (small) params — that is init, not step execution; the audited
programs themselves are traced abstractly.

Config shape (all sections optional)::

    {
      "train":    {"model": {"type": "simple", "hidden_dim": 10},
                   "config": {<deepspeed_tpu config dict>},
                   "batch": {"x": [[2, 10], "float32"],
                             "y": [[2, 1],  "float32"]}},
      "pipeline": {"model": {"type": "preset", "name": "tiny", "dtype": "float32"},
                   "config": {"parallel": {"pipeline_parallel_size": 2}, ...},
                   "seq_len": 16},
      "inference": {"model": {"type": "preset", "name": "tiny"},
                    "batch_size": 1, "prompt_len": 64, "max_new_tokens": 8},
      "serving":   {"model": {"type": "preset", "name": "tiny"},
                    "config": {"block_size": 16, "max_seqs": 4,
                               "max_model_len": 64, "prefill_chunk": 16}}
    }

``batch`` entries are ``name: [shape, dtype]`` pairs describing ONE microbatch
(the gas dim is added by the engine hook). Transformer models may omit
``batch``: token batches are synthesized from the model config.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

# Engines built by ``build_from_config``. Registration sites hold only a
# WEAKREF to their engine (the registry must never pin a replaced engine in a
# long-lived training process) — so the harness itself must keep the engines
# it constructed alive, or every entry goes stale before the analyzers run:
# exactly that happened between PR 3 and PR 7, where the audit gate silently
# audited only the two pipeline closures that survive by accident of cyclic
# references. CLI runs are short-lived, so pinning here is free; in-process
# callers (tests) release the engines with ``clear_keepalive()``.
_KEEPALIVE: List[Any] = []


def clear_keepalive() -> None:
    _KEEPALIVE.clear()


def _np_dtype(name: str):
    import numpy as np

    return np.dtype(name)


def _build_model(spec: Dict[str, Any]):
    kind = spec.get("type", "preset")
    if kind == "simple":
        from deepspeed_tpu.models import simple_model

        kw = {k: v for k, v in spec.items() if k != "type"}
        return simple_model(**kw)
    if kind == "preset":
        from deepspeed_tpu.models import create_model
        import jax.numpy as jnp

        kw = {k: v for k, v in spec.items() if k not in ("type", "name")}
        if isinstance(kw.get("dtype"), str):
            kw["dtype"] = jnp.dtype(kw["dtype"]).type
        return create_model(spec["name"], **kw)
    raise ValueError(f"unknown model type '{kind}' (simple | preset)")


def _micro_batch(section: Dict[str, Any], model, micro_size: int):
    """One microbatch of host zeros matching the declared (or synthesized)
    shapes — only shapes/dtypes reach the auditor."""
    import numpy as np

    spec = section.get("batch")
    if spec is not None:
        return {k: np.zeros(tuple(shape), _np_dtype(dtype))
                for k, (shape, dtype) in spec.items()}
    cfg = model.config
    if cfg is None:
        raise ValueError(
            "non-transformer models need an explicit 'batch' spec "
            "({name: [shape, dtype]}) in the audit config section")
    seq = int(section.get("seq_len", min(cfg.max_seq_len, 32)))
    return {"input_ids": np.zeros((micro_size, seq), np.int32)}


def run_section_train(section: Dict[str, Any],
                      prefix: str = "train") -> List[str]:
    import deepspeed_tpu

    model = _build_model(section.get("model", {"type": "simple"}))
    cfg = dict(section.get("config") or {})
    cfg.setdefault("train_micro_batch_size_per_gpu", 2)
    cfg.setdefault("optimizer", {"type": "adamw", "params": {"lr": 1e-3}})
    cfg.setdefault("steps_per_print", 10 ** 9)
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    _KEEPALIVE.append(engine)
    gb = engine.train_batch_size() // engine.gradient_accumulation_steps()
    micro = _micro_batch(section, model, gb)
    return engine.register_audit_entries(micro, prefix=prefix)


def run_section_inference(section: Dict[str, Any]) -> List[str]:
    from deepspeed_tpu.inference import init_inference

    spec = dict(section["model"])
    if spec.get("type", "preset") != "preset":
        raise ValueError("inference audit section needs a preset model "
                         "(the KV arena is sized from its config)")
    # init_inference derives dtype and max_seq_len itself
    overrides = {k: v for k, v in spec.items()
                 if k not in ("type", "name", "dtype", "max_seq_len")}
    kw = {k: section[k] for k in ("tensor_parallel", "expert_parallel",
                                  "dtype", "max_out_tokens")
          if k in section}
    # pass the preset NAME: init_inference builds the model with the
    # engine's compute dtype, keeping params/cache/program dtypes coherent
    engine = init_inference(model=spec["name"], **kw, **overrides)
    _KEEPALIVE.append(engine)
    return engine.register_audit_entries(
        batch_size=int(section.get("batch_size", 1)),
        prompt_len=int(section.get("prompt_len", 64)),
        max_new_tokens=int(section.get("max_new_tokens", 8)))


def run_section_serving(section: Dict[str, Any]) -> List[str]:
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.serving import init_serving

    spec = dict(section["model"])
    if spec.get("type", "preset") != "preset":
        raise ValueError("serving audit section needs a preset model "
                         "(the paged arena is sized from its config)")
    overrides = {k: v for k, v in spec.items()
                 if k not in ("type", "name", "dtype", "max_seq_len")}
    kw = {k: section[k] for k in ("tensor_parallel", "expert_parallel",
                                  "dtype") if k in section}
    scfg = ServingConfig.from_dict(section.get("config") or {})
    # "draft_model": "<preset>" turns the section speculative (the config
    # should set speculative.mode='draft') so the verify + draft-model
    # programs register and the audit/cost gates budget them
    if "draft_model" in section:
        kw["draft_model"] = section["draft_model"]
    engine = init_serving(model=spec["name"], serving_config=scfg,
                          **kw, **overrides)
    _KEEPALIVE.append(engine)
    # construction registered the entries; the explicit call returns their
    # names for the CLI (idempotent — latest registration wins)
    names = engine._register_audit_entries()
    if section.get("fleet"):
        # "fleet": true registers the prefill/decode KV-handoff program
        # pair (serving/kv_export + serving/kv_import) against this
        # engine's arena shapes, exactly as a disaggregated FleetRouter
        # does at construction — so the audit/cost gates budget them
        from deepspeed_tpu.serving.fleet.disagg import (
            ArenaHandoff, register_handoff_audit_entries)

        handoff = ArenaHandoff()
        _KEEPALIVE.append(handoff)
        names += register_handoff_audit_entries(engine, handoff)
    return names


def run_section_rlhf(section: Dict[str, Any]) -> List[str]:
    """Build a ``HybridEngine`` on the training mesh and trigger one flip,
    registering the ``rlhf/flip`` resharding program (under ZeRO-3 the
    program IS the fsdp→serving all-gather, so the audit's collective
    census and tpucost's bytes budget are exactly the flip's cost). The
    rollout-side device programs (``serving/score_chunk`` etc.) register
    through the ``serving`` section — one shape set, no duplicates."""
    from deepspeed_tpu.config.config import load_config
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.runtime.hybrid_engine import HybridEngine

    model = _build_model(section.get("model", {"type": "preset",
                                               "name": "tiny"}))
    cfg = dict(section.get("config") or {})
    cfg.setdefault("train_micro_batch_size_per_gpu", 2)
    cfg.setdefault("optimizer", {"type": "adamw", "params": {"lr": 1e-3}})
    cfg.setdefault("steps_per_print", 10 ** 9)
    # engine construction replaces the PROCESS-global ambient mesh, which
    # the pipeline section's lazily-synthesized entries still need at
    # trace time (their shard_map axes come from it) — restore it after
    prev_mesh = mesh_mod.get_mesh()
    try:
        engine = HybridEngine(
            model=model, config=load_config(cfg),
            max_out_tokens=int(section.get("max_out_tokens", 64)),
            inference_mesh="train")
        _KEEPALIVE.append(engine)
        engine.refresh_params()   # builds + registers the jitted flip
    finally:
        if prev_mesh is not None:
            mesh_mod.set_mesh(prev_mesh)
    return ["rlhf/flip"] if engine._flip_program is not None else []


def build_from_config(config: Dict[str, Any]) -> List[str]:
    """Build every engine the config names; returns the registered entry
    names (the registry keeps the entries for the CLI to audit)."""
    registered: List[str] = []
    for key in ("train", "pipeline"):     # pipeline IS a train engine with pp>1
        if key in config:
            registered += run_section_train(config[key], prefix=key)
    if "inference" in config:
        registered += run_section_inference(config["inference"])
    if "serving" in config:
        registered += run_section_serving(config["serving"])
    if "rlhf" in config:
        registered += run_section_rlhf(config["rlhf"])
    return registered


def load_config(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)
