"""tpuaudit baseline — identical semantics to tpulint's (count budgets per
``entry::check`` key, stale-key erroring, pruning); the implementation is
shared from ``tools.tpulint.baseline`` so the two gates can never drift."""

from __future__ import annotations

from typing import Dict, Sequence

from ..tpulint.baseline import (BASELINE_VERSION, counts_of,  # noqa: F401
                                gate_and_report, load, new_findings, pruned,
                                stale_keys, write_counts)
from ..tpulint import baseline as _shared


def write(path: str, findings: Sequence) -> None:
    _shared.write(path, findings, tool="tpuaudit")
