"""tpuaudit core — the Program model and the audit driver.

For every registered entry point the driver

1. evaluates the ``build`` thunk → ``(fn, args, kwargs)``;
2. ``jax.jit(fn).trace(*args)`` — abstract trace, no device math;
3. ``traced.lower()`` → StableHLO text (explicit collectives from shard_map
   bodies, donation/donor arg attributes);
4. optionally ``lowered.compile()`` — still host-only — because GSPMD inserts
   resharding collectives during PARTITIONING: the lowered module only carries
   sharding annotations, the compiled module carries the all-gathers you will
   actually pay for;
5. hands the resulting ``Program`` to every check (``checks.py``).

Findings mirror tpulint's shape (``key`` = ``entry::check`` is the baseline
bucket) so the two analyzers share baseline/CLI semantics.
"""

from __future__ import annotations

import contextlib
import dataclasses
import re
from collections import Counter
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .registry import COLLECTIVE_KINDS, EntryPoint

__all__ = ["Finding", "Program", "audit_entry", "run_audit",
            "collect_collectives", "resolve_mesh", "trace_entry",
            "iter_eqns_of"]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``key`` (entry::check) is the baseline bucket."""
    check: str
    entry: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.entry}::{self.check}"

    def render(self) -> str:
        return f"{self.entry}: {self.check}: {self.message}"

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Program:
    """Everything a check may inspect about one traced entry point."""

    entry: EntryPoint
    closed_jaxpr: Any                  # jax ClosedJaxpr
    in_avals: List[Any]                # flat input avals (trace order)
    out_avals: List[Any]               # flat output avals
    in_labels: List[str]               # "arg0['w']"-style path per input leaf
    arg_of_input: List[int]            # top-level argnum per input leaf (-1 unknown)
    donated: List[bool]                # per input leaf
    stablehlo: str
    compiled_hlo: Optional[str]

    def iter_eqns(self):
        """All equations, descending into sub-jaxprs (scan/cond/pjit/...)."""
        yield from iter_eqns_of(self.closed_jaxpr)


def iter_eqns_of(closed_jaxpr) -> Iterable[Any]:
    """All equations of a ClosedJaxpr, descending into sub-jaxprs — shared
    by the audit checks and tpucost's jaxpr op census."""
    seen: Set[int] = set()

    def walk(jaxpr):
        if id(jaxpr) in seen:
            return
        seen.add(id(jaxpr))
        for eqn in jaxpr.eqns:
            yield eqn
            for sub in _subjaxprs(eqn):
                yield from walk(sub)

    yield from walk(closed_jaxpr.jaxpr)


def _subjaxprs(eqn) -> Iterable[Any]:
    for v in eqn.params.values():
        for cand in (v if isinstance(v, (list, tuple)) else (v,)):
            jaxpr = getattr(cand, "jaxpr", None)
            if jaxpr is not None and hasattr(jaxpr, "eqns"):
                yield jaxpr
            elif hasattr(cand, "eqns"):
                yield cand


# -- collective census -------------------------------------------------------

# StableHLO spells kinds with underscores (`stablehlo.all_gather`); the
# post-optimization HLO uses dashes and may split ops into -start/-done pairs.
_STABLEHLO_RE = re.compile(
    r'stablehlo\.(all_gather|all_reduce|reduce_scatter|all_to_all|'
    r'collective_permute|collective_broadcast)\b')
_HLO_RE = re.compile(
    r'\b(all-gather|all-reduce|reduce-scatter|all-to-all|collective-permute|'
    r'collective-broadcast)(?:-start)?\(')


def collect_collectives(stablehlo: Optional[str],
                        compiled_hlo: Optional[str]) -> Dict[str, int]:
    """Collective kinds present in the program, canonical dashed names →
    occurrence count. The two texts are complementary: shard_map bodies put
    explicit collectives in the StableHLO; GSPMD resharding only shows up
    post-compile. An explicit collective appears in BOTH texts, so the
    per-kind count is the max over sources, not the sum."""
    lowered: Counter = Counter()
    compiled: Counter = Counter()
    if stablehlo:
        for m in _STABLEHLO_RE.finditer(stablehlo):
            lowered[m.group(1).replace("_", "-")] += 1
    if compiled_hlo:
        for m in _HLO_RE.finditer(compiled_hlo):
            compiled[m.group(1)] += 1
    counts = {k: max(lowered.get(k, 0), compiled.get(k, 0))
              for k in set(lowered) | set(compiled)}
    assert set(counts) <= set(COLLECTIVE_KINDS)
    return counts


# -- program construction ----------------------------------------------------


def _flat_labels(args: tuple, kwargs: dict) -> Tuple[List[str], List[int]]:
    """Flat leaf labels + owning top-level argnum, matching jit's flatten
    order ((args, kwargs) as one tree)."""
    import jax

    labels: List[str] = []
    argnums: List[int] = []
    for i, a in enumerate(args):
        for path, _ in jax.tree_util.tree_leaves_with_path(a):
            labels.append(f"arg{i}{jax.tree_util.keystr(path)}")
            argnums.append(i)
    for k in sorted(kwargs):
        for path, _ in jax.tree_util.tree_leaves_with_path(kwargs[k]):
            labels.append(f"{k}{jax.tree_util.keystr(path)}")
            argnums.append(-1)
    return labels, argnums


def resolve_mesh(ep: EntryPoint):
    """The entry's mesh: a Mesh, None, or a zero-arg resolver (registration
    sites that only know the mesh lazily); note jax.sharding.Mesh itself is
    callable (a ContextDecorator), so type-check before resolving."""
    import jax

    mesh = ep.mesh
    if mesh is not None and not isinstance(mesh, jax.sharding.Mesh) \
            and callable(mesh):
        mesh = mesh()
    return mesh


def trace_entry(ep: EntryPoint, do_compile: Optional[bool] = None
                ) -> Tuple[Any, Any, Any, tuple, dict]:
    """Trace + lower (+ compile) one entry point under its mesh; returns
    ``(traced, lowered, compiled-or-None, args, kwargs)``. The shared front
    half of ``build_program``, also used by ``tools.tpucost`` — which needs
    the live ``Lowered``/``Compiled`` stages for XLA's cost and memory
    analysis, not just their text."""
    import jax

    fn, args, kwargs = ep.build()
    if not hasattr(fn, "trace"):      # plain python callable
        fn = jax.jit(fn, donate_argnums=ep.donate_argnums)

    mesh = resolve_mesh(ep)
    ctx = contextlib.nullcontext()
    if mesh is not None:
        from deepspeed_tpu.parallel import mesh as mesh_mod

        ctx = mesh_mod.ambient(mesh)
    with ctx:
        traced = fn.trace(*args, **kwargs)
        lowered = traced.lower()
        compiled = None
        if do_compile if do_compile is not None else ep.compile:
            compiled = lowered.compile()
    return traced, lowered, compiled, args, kwargs


def build_program(ep: EntryPoint, do_compile: Optional[bool] = None) -> Program:
    """Trace + lower (+ compile) one entry point. Raises on trace failure —
    ``audit_entry`` turns that into a ``trace-error`` finding."""
    traced, lowered, compiled, args, kwargs = trace_entry(ep, do_compile)
    stablehlo = lowered.as_text()
    compiled_hlo = compiled.as_text() if compiled is not None else None

    closed = traced.jaxpr
    labels, argnums = _flat_labels(args, kwargs)
    in_avals = list(closed.in_avals)
    if len(labels) != len(in_avals):
        # structure mismatch (e.g. a fn with captured tracers) — keep going
        # with positional labels; donation mapping is disabled
        labels = [f"in{i}" for i in range(len(in_avals))]
        argnums = [-1] * len(in_avals)
    donate = set(ep.donate_argnums)
    donated = [a in donate for a in argnums]
    return Program(entry=ep, closed_jaxpr=closed, in_avals=in_avals,
                   out_avals=list(closed.out_avals), in_labels=labels,
                   arg_of_input=argnums, donated=donated,
                   stablehlo=stablehlo, compiled_hlo=compiled_hlo)


# -- driver ------------------------------------------------------------------


def audit_entry(ep: EntryPoint, select: Optional[Set[str]] = None,
                options: Optional[Dict[str, Any]] = None) -> List[Finding]:
    from .checks import CHECKS

    from .registry import StaleEntryError

    try:
        program = build_program(
            ep, do_compile=None if options is None
            else options.get("compile"))
    except StaleEntryError:
        return []   # the owning engine is gone; nothing to audit
    except Exception as e:                        # noqa: BLE001 — any trace
        # failure is itself a reportable (and baselinable) audit outcome
        msg = f"{type(e).__name__}: {e}"
        return [Finding("trace-error", ep.name,
                        f"could not trace/lower entry point: {msg[:500]}")]
    findings: List[Finding] = []
    for check in CHECKS:
        if select is not None and check.name not in select:
            continue
        if check.name in ep.suppress:
            continue
        findings.extend(check.run(program, options or {}))
    return findings


def run_audit(entries: Sequence[EntryPoint],
              select: Optional[Set[str]] = None,
              options: Optional[Dict[str, Any]] = None,
              publish_metrics: bool = True) -> List[Finding]:
    """Audit entry points and (by default) publish per-(entry, check) finding
    counters into the observability MetricsRegistry, so a run that also dumps
    metrics JSONL shows audit regressions in ``observability report``."""
    findings: List[Finding] = []
    for ep in entries:
        findings.extend(audit_entry(ep, select=select, options=options))
    findings.sort(key=lambda f: (f.entry, f.check, f.message))
    if publish_metrics:
        _publish(entries, findings)
    return findings


def _publish(entries: Sequence[EntryPoint], findings: Sequence[Finding]) -> None:
    try:
        from deepspeed_tpu.observability import get_registry
    except ImportError:
        return
    reg = get_registry()
    reg.counter("tpuaudit/entries_audited",
                help="entry points traced by tpuaudit").inc(len(entries))
    counter = reg.counter("tpuaudit/findings",
                          help="tpuaudit findings per entry point and check")
    for f in findings:
        counter.inc(entry=f.entry, check=f.check)
