"""Entry-point registry — what the auditor audits.

Each engine layer registers its jitted callables here (guarded imports, so a
``deepspeed_tpu`` deployed without the ``tools/`` tree keeps working) together
with the *declared* contract the checks verify the program against:

* ``expected_collectives`` — the collective kinds this program is ALLOWED to
  contain. Anything else in the lowered/compiled program is a GSPMD-inserted
  reshard the author didn't plan for (the unexpected-collective check).
* ``donate_argnums`` — what the jit call actually donated; the donation checks
  compare it against what COULD alias.
* ``suppress`` — check names this entry opts out of, with the reason kept at
  the registration site (the program-level analog of tpulint's inline
  ``# tpulint: disable=...``).

Registration is cheap (a dataclass in a dict; jax is only imported when a
``ShapeDtypeStruct`` tree is built) and idempotent by name — engines re-register
when they re-specialize a step, and the newest program wins.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

# canonical (dashed) collective kind names; both the StableHLO op spelling
# (underscores) and the post-optimization HLO spelling (dashes) normalize here
COLLECTIVE_KINDS = (
    "all-gather", "all-reduce", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)


@dataclasses.dataclass
class EntryPoint:
    """One auditable program: a builder returning ``(fn, args, kwargs)`` where
    ``fn`` is jit-wrapped (or plain — the auditor wraps it) and ``args`` are
    abstract (``ShapeDtypeStruct`` trees) or concrete arrays (only their
    shape/dtype/sharding is used; nothing executes)."""

    name: str
    build: Callable[[], Tuple[Callable, tuple, dict]]
    expected_collectives: Optional[FrozenSet[str]] = frozenset()
    donate_argnums: Tuple[int, ...] = ()
    suppress: FrozenSet[str] = frozenset()
    mesh: Any = None          # activated (ambient) around trace/lower/compile
    compile: bool = True      # also compile (host-only) to see GSPMD's output
    tags: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.expected_collectives is not None:
            unknown = set(self.expected_collectives) - set(COLLECTIVE_KINDS)
            if unknown:
                raise ValueError(
                    f"entry '{self.name}': unknown collective kind(s) "
                    f"{sorted(unknown)} (valid: {list(COLLECTIVE_KINDS)})")
            self.expected_collectives = frozenset(self.expected_collectives)
        self.suppress = frozenset(self.suppress)
        self.donate_argnums = tuple(self.donate_argnums)


class StaleEntryError(RuntimeError):
    """Raised by a ``build`` thunk whose owning engine has been garbage
    collected. Registration sites hold only a weakref to their engine (the
    registry must never pin params/executables of a replaced engine in a
    long-lived process); the auditor silently skips stale entries."""


_ENTRIES: Dict[str, EntryPoint] = {}


def register_entry_point(name: str,
                         build: Optional[Callable] = None,
                         fn: Optional[Callable] = None,
                         args: Optional[tuple] = None,
                         kwargs: Optional[dict] = None,
                         **opts: Any) -> EntryPoint:
    """Register (or replace) an entry point. Pass either a ``build`` thunk —
    evaluated lazily at audit time, so registration never traces — or a
    ready ``fn`` + ``args`` pair."""
    if build is None:
        if fn is None or args is None:
            raise ValueError("register_entry_point needs build= or fn=+args=")
        frozen_fn, frozen_args, frozen_kwargs = fn, tuple(args), dict(kwargs or {})
        build = lambda: (frozen_fn, frozen_args, frozen_kwargs)
    ep = EntryPoint(name=name, build=build, **opts)
    _ENTRIES[name] = ep
    return ep


def get_entry_points(names: Optional[List[str]] = None) -> List[EntryPoint]:
    if names is None:
        return list(_ENTRIES.values())
    missing = [n for n in names if n not in _ENTRIES]
    if missing:
        raise KeyError(f"unregistered entry point(s): {', '.join(missing)}")
    return [_ENTRIES[n] for n in names]


def clear_registry() -> None:
    _ENTRIES.clear()


def abstract_tree(tree: Any) -> Any:
    """Concrete (or mixed) pytree → ``ShapeDtypeStruct`` tree, preserving
    shardings where leaves carry them. The registration-site helper: engines
    hand the auditor shapes, never live buffers."""
    import jax

    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if isinstance(x, (bool, int, float, complex)):
            return x  # keep python scalars AS scalars — weak types must trace
        sharding = getattr(x, "sharding", None)
        if not isinstance(sharding, jax.sharding.NamedSharding):
            # single-device/committed shardings of stray host scalars would
            # conflict with the mesh-placed arguments at trace time; only
            # mesh shardings carry audit-relevant information
            sharding = None
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    return jax.tree.map(one, tree)


def abstract_with_shardings(tree: Any, shardings: Any) -> Any:
    """Host-array pytree + matching sharding tree → ``ShapeDtypeStruct``
    tree (engines compute batch shardings separately from the batch data)."""
    import jax

    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        tree, shardings)
