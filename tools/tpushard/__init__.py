"""tpushard — whole-program sharding analyzer.

The fourth static analyzer: tpulint reads the source, tpuaudit the program
semantics, tpucost the program cost — tpushard reads the program LAYOUT.
For every registered entry point it lowers the program host-side and checks
the actual per-parameter / per-output shardings against the placement the
logical-axis rule registry (``deepspeed_tpu/parallel/rules.py``) derives for
the entry's declared policy.
"""

from .core import (EntryReport, analyze_entry, canonical_hash,  # noqa: F401
                   run_shard)
