"""tpushard core — actual vs registry-derived layout, per entry point.

For every tpuaudit entry point carrying a ``tags["shard"]`` contract (see
``deepspeed_tpu.parallel.rules.shard_tag``) the analyzer

1. traces/lowers (and, where the entry allows, compiles) the program
   host-side via tpuaudit's ``trace_entry`` — no device math;
2. reads the ACTUAL sharding of every parameter leaf (the compiled
   executable's ``input_shardings`` when available — what XLA will really
   run — else the registration-site ``ShapeDtypeStruct`` shardings);
3. recomputes the EXPECTED placement from the rule registry: the tag's
   policy resolved over the model's logical-axis tree;
4. reports four finding classes:

   * ``rule-violation``       — a leaf's actual sharding is not equivalent
     to what the registry derives for it;
   * ``implicit-reshard``     — GSPMD inserted collective kinds outside the
     entry's declared set WHILE rule violations exist: the cost of the
     mismatch, attributed to the mismatched operands (without violations
     this stays tpuaudit's ``unexpected-collective`` — no double report);
   * ``cross-program-mismatch`` — the same logical param is sharded
     differently in two entries of one ``group`` (entries exchanging live
     buffers: train↔eval, prefill↔decode↔verify, the RLHF flip's target vs
     the serving programs), or the KV-handoff export's output buffers do
     not land exactly like the import's staging args;
   * ``replication-waste``    — a >1 MiB buffer is fully replicated where
     the rules map an axis; priced as actual bytes minus the expected
     per-device shard size.

Findings reuse tpuaudit's shape (``key`` = ``entry::check``) so the gate,
baseline and CLI semantics are shared via ``tools.tpulint.baseline``.

Equivalence uses ``Sharding.is_equivalent_to(other, ndim)``: it compares
across distinct mesh objects and normalizes size-1 mesh axes (``P('model')``
over a 1-wide model axis IS replication), so a 1-device debug mesh never
false-positives.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..tpuaudit.core import Finding, collect_collectives, resolve_mesh, \
    trace_entry
from ..tpuaudit.registry import EntryPoint, StaleEntryError

__all__ = ["EntryReport", "analyze_entry", "canonical_hash", "run_shard"]

REPLICATION_WASTE_MIN_BYTES = 1 << 20   # 1 MiB: below this, replication is
                                        # a latency win, not a memory bug

# compiled-HLO canonicalization: the raw text embeds source-location
# metadata (file/line of every op), so ANY refactor that shifts lines
# changes the raw hash. Stripping `metadata={...}` and collapsing
# whitespace leaves exactly the computation + layout — the thing the
# rule-registry migration must preserve bit-for-bit.
_METADATA_RE = re.compile(r",?\s*metadata=\{[^}]*\}")
_WS_RE = re.compile(r"\s+")


def canonical_hash(hlo_text: str) -> str:
    """Position-independent hash of a compiled-HLO text (16 hex chars)."""
    text = _METADATA_RE.sub("", hlo_text)
    text = _WS_RE.sub(" ", text).strip()
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:16]


@dataclasses.dataclass
class EntryReport:
    """Per-entry coverage/cost stats for the CLI table and the metrics."""

    entry: str
    policy: Optional[str] = None        # None: handoff-only or untagged
    group: Optional[str] = None
    params_total: int = 0               # leaves the contract covers
    params_checked: int = 0             # leaves with a known actual sharding
    rule_violations: int = 0
    reshard_collectives: int = 0        # occurrences of undeclared kinds
    replicated_bytes: int = 0           # waste priced by replication-waste
    program_hash: Optional[str] = None  # canonical compiled-HLO hash

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# -- per-leaf comparison helpers ---------------------------------------------


def _flat_with_labels(tree: Any) -> List[Tuple[str, Any]]:
    import jax

    return [(jax.tree_util.keystr(path), leaf)
            for path, leaf in jax.tree_util.tree_leaves_with_path(tree)]


def _spec_leaves(specs: Any) -> List[Any]:
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))


def _sharding_of(leaf: Any) -> Optional[Any]:
    if hasattr(leaf, "is_equivalent_to"):
        return leaf                      # already a Sharding leaf
    s = getattr(leaf, "sharding", None)
    return s if s is not None and hasattr(s, "is_equivalent_to") else None


def _mesh_of_tree(tree: Any) -> Optional[Any]:
    """The mesh implied by a tree of actual shardings — the first
    NamedSharding leaf's. Output-contract entries (the RLHF flip) land on a
    mesh that is NOT the trace mesh, and the tag cannot carry the Mesh
    object itself: everything in ``ep.tags`` must stay JSON-serializable
    (crash-bundle fingerprints, the analyzers' ``--format json``)."""
    import jax

    for leaf in jax.tree.leaves(tree):
        mesh = getattr(_sharding_of(leaf), "mesh", None)
        if mesh is not None:
            return mesh
    return None


def _describe(sharding: Any) -> str:
    spec = getattr(sharding, "spec", None)
    if spec is not None:
        return str(spec)
    if getattr(sharding, "is_fully_replicated", False):
        return "replicated"
    return str(sharding)


def _nbytes(leaf: Any) -> int:
    size = 1
    for s in getattr(leaf, "shape", ()):
        size *= int(s)
    dtype = getattr(leaf, "dtype", None)
    return size * (dtype.itemsize if dtype is not None else 1)


def _check_tree(entry: str, side: str, policy_name: str, mesh: Any,
                sds_tree: Any, actual_tree: Any, expected_specs: Any,
                findings: List[Finding], report: EntryReport,
                group_params: Optional[Dict[str, List]] = None,
                group: Optional[str] = None) -> None:
    """Compare one (params-or-outputs) tree leaf-by-leaf against the
    registry-derived specs; append rule-violation / replication-waste
    findings and record shardings for the cross-program pass."""
    from jax.sharding import NamedSharding

    labelled = _flat_with_labels(sds_tree)
    actuals = [_sharding_of(x) for _, x in _flat_with_labels(actual_tree)] \
        if actual_tree is not None else [None] * len(labelled)
    specs = _spec_leaves(expected_specs)
    if not (len(labelled) == len(actuals) == len(specs)):
        findings.append(Finding(
            "trace-error", entry,
            f"{side} tree/spec arity mismatch: {len(labelled)} leaves, "
            f"{len(actuals)} shardings, {len(specs)} specs"))
        return

    for (label, sds), actual, spec in zip(labelled, actuals, specs):
        report.params_total += 1
        shape = tuple(getattr(sds, "shape", ()))
        expected = NamedSharding(mesh, spec)
        if actual is None:
            continue    # registration site carried no placement: uncheckable
        report.params_checked += 1
        if group_params is not None and group is not None:
            group_params.setdefault((group, label), []).append(
                (entry, actual, sds))
        try:
            ok = actual.is_equivalent_to(expected, len(shape))
        except (TypeError, ValueError) as e:
            findings.append(Finding(
                "rule-violation", entry,
                f"{side} {label}: cannot compare actual {_describe(actual)} "
                f"with expected {spec} (policy {policy_name!r}): {e}"))
            report.rule_violations += 1
            continue
        if not ok:
            report.rule_violations += 1
            findings.append(Finding(
                "rule-violation", entry,
                f"{side} {label}: expected {spec} (policy {policy_name!r}), "
                f"actual {_describe(actual)}"))
        nbytes = _nbytes(sds)
        if (nbytes >= REPLICATION_WASTE_MIN_BYTES
                and getattr(actual, "is_fully_replicated", False)
                and not expected.is_fully_replicated):
            shard_elems = 1
            for s in expected.shard_shape(shape):
                shard_elems *= int(s)
            dtype = getattr(sds, "dtype", None)
            shard_bytes = shard_elems * (dtype.itemsize if dtype is not None
                                         else 1)
            waste = nbytes - shard_bytes
            report.replicated_bytes += waste
            findings.append(Finding(
                "replication-waste", entry,
                f"{side} {label}: {nbytes:,} B fully replicated where the "
                f"rules map {spec} ({waste:,} B/device recoverable)"))


# -- single-entry analysis ---------------------------------------------------


def analyze_entry(ep: EntryPoint,
                  rule_overrides: Optional[Dict[str, Any]] = None,
                  group_params: Optional[Dict[str, List]] = None,
                  handoff_sides: Optional[Dict[str, Dict]] = None,
                  ) -> Tuple[List[Finding], Optional[EntryReport]]:
    """Analyze one entry point. Returns ``(findings, report)``; report is
    None for entries with neither a ``shard`` nor a ``handoff`` tag (no
    contract to audit — e.g. programs that take no parameters).

    ``rule_overrides`` remaps logical axes on the EXPECTATION side only —
    the fault-injection seam the selftest drives (a wrong rule must produce
    a named rule-violation and fail the gate).
    """
    from deepspeed_tpu.parallel.rules import get_policy

    shard = ep.tags.get("shard")
    handoff = ep.tags.get("handoff")
    if shard is None and handoff is None:
        return [], None

    findings: List[Finding] = []
    report = EntryReport(entry=ep.name,
                         policy=shard.get("policy") if shard else None,
                         group=shard.get("group") if shard else None)
    try:
        traced, lowered, compiled, args, kwargs = trace_entry(ep)
    except StaleEntryError:
        return [], None
    except Exception as e:                 # noqa: BLE001 — reportable outcome
        msg = f"{type(e).__name__}: {e}"
        findings.append(Finding(
            "trace-error", ep.name,
            f"could not trace/lower entry point: {msg[:500]}"))
        return findings, report

    if compiled is not None:
        report.program_hash = canonical_hash(compiled.as_text())

    mesh = resolve_mesh(ep)

    if shard is not None and mesh is not None:
        parg = shard.get("params_arg", 0)
        params_sds = args[parg]
        policy = get_policy(shard["policy"])
        in_shardings = None
        if compiled is not None:
            try:
                in_shardings = compiled.input_shardings[0][parg]
            except Exception:       # noqa: BLE001 — fall back to the SDS tree
                in_shardings = None
        actual_in = in_shardings if in_shardings is not None else params_sds

        if shard.get("check_output"):
            # output-contract entry (the RLHF flip): the policy binds the
            # OUTPUT tree, resolved on the target mesh (read off the actual
            # output shardings — the tag stays JSON-serializable); the input
            # side is checked against the nested ``source`` policy
            out_specs = policy.param_specs(
                params_sds, shard["axes"],
                expert_parallel=shard.get("expert_parallel", False),
                fsdp_min_size=shard.get("fsdp_min_size"),
                rule_overrides=rule_overrides)
            actual_out = (compiled.output_shardings if compiled is not None
                          else None)
            out_mesh = _mesh_of_tree(actual_out) or mesh
            _check_tree(ep.name, "output", shard["policy"], out_mesh,
                        params_sds, actual_out, out_specs, findings, report,
                        group_params=group_params, group=shard.get("group"))
            source = shard.get("source")
            if source is not None:
                src_policy = get_policy(source["policy"])
                src_specs = src_policy.param_specs(
                    params_sds, shard["axes"],
                    expert_parallel=shard.get("expert_parallel", False),
                    fsdp_min_size=source.get("fsdp_min_size"),
                    rule_overrides=rule_overrides)
                _check_tree(ep.name, "param", source["policy"], mesh,
                            params_sds, actual_in, src_specs, findings,
                            report)
        else:
            specs = policy.param_specs(
                params_sds, shard["axes"],
                expert_parallel=shard.get("expert_parallel", False),
                fsdp_min_size=shard.get("fsdp_min_size"),
                rule_overrides=rule_overrides)
            _check_tree(ep.name, "param", shard["policy"], mesh, params_sds,
                        actual_in, specs, findings, report,
                        group_params=group_params, group=shard.get("group"))

        # implicit-reshard: undeclared collective kinds coexisting with rule
        # violations — the GSPMD cost of the mismatch. Without violations
        # this is tpuaudit's unexpected-collective; we do not double-report.
        if report.rule_violations and ep.expected_collectives is not None:
            counts = collect_collectives(
                lowered.as_text(),
                compiled.as_text() if compiled is not None else None)
            extra = {k: n for k, n in counts.items()
                     if k not in ep.expected_collectives}
            if extra:
                report.reshard_collectives = sum(extra.values())
                kinds = ", ".join(f"{k}×{n}" for k, n in sorted(extra.items()))
                findings.append(Finding(
                    "implicit-reshard", ep.name,
                    f"GSPMD inserted undeclared collectives ({kinds}) while "
                    f"{report.rule_violations} param(s) violate the "
                    f"{shard['policy']!r} rules — the reshard is the price "
                    f"of the mismatched operands"))

    if handoff is not None and handoff_sides is not None:
        side: Dict[str, Any] = {"entry": ep.name, "mesh": mesh}
        if handoff.get("role") == "export":
            side["shardings"] = (list(compiled.output_shardings)
                                 if compiled is not None else None)
            side["avals"] = list(traced.jaxpr.out_avals)
        else:
            buf_args = tuple(handoff.get("buffer_args", ()))
            shardings, avals = [], []
            for i in buf_args:
                avals.append(args[i])
                s = None
                if compiled is not None:
                    try:
                        s = compiled.input_shardings[0][i]
                    except Exception:   # noqa: BLE001
                        s = _sharding_of(args[i])
                shardings.append(s)
            side["shardings"] = shardings
            side["avals"] = avals
        handoff_sides[handoff.get("role", "?")] = side

    return findings, report


def _check_handoff(handoff_sides: Dict[str, Dict],
                   findings: List[Finding]) -> None:
    """KV-handoff geometry: the export program's output buffers must be
    laid out exactly like the import program's staging-buffer args — a
    mismatch means every migrated request's KV reshards mid-flight (the
    runtime twin is ``HandoffGeometryError``)."""
    exp, imp = handoff_sides.get("export"), handoff_sides.get("import")
    if not exp or not imp:
        return
    e_sh, i_sh = exp.get("shardings"), imp.get("shardings")
    e_av, i_av = exp.get("avals", []), imp.get("avals", [])
    if e_sh is None or i_sh is None:
        return
    if len(e_sh) != len(i_sh) or len(e_av) != len(i_av):
        findings.append(Finding(
            "cross-program-mismatch", exp["entry"],
            f"handoff arity mismatch: export produces {len(e_sh)} "
            f"buffer(s), import stages {len(i_sh)}"))
        return
    for k, (ea, ia, es, isx) in enumerate(zip(e_av, i_av, e_sh, i_sh)):
        e_shape = tuple(getattr(ea, "shape", ()))
        i_shape = tuple(getattr(ia, "shape", ()))
        if e_shape != i_shape or getattr(ea, "dtype", None) != getattr(
                ia, "dtype", None):
            findings.append(Finding(
                "cross-program-mismatch", exp["entry"],
                f"handoff buffer {k}: export emits "
                f"{e_shape}/{getattr(ea, 'dtype', '?')}, import expects "
                f"{i_shape}/{getattr(ia, 'dtype', '?')}"))
            continue
        if es is None or isx is None:
            continue
        try:
            ok = es.is_equivalent_to(isx, len(e_shape))
        except (TypeError, ValueError):
            ok = False
        if not ok:
            findings.append(Finding(
                "cross-program-mismatch", exp["entry"],
                f"handoff buffer {k}: export lands {_describe(es)} but "
                f"{imp['entry']} stages {_describe(isx)} — the fleet would "
                f"reshard every migrated request's KV"))


def _check_groups(group_params: Dict[Tuple[str, str], List],
                  findings: List[Finding]) -> None:
    """Same logical param, different sharding, inside one buffer-exchange
    group. Entries only compare when their leaf shapes/dtypes AND mesh
    geometry (axis names + sizes) agree — the precondition for actually
    exchanging live buffers; disjoint harness engines that merely share a
    group name never cross-fire."""
    def mesh_sig(sh):
        m = getattr(sh, "mesh", None)
        if m is None:
            return None
        return (tuple(m.axis_names), tuple(m.devices.shape))

    for (group, label), uses in sorted(group_params.items()):
        if len(uses) < 2:
            continue
        ref_entry, ref_sh, ref_sds = uses[0]
        for entry, sh, sds in uses[1:]:
            if (tuple(getattr(sds, "shape", ())) !=
                    tuple(getattr(ref_sds, "shape", ()))
                    or getattr(sds, "dtype", None) !=
                    getattr(ref_sds, "dtype", None)):
                continue
            sig_a, sig_b = mesh_sig(ref_sh), mesh_sig(sh)
            if sig_a is not None and sig_b is not None and sig_a != sig_b:
                continue
            ndim = len(getattr(sds, "shape", ()))
            try:
                ok = sh.is_equivalent_to(ref_sh, ndim)
            except (TypeError, ValueError):
                ok = False
            if not ok:
                findings.append(Finding(
                    "cross-program-mismatch", entry,
                    f"param {label}: sharded {_describe(sh)} here but "
                    f"{_describe(ref_sh)} in {ref_entry} (group "
                    f"{group!r}) — exchanging this buffer reshards it"))


# -- driver ------------------------------------------------------------------


def run_shard(entries: Sequence[EntryPoint],
              rule_overrides: Optional[Dict[str, Any]] = None,
              publish_metrics: bool = True,
              ) -> Tuple[List[Finding], List[EntryReport]]:
    """Analyze every entry; returns sorted findings + per-entry reports
    (reports only for entries carrying a layout contract)."""
    findings: List[Finding] = []
    reports: List[EntryReport] = []
    group_params: Dict[Tuple[str, str], List] = {}
    handoff_sides: Dict[str, Dict] = {}
    for ep in entries:
        fs, report = analyze_entry(ep, rule_overrides=rule_overrides,
                                   group_params=group_params,
                                   handoff_sides=handoff_sides)
        findings.extend(fs)
        if report is not None:
            reports.append(report)
    _check_handoff(handoff_sides, findings)
    _check_groups(group_params, findings)
    findings.sort(key=lambda f: (f.entry, f.check, f.message))
    if publish_metrics:
        _publish(reports, findings)
    return findings, reports


def _publish(reports: Sequence[EntryReport],
             findings: Sequence[Finding]) -> None:
    try:
        from deepspeed_tpu.observability import get_registry
    except ImportError:
        return
    reg = get_registry()
    reg.counter("tpushard/entries_analyzed",
                help="entry points with a layout contract analyzed by "
                     "tpushard").inc(len(reports))
    counter = reg.counter("tpushard/findings",
                          help="tpushard findings per entry point and check")
    for f in findings:
        counter.inc(entry=f.entry, check=f.check)
    for r in reports:
        for metric in ("params_total", "params_checked", "rule_violations",
                       "reshard_collectives", "replicated_bytes"):
            reg.gauge(f"tpushard/{r.entry}/{metric}").set(
                getattr(r, metric))
