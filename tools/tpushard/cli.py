"""tpushard CLI — the static sharding gate.

Usage::

    # gate run (what CI does): selftest engines vs the committed baseline
    python -m tools.tpushard --config tools/tpuaudit/selftest_config.json

    python -m tools.tpushard --config c.json --format json
    python -m tools.tpushard --config c.json --baseline b.json --write-baseline
    python -m tools.tpushard --config c.json --override-rule vocab=data

Shares the tpuaudit registry + harness (one ``--config`` builds the engines
for all analyzers) and the tpulint/tpuaudit/tpucost gate semantics: exit 0
clean, 1 new findings or stale baseline entries, 2 usage error.
``--baseline`` defaults to the committed ``.tpushard-baseline.json`` when it
exists, so the bare gate command needs no flags. ``--override-rule`` remaps a
logical axis on the EXPECTATION side only — the fault-injection seam: a
deliberately wrong rule must surface as named rule-violations and exit 1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from ..tpulint.baseline import gate_and_report
from .core import EntryReport, run_shard

DEFAULT_BASELINE = ".tpushard-baseline.json"


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpushard",
        description="Whole-program sharding analyzer: lowers the registered "
                    "entry points host-side (no TPU) and checks every "
                    "parameter/output placement against the logical-axis "
                    "rule registry (deepspeed_tpu/parallel/rules.py).")
    parser.add_argument("--config", metavar="FILE", default=None,
                        help="JSON harness config (same file tpuaudit uses); "
                             "builds the engines so they register their "
                             "entry points")
    parser.add_argument("--entries", metavar="NAMES", default=None,
                        help="comma-separated entry-point names "
                             "(default: every registered entry)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", metavar="FILE", default=None,
                        help=f"baseline of accepted findings (default: "
                             f"{DEFAULT_BASELINE} when it exists)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write the current findings to --baseline and "
                             "exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop stale baseline keys and ratchet budgets "
                             "down to current counts, then exit 0")
    parser.add_argument("--override-rule", metavar="AXIS=MESH_AXIS",
                        action="append", default=[],
                        help="remap one logical axis in the EXPECTED rules "
                             "(fault injection; repeatable; MESH_AXIS of "
                             "'none' clears the mapping)")
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU device count (default 8, the "
                             "tier-1 mesh; must run before jax imports)")
    parser.add_argument("--metrics-jsonl", metavar="FILE", default=None,
                        help="also dump the tpushard/* metrics to a JSONL "
                             "(readable by 'observability report')")
    parser.add_argument("--list-entries", action="store_true",
                        help="print the registered entry points and exit")
    return parser


def _parse_overrides(items: List[str]) -> Dict[str, Optional[str]]:
    out: Dict[str, Optional[str]] = {}
    for item in items:
        axis, sep, mesh_axis = item.partition("=")
        if not sep or not axis:
            raise ValueError(f"--override-rule wants AXIS=MESH_AXIS, "
                             f"got {item!r}")
        out[axis.strip()] = (None if mesh_axis.strip().lower() == "none"
                             else mesh_axis.strip())
    return out


def _table(reports: List[EntryReport]) -> str:
    headers = ["entry", "policy", "group", "checked", "viol", "reshards",
               "repl_bytes", "hash"]
    rows = []
    for r in reports:
        rows.append([
            r.entry,
            r.policy or "-",
            r.group or "-",
            f"{r.params_checked}/{r.params_total}",
            str(r.rule_violations),
            str(r.reshard_collectives),
            f"{r.replicated_bytes:,}",
            (r.program_hash or "-"),
        ])
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*r) for r in rows]
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    try:
        overrides = _parse_overrides(args.override_rule)
    except ValueError as e:
        print(f"tpushard: {e}", file=sys.stderr)
        return 2

    # determinism (same contract as tpucost): executables deserialized from
    # the persistent compile cache lose analysis-relevant attributes
    os.environ["DSTPU_COMPILE_CACHE"] = "0"

    from ..tpuaudit.cli import _setup_platform

    _setup_platform(args.devices)

    from ..tpuaudit.registry import get_entry_points

    if args.config:
        from ..tpuaudit import harness

        try:
            harness.build_from_config(harness.load_config(args.config))
        except (OSError, json.JSONDecodeError, ValueError, KeyError) as e:
            print(f"tpushard: bad --config {args.config}: {e}",
                  file=sys.stderr)
            return 2

    try:
        names = ([n.strip() for n in args.entries.split(",") if n.strip()]
                 if args.entries else None)
        entries = get_entry_points(names)
    except KeyError as e:
        print(f"tpushard: {e.args[0]}", file=sys.stderr)
        return 2

    if args.list_entries:
        for ep in entries:
            tag = ep.tags.get("shard")
            handoff = ep.tags.get("handoff")
            contract = (f"policy={tag['policy']}" if tag
                        else f"handoff={handoff['role']}" if handoff
                        else "untagged")
            print(f"{ep.name}: {contract}")
        return 0
    if not entries:
        print("tpushard: no entry points registered (pass --config, or "
              "construct the engines in-process first)", file=sys.stderr)
        return 2

    findings, reports = run_shard(entries, rule_overrides=overrides or None)

    if args.metrics_jsonl:
        from deepspeed_tpu.observability import get_registry

        get_registry().dump_jsonl(args.metrics_jsonl,
                                  extra={"tool": "tpushard"})

    baseline_path = args.baseline
    if baseline_path is None and not (args.write_baseline
                                      or args.prune_baseline):
        if os.path.exists(DEFAULT_BASELINE):
            baseline_path = DEFAULT_BASELINE

    if (args.write_baseline or args.prune_baseline) and any(
            f.check == "trace-error" for f in findings):
        # same contract as tpucost: accepting debt while entries fail to
        # build looks like a successful ratchet
        for f in findings:
            if f.check == "trace-error":
                print(f"tpushard: {f.render()}", file=sys.stderr)
        print("tpushard: refusing to touch the baseline while entries fail "
              "to trace", file=sys.stderr)
        return 2

    # partial runs (--entries) must not condemn keys they never analyzed;
    # cross-program keys need BOTH sides, so they are in scope only for
    # full runs
    def in_scope(key: str) -> bool:
        entry, _, _ = key.rpartition("::")
        return names is None or entry in names

    if args.format == "text":
        tagged = sum(1 for r in reports)
        print("== sharding ==")
        if reports:
            print(_table(reports))
        untagged = [ep.name for ep in entries
                    if "shard" not in ep.tags and "handoff" not in ep.tags]
        if untagged:
            print(f"no layout contract (untagged): {', '.join(untagged)}")
        print(f"{tagged}/{len(entries)} entries carry a layout contract")
        print()

    rc = gate_and_report(
        findings, tool="tpushard", fmt=args.format,
        baseline_path=baseline_path, write_baseline=args.write_baseline,
        prune_baseline=args.prune_baseline, in_scope=in_scope)
    return rc


if __name__ == "__main__":
    sys.exit(main())
