#!/usr/bin/env python
"""Benchmark harness — run by the driver on real TPU hardware.

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Benchmark: GPT-2 125M causal-LM training throughput on one chip, bf16,
tokens/sec (BASELINE.json tracked config #1). ``vs_baseline`` reports
MFU / 0.5 — the fraction of the driver's north-star (≥50% MFU) achieved,
so 1.0 == target reached.

Outage handling: the TPU arrives over a tunnel that can be transiently
unavailable (round 4's official record was a bare ``UNAVAILABLE``
traceback). The parent runs the measurement in a watchdogged child
immediately (no extra backend init when the tunnel is healthy); only when
the child fails with a backend-down signature does it fall back to a
bounded probe/retry ladder (~7.5 min worst case) and one re-run. If the
backend never comes up — or the child hangs past the watchdog — it prints
a parseable skip record
    {"metric": ..., "value": null, "unit": ..., "vs_baseline": null,
     "skipped": true, "reason": ...}
and exits 0 so the round still has a structured result. Genuine bench
bugs (non-backend failures) still exit non-zero with the child's stderr.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

METRIC = "gpt2_125m_bf16_train_tokens_per_sec_per_chip"
UNIT = "tokens/s"

# Substrings marking "the backend/tunnel is down", as opposed to a bug in
# the bench itself. Matched against child stderr.
_BACKEND_DOWN_MARKERS = (
    "UNAVAILABLE",
    "Unable to initialize backend",
    "TPU backend setup",
    "DEADLINE_EXCEEDED",
    "connection dropped",
    "Socket closed",
    "failed to connect",
)


def _skip(reason: str) -> None:
    print(json.dumps({
        "metric": METRIC, "value": None, "unit": UNIT,
        "vs_baseline": None, "skipped": True, "reason": reason[-500:],
    }))
    sys.exit(0)


def _probe_backend(attempts: int = 5, probe_timeout: int = 75) -> str | None:
    """Try to bring up the jax backend in a throwaway subprocess.

    Returns None on success, else the last failure reason. Backend init on
    the tunnel can HANG as well as raise, so every attempt gets its own
    process + timeout. Worst case ~7.5 min: 5 x 75 s timeouts plus
    8+16+24+32 s of backoff sleeps.
    """
    last = "unknown"
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices(); print(jax.default_backend())"],
                timeout=probe_timeout, capture_output=True, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            if r.returncode == 0:
                return None
            last = (r.stderr or r.stdout or "probe failed").strip()[-500:]
        except subprocess.TimeoutExpired:
            last = f"backend-init probe timed out after {probe_timeout}s"
        if i < attempts - 1:
            time.sleep(8 * (i + 1))
    return last


def _run_child(timeout_s: float):
    """Run the BENCH_CHILD measurement in its own process GROUP so a
    watchdog kill cannot orphan a hung grandchild holding the TPU.
    Returns (returncode|None, stdout, stderr); None = timed out+killed."""
    import signal

    env = dict(os.environ, BENCH_CHILD="1")
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
        sys.stderr.write(err or "")   # forward child diagnostics
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        # collect whatever the child managed to write before the kill —
        # it shows WHERE it hung (backend init vs mid-bench)
        out, err = proc.communicate()
        return None, out or "", err or ""


def _run_watchdogged() -> None:
    """Parent mode: run the measurement child immediately; probe/retry only
    after a backend-down failure (a healthy tunnel pays zero extra init).

    The WHOLE parent is bounded by BENCH_TOTAL_BUDGET (default 1500 s) so
    the structured skip record always lands before any outer runner's
    timeout — run_bench_suite.py gives each entry 30 min."""
    start = time.monotonic()
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", 1500))

    def remaining() -> float:
        return budget - (time.monotonic() - start)

    first_timeout = float(os.environ.get("BENCH_WATCHDOG_TIMEOUT",
                                         budget * 0.6))
    err = ""
    for attempt in range(2):  # one mid-run tunnel drop gets one retry
        timeout_s = (min(first_timeout, remaining()) if attempt == 0
                     else max(remaining(), 60))
        rc, out, errtxt = _run_child(timeout_s)
        if rc is None:
            tail = (errtxt or "").strip().splitlines()[-3:]
            _skip(f"bench run exceeded {timeout_s:.0f}s watchdog "
                  f"(tunnel hang suspected); child stderr tail: "
                  f"{' | '.join(tail) if tail else '<empty>'}")
        if rc == 0:
            sys.stdout.write(out)
            return
        err = (errtxt or "")[-2000:]
        if not any(m in err for m in _BACKEND_DOWN_MARKERS):
            sys.stderr.write(errtxt or "")
            sys.exit(rc)  # real bug: surface it
        if attempt == 0:
            # probe ladder capped at 3 attempts (~4.3 min worst case) to
            # stay inside the budget
            down = _probe_backend(attempts=3)
            if down is not None:
                _skip(f"TPU backend unavailable after bounded retries: {down}")
            if remaining() < 120:
                _skip("TPU backend recovered but the run budget is spent; "
                      f"first failure: {err[-300:]}")
    _skip(f"TPU backend dropped twice despite a healthy probe: {err[-400:]}")


def peak_flops_per_chip() -> float:
    """bf16 peak for the attached chip generation."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    table = {
        "v5 lite": 197e12, "v5litepod": 197e12, "v5e": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12,
        "v6 lite": 918e12, "v6e": 918e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12


def main() -> None:
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import create_model

    batch, seq = int(os.environ.get("BENCH_BATCH", 32)), int(os.environ.get("BENCH_SEQ", 1024))
    remat = os.environ.get("BENCH_REMAT", "1") == "1"
    remat_policy = os.environ.get("BENCH_REMAT_POLICY", "dots")
    # full layer unroll: measured 115.2k tok/s vs 101.6k with the 12-layer
    # scan on v5e (XLA pipelines across layer boundaries); partial unroll
    # (2 or 6) is WORSE than either — all-or-nothing
    unroll = int(os.environ.get("BENCH_UNROLL", 12))
    model = create_model("gpt2-125m", dtype=jnp.bfloat16, remat=remat,
                         remat_policy=remat_policy, scan_unroll=unroll,
                         max_seq_len=seq)

    # the Pallas kernels must actually be the hot path on TPU (round-1 miss:
    # kernels existed but the bench ran plain-jnp attention)
    from deepspeed_tpu.models.transformer import active_attention_impl

    if jax.default_backend() == "tpu":
        impl = active_attention_impl(model.config)
        assert impl == "flash_attention", (
            f"expected Pallas flash attention on TPU, resolved '{impl}'")
    cfg = {
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
        # per-phase breakdown next to the end-to-end number: spans + comm
        # census + compile/memory telemetry land in a metrics JSONL so the
        # perf trajectory carries more than one scalar (BENCH_OBS=0 opts out)
        "observability": {
            "enabled": os.environ.get("BENCH_OBS", "1") == "1",
            "output_dir": os.environ.get("BENCH_OBS_DIR",
                                         "bench_results/obs_train"),
        },
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)

    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (1, batch, seq), 0, model.config.vocab_size)
    batch_tree = {"input_ids": ids}

    # warmup (compile); float() forces materialisation — block_until_ready is
    # not a reliable fence over remote-tunnel backends
    for _ in range(2):
        loss = engine.train_batch(batch=batch_tree)
    float(loss)

    steps = int(os.environ.get("BENCH_STEPS", 30))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch_tree)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    n_params = sum(int(p.size) for p in jax.tree.leaves(engine.params))
    cfg_m = model.config
    # training flops/token: 6*N for matmul params + attention 12*L*H*S per token
    flops_per_token = 6 * n_params + 12 * cfg_m.num_layers * cfg_m.hidden_size * seq
    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip()

    from deepspeed_tpu.observability import get_session

    obs = get_session()
    if obs.enabled:
        obs.registry.gauge("bench/tokens_per_sec").set(tokens_per_sec)
        obs.registry.gauge("bench/mfu").set(mfu)
        obs.dump_metrics(path=os.environ.get("BENCH_METRICS_JSONL",
                                             "BENCH_metrics_train.jsonl"),
                         metric=METRIC, steps=steps, batch=batch, seq=seq)
        obs.export_chrome_trace()
        obs.close(export=False)   # already exported to the bench paths

    print(json.dumps({
        "metric": METRIC,
        "value": round(tokens_per_sec, 1),
        "unit": UNIT,
        "vs_baseline": round(mfu / 0.5, 4),
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        _run_watchdogged()
