#!/usr/bin/env python
"""Benchmark harness — run by the driver on real TPU hardware.

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Benchmark: GPT-2 125M causal-LM training throughput on one chip, bf16,
tokens/sec (BASELINE.json tracked config #1). ``vs_baseline`` reports
MFU / 0.5 — the fraction of the driver's north-star (≥50% MFU) achieved,
so 1.0 == target reached.

Outage handling: the TPU arrives over a tunnel that can be transiently
unavailable (round 4's official record was a bare ``UNAVAILABLE``
traceback). The parent runs the measurement in a watchdogged child
immediately (no extra backend init when the tunnel is healthy); only when
the child fails with a backend-down signature does it fall back to a
bounded probe/retry ladder and one re-run (``bench_common.py``). If the
backend never comes up — or the child hangs past the watchdog (SIGUSR1
flight-record dump, then SIGKILL) — it prints a parseable skip record
    {"metric": ..., "value": null, "unit": ..., "vs_baseline": null,
     "skipped": true, "failure_kind": "hang|backend-init|crash",
     "reason": ...}
and exits 0 so the round still has a structured result; a hang's reason
carries the crash-bundle path and the stalled span name. Genuine bench
bugs (non-backend failures) still exit non-zero with the child's stderr.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_common import run_watchdogged  # noqa: E402

METRIC = "gpt2_125m_bf16_train_tokens_per_sec_per_chip"
UNIT = "tokens/s"


def peak_flops_per_chip() -> float:
    """bf16 peak for the attached chip generation (the cost model's table)."""
    import jax

    from deepspeed_tpu.autotuning.cost_model import peak_flops_for

    return peak_flops_for(jax.devices()[0].device_kind)


def predict_main() -> None:
    """BENCH_PREDICT=1 child mode: the ANALYTIC predicted MFU for this
    bench's exact config, host-side (CPU jax, no engine, no params). This is
    what a tunnel-outage skip record carries as ``predicted_mfu`` — the
    static half of the measured-vs-predicted pairing, computable when the
    measured half isn't."""
    import jax.numpy as jnp

    from deepspeed_tpu.autotuning.cost_model import (TpuCostModel,
                                                     peak_flops_for)
    from deepspeed_tpu.models import create_model
    from deepspeed_tpu.profiling import transformer_breakdown

    batch = int(os.environ.get("BENCH_BATCH", 32))
    seq = int(os.environ.get("BENCH_SEQ", 1024))
    model = create_model("gpt2-125m", dtype=jnp.bfloat16, max_seq_len=seq)
    cfg = model.config
    n = transformer_breakdown(cfg, batch, seq).total_params
    flops_per_token = 6 * n + 12 * cfg.num_layers * cfg.hidden_size * seq
    # mfu=1.0: predict the CEILING (roofline + overhead), not the 50% target
    cm = TpuCostModel(model_info={
        "num_params": n, "hidden_size": cfg.hidden_size,
        "num_layers": cfg.num_layers, "seq_length": seq,
        "vocab_size": cfg.vocab_size}, mfu=1.0)
    tps = cm.predict_throughput({"train_micro_batch_size_per_gpu": batch})
    print(json.dumps({
        "predicted_mfu": round(tps * flops_per_token / peak_flops_for(None),
                               4),
        "predicted_tokens_per_sec": round(tps, 1),
        "source": "analytic-roofline",
    }))


def main() -> None:
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import create_model

    batch, seq = int(os.environ.get("BENCH_BATCH", 32)), int(os.environ.get("BENCH_SEQ", 1024))
    remat = os.environ.get("BENCH_REMAT", "1") == "1"
    remat_policy = os.environ.get("BENCH_REMAT_POLICY", "dots")
    # full layer unroll: measured 115.2k tok/s vs 101.6k with the 12-layer
    # scan on v5e (XLA pipelines across layer boundaries); partial unroll
    # (2 or 6) is WORSE than either — all-or-nothing
    unroll = int(os.environ.get("BENCH_UNROLL", 12))
    model = create_model("gpt2-125m", dtype=jnp.bfloat16, remat=remat,
                         remat_policy=remat_policy, scan_unroll=unroll,
                         max_seq_len=seq)

    # the Pallas kernels must actually be the hot path on TPU (round-1 miss:
    # kernels existed but the bench ran plain-jnp attention)
    from deepspeed_tpu.models.transformer import active_attention_impl

    if jax.default_backend() == "tpu":
        impl = active_attention_impl(model.config)
        assert impl == "flash_attention", (
            f"expected Pallas flash attention on TPU, resolved '{impl}'")
    cfg = {
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
        # per-phase breakdown next to the end-to-end number: spans + comm
        # census + compile/memory telemetry land in a metrics JSONL so the
        # perf trajectory carries more than one scalar (BENCH_OBS=0 opts out)
        "observability": {
            "enabled": os.environ.get("BENCH_OBS", "1") == "1",
            "output_dir": os.environ.get("BENCH_OBS_DIR",
                                         "bench_results/obs_train"),
            # fleet-health smoke: per-rank step-time skew lands in the
            # metrics JSONL, and the bench record carries it as
            # step_time_skew (single-host: a 1-rank fleet, skew 0.0 — the
            # wiring is what the smoke proves). Cadence defaults to
            # warmup(2) + step count so exactly ONE gather runs, on the
            # LAST timed step (global-step counting includes the warmup),
            # right where the loop's own float(loss) sync lands — the
            # tracked tokens/sec number stays comparable. The numerics
            # sentinel is deliberately NOT enabled here: its isfinite
            # reductions compile into the hot step.
            "fleet_health": True,
            "fleet_cadence_steps": int(os.environ.get(
                "BENCH_FLEET_CADENCE",
                2 + int(os.environ.get("BENCH_STEPS", 30)))),
            # BENCH_PROFILE=1: deep-profiler capture windows mid-bench —
            # a scheduled window every BENCH_PROFILE_EVERY steps, parsed
            # into profile_summary.json (measured vs tpucost-predicted
            # step time for train/step) next to the metrics JSONL
            "profiling": {
                "enabled": os.environ.get("BENCH_PROFILE", "0") == "1",
                "profile_every_steps": int(os.environ.get(
                    "BENCH_PROFILE_EVERY", 10)),
                "window_iterations": int(os.environ.get(
                    "BENCH_PROFILE_WINDOW", 4)),
            },
        },
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)

    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (1, batch, seq), 0, model.config.vocab_size)
    batch_tree = {"input_ids": ids}

    # warmup (compile); float() forces materialisation — block_until_ready is
    # not a reliable fence over remote-tunnel backends
    for _ in range(2):
        loss = engine.train_batch(batch=batch_tree)
    float(loss)

    steps = int(os.environ.get("BENCH_STEPS", 30))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch_tree)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    n_params = sum(int(p.size) for p in jax.tree.leaves(engine.params))
    cfg_m = model.config
    # training flops/token: 6*N for matmul params + attention 12*L*H*S per token
    flops_per_token = 6 * n_params + 12 * cfg_m.num_layers * cfg_m.hidden_size * seq
    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip()

    from deepspeed_tpu.observability import get_session

    obs = get_session()
    metrics_path = os.environ.get("BENCH_METRICS_JSONL",
                                  "BENCH_metrics_train.jsonl")
    if obs.enabled:
        obs.registry.gauge("bench/tokens_per_sec").set(tokens_per_sec)
        obs.registry.gauge("bench/mfu").set(mfu)
        obs.dump_metrics(path=metrics_path,
                         metric=METRIC, steps=steps, batch=batch, seq=seq)
        obs.export_chrome_trace()
        obs.close(export=False)   # already exported to the bench paths

    from bench_common import fleet_skew_from_metrics

    record = {
        "metric": METRIC,
        "value": round(tokens_per_sec, 1),
        "unit": UNIT,
        "vs_baseline": round(mfu / 0.5, 4),
    }
    skew = fleet_skew_from_metrics(metrics_path if obs.enabled else None)
    if skew is not None:
        record["step_time_skew"] = round(skew, 4)

    # static cost vector for the step program the loop just ran (the
    # engine registered it with the audit registry at first train_batch):
    # the record carries measured-vs-predicted MFU side by side, so the
    # r03-style trajectory shows how far each round sat from its own
    # program's ceiling. BENCH_COST=0 opts out (the AOT re-extraction
    # costs one uncached host compile).
    if os.environ.get("BENCH_COST", "1") == "1":
        from bench_common import cost_vector_record

        cost = cost_vector_record("train/step")
        if cost is not None:
            record["tpucost"] = cost
            record["measured_vs_predicted_mfu"] = [
                round(mfu, 4), cost["predicted_mfu"]]
    print(json.dumps(record))


if __name__ == "__main__":
    if os.environ.get("BENCH_PREDICT") == "1":
        predict_main()
    elif os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        run_watchdogged(
            METRIC, UNIT, os.path.abspath(__file__),
            crash_dir=os.path.join(
                os.environ.get("BENCH_OBS_DIR", "bench_results/obs_train"),
                "crash"))
