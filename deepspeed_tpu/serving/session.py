"""Request sessions — the client half of the streaming front end.

A ``RequestHandle`` is what ``ServingEngine.submit`` returns: a thread-safe
incremental view of one request's output tokens. It works in both engine
modes:

* **step-driven** (tests, benches): iterating ``stream()`` or calling
  ``result()`` drives ``engine.step()`` itself until tokens arrive;
* **threaded** (``engine.start()``): a driver thread steps the engine;
  consumers block on the handle's condition variable.

Cancellation is cooperative: ``cancel()`` marks the request and the engine
releases its row/blocks at the next iteration boundary (or immediately when
called between steps).
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional

import numpy as np

from .scheduler import CANCELLED, FINISHED, Request

__all__ = ["RequestHandle", "RequestCancelled"]


class RequestCancelled(RuntimeError):
    """Raised by ``result()`` when the request was cancelled."""


class RequestHandle:
    """Incremental, thread-safe view of one request's generated tokens."""

    def __init__(self, engine, req: Request):
        self._engine = engine
        self._req = req
        self._cond = threading.Condition()
        self._tokens: List[int] = []

    # -- engine-side (called from ServingEngine.step under its lock) -------
    def _push(self, token: int) -> None:
        with self._cond:
            self._tokens.append(int(token))
            self._cond.notify_all()

    def _wake(self) -> None:
        """Terminal-state transition: wake any blocked consumers."""
        with self._cond:
            self._cond.notify_all()

    # -- client-side -------------------------------------------------------
    @property
    def request_id(self) -> int:
        return self._req.rid

    @property
    def state(self) -> str:
        return self._req.state

    @property
    def done(self) -> bool:
        return self._req.done

    @property
    def tokens(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        return self._req.ttft_s

    @property
    def tpot_s(self) -> Optional[float]:
        return self._req.tpot_s

    @property
    def preemptions(self) -> int:
        return self._req.preemptions

    @property
    def spec_acceptance_rate(self) -> Optional[float]:
        """Accepted / proposed draft tokens for this request (None until
        speculation proposed anything)."""
        if self._req.spec_proposed == 0:
            return None
        return self._req.spec_accepted / self._req.spec_proposed

    def fork(self, n: int, seeds: Optional[List[int]] = None
             ) -> List["RequestHandle"]:
        """Branch ``n`` parallel samples off this request at its current
        position: the siblings share every block (prompt AND generated)
        through the refcounted COW tables, inherit the tokens streamed so
        far, and diverge from the next token on — sibling ``i`` samples
        with ``seeds[i]`` (default ``seed + i + 1``). The request must be
        actively decoding."""
        return self._engine.fork(self, n, seeds=seeds)

    def cancel(self) -> bool:
        """Cancel the request; returns False when it already finished."""
        return self._engine.cancel(self)

    def stream(self, timeout_s: Optional[float] = None) -> Iterator[int]:
        """Yield tokens as they are generated. In step-driven mode this
        DRIVES the engine (each starved iteration runs one engine step); in
        threaded mode it blocks on the condition variable. Ends when the
        request finishes or is cancelled; raises TimeoutError past
        ``timeout_s`` without a token (engine clock in step-driven mode),
        and RuntimeError when the engine stops making progress entirely
        (the same starvation guard as ``ServingEngine.run``)."""
        i = 0
        deadline = (self._engine.clock() + timeout_s
                    if timeout_s is not None else None)
        starved = 0
        while True:
            tok = None
            with self._cond:
                if i < len(self._tokens):
                    tok = self._tokens[i]
                    i += 1
                elif self._req.done:
                    return
                elif self._engine.threaded:
                    if not self._cond.wait(timeout=timeout_s):
                        raise TimeoutError(
                            f"request {self._req.rid}: no token within "
                            f"{timeout_s}s")
                    continue
            if tok is not None:
                deadline = (self._engine.clock() + timeout_s
                            if timeout_s is not None else None)
                starved = 0
                yield tok
                continue
            # step-driven: advance the engine outside our condition lock
            if deadline is not None and self._engine.clock() > deadline:
                raise TimeoutError(
                    f"request {self._req.rid}: no token within {timeout_s}s")
            if self._engine.step():
                starved = 0
            else:
                starved += 1
                if starved > 2 * self._engine.config.max_queue + 4:
                    raise RuntimeError(
                        f"request {self._req.rid}: serving stalled — no "
                        "request can make progress (block pool or row "
                        "count too small for the workload)")

    def result(self, timeout_s: Optional[float] = None) -> np.ndarray:
        """Block (or drive) until the request finishes; returns the full
        generated token array. Raises ``RequestCancelled`` on cancellation."""
        for _ in self.stream(timeout_s=timeout_s):
            pass
        if self._req.state == CANCELLED:
            raise RequestCancelled(f"request {self._req.rid} was cancelled")
        assert self._req.state == FINISHED
        return np.asarray(self.tokens, np.int32)
