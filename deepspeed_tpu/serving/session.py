"""Request sessions — the client half of the streaming front end.

A ``RequestHandle`` is what ``ServingEngine.submit`` returns: a thread-safe
incremental view of one request's output tokens. It works in both engine
modes:

* **step-driven** (tests, benches): iterating ``stream()`` or calling
  ``result()`` drives ``engine.step()`` itself until tokens arrive;
* **threaded** (``engine.start()``): a driver thread steps the engine;
  consumers block on the handle's condition variable.

Cancellation is cooperative: ``cancel()`` marks the request and the engine
releases its row/blocks at the next iteration boundary (or immediately when
called between steps).
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Iterator, List, Optional

import numpy as np

from .scheduler import CANCELLED, DEADLINE_EXCEEDED, FINISHED, Request

if TYPE_CHECKING:  # circular at runtime: api.py imports this module
    from .api import ServingEngine

__all__ = ["RequestHandle", "RequestCancelled", "DeadlineExceeded"]


class RequestCancelled(RuntimeError):
    """Raised by ``result()`` when the request was cancelled."""


class DeadlineExceeded(RuntimeError):
    """Raised by ``result()`` when the request's deadline expired before it
    finished — its rows/blocks were reclaimed at the iteration boundary and
    the tokens streamed so far are all there will be."""


def drive_stream(cond: threading.Condition, tokens: List[int], is_done,
                 clock, threaded, step, starvation_limit, label: str,
                 stall_msg: str,
                 timeout_s: Optional[float]) -> Iterator[int]:
    """The drive-or-wait streaming loop shared by ``RequestHandle`` and the
    fleet's ``FleetHandle``: yield tokens from ``tokens`` (a live list
    guarded by ``cond``) as they appear; in step-driven mode each starved
    pass runs one ``step()``, in threaded mode block on ``cond``. Raises
    TimeoutError past ``timeout_s`` without a token and RuntimeError with
    ``stall_msg`` after ``starvation_limit()`` consecutive progress-free
    steps. ``is_done``/``threaded``/``starvation_limit`` are callables —
    all three can change while the stream is live (request finishing, a
    driver thread starting, config reload)."""
    i = 0
    deadline = clock() + timeout_s if timeout_s is not None else None
    starved = 0
    while True:
        tok = None
        with cond:
            if i < len(tokens):
                tok = tokens[i]
                i += 1
            elif is_done():
                return
            elif threaded():
                if not cond.wait(timeout=timeout_s):
                    raise TimeoutError(
                        f"{label}: no token within {timeout_s}s")
                continue
        if tok is not None:
            deadline = clock() + timeout_s if timeout_s is not None else None
            starved = 0
            yield tok
            continue
        # step-driven: advance the driver outside the condition lock
        if deadline is not None and clock() > deadline:
            raise TimeoutError(f"{label}: no token within {timeout_s}s")
        if step():
            starved = 0
        else:
            starved += 1
            if starved > starvation_limit():
                raise RuntimeError(f"{label}: {stall_msg}")


class RequestHandle:
    """Incremental, thread-safe view of one request's generated tokens."""

    def __init__(self, engine: "ServingEngine", req: Request):
        self._engine = engine
        self._req = req
        self._cond = threading.Condition()
        self._tokens: List[int] = []

    # -- engine-side (called from ServingEngine.step under its lock) -------
    def _push(self, token: int) -> None:
        with self._cond:
            self._tokens.append(int(token))
            self._cond.notify_all()

    def _wake(self) -> None:
        """Terminal-state transition: wake any blocked consumers."""
        with self._cond:
            self._cond.notify_all()

    # -- client-side -------------------------------------------------------
    @property
    def request_id(self) -> int:
        return self._req.rid

    @property
    def state(self) -> str:
        return self._req.state

    @property
    def done(self) -> bool:
        return self._req.done

    @property
    def tokens(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        return self._req.ttft_s

    @property
    def tpot_s(self) -> Optional[float]:
        return self._req.tpot_s

    @property
    def preemptions(self) -> int:
        return self._req.preemptions

    @property
    def spec_acceptance_rate(self) -> Optional[float]:
        """Accepted / proposed draft tokens for this request (None until
        speculation proposed anything)."""
        if self._req.spec_proposed == 0:
            return None
        return self._req.spec_accepted / self._req.spec_proposed

    def fork(self, n: int, seeds: Optional[List[int]] = None
             ) -> List["RequestHandle"]:
        """Branch ``n`` parallel samples off this request at its current
        position: the siblings share every block (prompt AND generated)
        through the refcounted COW tables, inherit the tokens streamed so
        far, and diverge from the next token on — sibling ``i`` samples
        with ``seeds[i]`` (default ``seed + i + 1``). The request must be
        actively decoding."""
        return self._engine.fork(self, n, seeds=seeds)

    def cancel(self) -> bool:
        """Cancel the request; returns False when it already finished."""
        return self._engine.cancel(self)

    def stream(self, timeout_s: Optional[float] = None) -> Iterator[int]:
        """Yield tokens as they are generated. In step-driven mode this
        DRIVES the engine (each starved iteration runs one engine step); in
        threaded mode it blocks on the condition variable. Ends when the
        request finishes or is cancelled; raises TimeoutError past
        ``timeout_s`` without a token (engine clock in step-driven mode),
        and RuntimeError when the engine stops making progress entirely
        (the same starvation guard as ``ServingEngine.run``)."""
        eng = self._engine
        yield from drive_stream(
            self._cond, self._tokens, lambda: self._req.done, eng.clock,
            lambda: eng.threaded, eng.step,
            lambda: 2 * eng.config.max_queue + 4,
            f"request {self._req.rid}",
            "serving stalled — no request can make progress (block pool "
            "or row count too small for the workload)", timeout_s)

    def result(self, timeout_s: Optional[float] = None) -> np.ndarray:
        """Block (or drive) until the request finishes; returns the full
        generated token array. Raises ``RequestCancelled`` on cancellation
        and ``DeadlineExceeded`` when the deadline expired mid-stream."""
        for _ in self.stream(timeout_s=timeout_s):
            pass
        if self._req.state == CANCELLED:
            raise RequestCancelled(f"request {self._req.rid} was cancelled")
        if self._req.state == DEADLINE_EXCEEDED:
            raise DeadlineExceeded(
                f"request {self._req.rid} missed its deadline "
                f"({len(self.tokens)} of {self._req.max_new_tokens} tokens "
                "generated)")
        assert self._req.state == FINISHED
        return np.asarray(self.tokens, np.int32)
