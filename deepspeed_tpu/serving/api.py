"""ServingEngine — the continuous-batching request front end.

The MII/FastGen analog for this stack: wraps an ``InferenceEngine`` (which
owns params, mesh and dtype discipline) with the paged KV arena
(``paged_kv.py``), the iteration-level scheduler (``scheduler.py``) and a
streaming session API (``session.py``).

One *iteration* (``step()``) is: admit queued requests onto free decode
rows → run at most one prefill chunk → run one decode step over every
decoding row → host-materialize the sampled tokens (the iteration's one
sync), stream them to handles, grow/free blocks. Both device programs are
compiled exactly once per (shape) configuration: occupancy, request mix and
sampling settings are all *data* (see ``docs/serving.md`` for the jit-cache
discipline rationale).

Telemetry flows through the PR-2 observability substrate: ``serving/*``
metrics in the MetricsRegistry (ttft_ms, tpot_ms, queue_depth,
kv_blocks_in_use, preemptions, ...), spans ``serving/prefill_chunk`` and
``serving/decode`` (which also give the recompile watchdog its attribution
site), and tpuaudit entries of the same names.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..config.config import ServingConfig
from ..observability import get_session
from ..parallel import mesh as mesh_mod
from ..utils.logging import log_dist, logger
from . import paged_kv
from .scheduler import DECODE, Request, SamplingParams, Scheduler
from .session import RequestHandle

__all__ = ["ServingEngine", "init_serving"]


def _percentile(samples: List[float], q: float) -> float:
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


class ServingEngine:
    """Continuous-batching serving over an ``InferenceEngine``'s params."""

    def __init__(self, engine, config: Optional[ServingConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self.config = config or ServingConfig()
        self.config.validate()
        cfg = engine.model.config
        if cfg.attention_layers or cfg.attention_scale is not None:
            raise NotImplementedError(
                "serving does not support sliding-window/custom-scale "
                "attention models (GPT-Neo family) yet — the paged read "
                "path has no window operand")
        if cfg.attention_impl is not None:
            # custom impls are served through the dense gathered-view path
            # (the impl has no block-table operand); the Pallas paged
            # kernels only engage for attention_impl=None
            log_dist("serving: custom attention_impl set — the paged read "
                     "uses the dense gather view, not the paged kernels")
        if cfg.position == "learned" and \
                self.config.max_model_len > cfg.max_seq_len:
            raise ValueError(
                f"serving.max_model_len={self.config.max_model_len} exceeds "
                f"the model's learned-position table ({cfg.max_seq_len})")
        self.blocks_per_seq = paged_kv.assert_block_divisible(
            self.config.max_model_len, self.config.block_size)
        # bucketing unification (the _bucket satellite): align the wrapped
        # engine's prompt buckets to the serving block size, so a prompt
        # padded for compile-bucket reasons never implies arena blocks the
        # true prompt cannot use
        engine.config.prompt_bucket = self.config.block_size
        self.clock = clock
        self._lock = threading.RLock()
        self.alloc = paged_kv.BlockAllocator(self.config.pool_blocks())
        self.prefix = (paged_kv.PrefixCache(self.alloc,
                                            self.config.block_size)
                       if self.config.prefix_cache else None)
        self.sched = Scheduler(self.config, allocator=self.alloc,
                               clock=clock, prefix_cache=self.prefix)
        self._dtype = engine.config.dtype
        with mesh_mod.ambient(engine.mesh):
            self._arena = paged_kv.init_paged_cache(
                cfg, self.config.pool_blocks() + 1, self.config.block_size,
                self._dtype)
        # 'off' pins the dense gather-view read (the A/B baseline);
        # 'auto' = Pallas paged kernels on TPU, jnp paged reference on CPU
        self._paged_impl = ("gather" if self.config.paged_kernel == "off"
                            else "auto")
        self._prefill = paged_kv.build_prefill_program(cfg, self._paged_impl)
        self._decode = paged_kv.build_decode_program(cfg, self._paged_impl)
        self._cow = paged_kv.build_cow_program()
        self._cow_copies = 0
        self._published_cow = 0
        import jax

        self._base_rng = jax.random.PRNGKey(self.config.seed)
        self._rid = 0
        self._iterations = 0
        # rid -> handle for requests still in flight; pruned at finish/
        # cancel (the client keeps its own reference) so a long-running
        # server never accumulates per-request state
        self._handles: Dict[int, RequestHandle] = {}
        self._published_preemptions = 0
        # bounded latency reservoirs: percentiles over the most recent
        # window, constant memory at serving lifetimes
        import collections

        self._ttft_samples = collections.deque(maxlen=8192)
        self._tpot_samples = collections.deque(maxlen=8192)
        self._tokens_out = 0
        self._started_s = clock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False
        self._register_audit_entries()
        log_dist(
            f"serving engine ready: rows={self.config.max_seqs}, "
            f"blocks={self.config.pool_blocks()}x{self.config.block_size} "
            f"(+scratch), max_model_len={self.config.max_model_len}, "
            f"chunk={self.config.prefill_chunk}, arena="
            f"{paged_kv.paged_cache_memory_bytes(cfg, self.config.pool_blocks() + 1, self.config.block_size, self._dtype) / 2 ** 20:.0f}"
            " MiB")

    # -- client API --------------------------------------------------------
    @property
    def threaded(self) -> bool:
        return self._thread is not None

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_token_id: Optional[int] = None, tenant: str = "default",
               deadline_s: Optional[float] = None,
               seed: int = 0) -> RequestHandle:
        """Enqueue one prompt; returns a streaming handle immediately.
        ``deadline_s`` is relative to now (scheduler-clock seconds) and
        drives EDF ordering within the tenant. ``seed`` selects the
        request's sampling stream: draws depend only on (engine seed,
        request seed, output-token index) — reproducible regardless of how
        the scheduler batched the request, and stable across
        preemption/recompute. Raises ``scheduler.QueueFull`` past
        ``serving.max_queue`` in-flight requests (backpressure) and
        ``ValueError`` for prompts that cannot fit the ``max_model_len``
        budget."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            req = Request(
                rid=self._rid, prompt=prompt,
                max_new_tokens=(max_new_tokens if max_new_tokens is not None
                                else self.config.default_max_new_tokens),
                sampling=SamplingParams(temperature=float(temperature),
                                        top_k=int(top_k), top_p=float(top_p)),
                eos_token_id=eos_token_id, tenant=tenant, seed=seed,
                deadline_s=(self.clock() + deadline_s
                            if deadline_s is not None else None))
            self.sched.submit(req)   # raises before rid is consumed
            self._rid += 1
            handle = RequestHandle(self, req)
            self._handles[req.rid] = handle
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "serving/requests_submitted",
                    help="requests accepted into the serving queue").inc(
                        tenant=tenant)
            return handle

    def cancel(self, handle: RequestHandle) -> bool:
        with self._lock:
            ok = self.sched.cancel(handle._req)
            self._handles.pop(handle._req.rid, None)
        if ok:
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "serving/requests_cancelled",
                    help="requests cancelled before completion").inc()
        handle._wake()
        return ok

    def in_flight(self) -> int:
        with self._lock:
            return self.sched.in_flight()

    # -- the iteration -----------------------------------------------------
    def step(self) -> bool:
        """One continuous-batching iteration; returns True when any request
        made progress (admission, a prefill chunk, or a decode token)."""
        with self._lock:
            progress = bool(self.sched.admit())
            progress |= self._step_prefill()
            progress |= self._step_decode()
            self._publish_iteration()
            self._iterations += 1
            return progress

    def _table_for(self, reqs: List[Request]) -> np.ndarray:
        """(len(reqs), MAXB) block table; unfilled entries → scratch 0."""
        bt = np.zeros((len(reqs), self.blocks_per_seq), np.int32)
        for i, r in enumerate(reqs):
            if r.blocks:
                bt[i, :len(r.blocks)] = r.blocks
        return bt

    @staticmethod
    def _sampling_arrays(reqs: List[Request]):
        return (np.asarray([r.sampling.temperature for r in reqs],
                           np.float32),
                np.asarray([r.sampling.top_k for r in reqs], np.int32),
                np.asarray([r.sampling.top_p for r in reqs], np.float32),
                np.asarray([r.seed for r in reqs], np.int32))

    def _make_writable(self, req: Request, start: int, end: int) -> bool:
        """Copy-on-write: every block covering write positions
        [start, end) must be exclusively owned before the jitted program
        scatters into it. Shared blocks (prefix sharing, refcount > 1) are
        duplicated on device and swapped into the request's table; the
        sharers keep the original. Returns False when the pool can't
        provide a private copy this iteration — the caller skips the
        request; copies already made stay (they are real private blocks,
        the retry skips them)."""
        for bi in self.sched.cow_block_indices(req, start, end):
            nid = self.sched.alloc_for_cow(req)
            if nid is None:
                return False
            old = req.blocks[bi]
            obs = get_session()
            with mesh_mod.ambient(self.engine.mesh):
                with obs.span("serving/cow_copy"):
                    self._arena = self._cow(self._arena,
                                            np.asarray(old, np.int32),
                                            np.asarray(nid, np.int32))
            req.blocks[bi] = nid
            self.alloc.free([old])   # drop THIS request's shared reference
            self._cow_copies += 1
        return True

    def _step_prefill(self) -> bool:
        req = self.sched.next_prefill()
        if req is None:
            return False
        C = self.config.prefill_chunk
        src = req.prompt
        start = req.prefill_pos
        n_valid = min(C, int(src.size) - start)
        if not self.sched.ensure_blocks(req, start + n_valid):
            return False    # pool dry, nothing evictable — wait a turn
        if not self._make_writable(req, start, start + n_valid):
            return False    # shared block needs a copy the pool can't give
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :n_valid] = src[start:start + n_valid]
        temps, topks, topps, seeds = self._sampling_arrays([req])
        obs = get_session()
        with mesh_mod.ambient(self.engine.mesh):
            with obs.span("serving/prefill_chunk", batch=1,
                          tokens=int(n_valid)):
                tok, _last, self._arena = self._prefill(
                    self.engine.params, self._arena,
                    self._table_for([req]), chunk,
                    np.asarray(start, np.int32),
                    np.asarray(n_valid, np.int32),
                    temps, topks, topps, seeds, self._base_rng)
                tok = np.asarray(tok)   # the fence: chunk really ran
        req.prefill_pos += n_valid
        req.length = req.prefill_pos
        # newly completed full prompt blocks become shareable prefix cache
        self.sched.note_prefill_progress(req, start, req.prefill_pos)
        self.sched.note_service(req, n_valid)
        if req.prefill_pos == int(src.size):
            req.state = DECODE
            if req.resume:
                # recompute after preemption: the stored pending token is
                # authoritative (identical under greedy; under temperature
                # sampling the resampled one may diverge) and was already
                # streamed — never re-emit
                req.resume = False
            else:
                self._emit(req, int(tok[0]), first=True)
        return True

    def _step_decode(self) -> bool:
        dec = self.sched.decode_requests()
        if not dec:
            return False
        for r in dec:
            # re-check state INSIDE the loop: an earlier ensure_blocks may
            # have evicted this very request — growing a now-QUEUED request
            # would hand pool blocks to a non-running request (and, pool
            # dry, let it evict an active one)
            if r.state == DECODE:
                self.sched.ensure_blocks(r, r.length + 1)
        ready = []
        for r in dec:
            if r.state != DECODE:
                continue
            if len(r.blocks) * self.config.block_size <= r.length:
                continue
            # the incoming token's block must be exclusively owned —
            # writing into a prefix-shared block would corrupt the sharers
            if not self._make_writable(r, r.length, r.length + 1):
                continue
            ready.append(r)
        # a later row's COW may have preempted an earlier accepted row
        ready = [r for r in ready if r.state == DECODE]
        if not ready:
            return False
        R = self.config.max_seqs
        bt = np.zeros((R, self.blocks_per_seq), np.int32)
        lengths = np.zeros((R,), np.int32)
        tokens = np.zeros((R,), np.int32)
        temps = np.zeros((R,), np.float32)
        topks = np.zeros((R,), np.int32)
        topps = np.ones((R,), np.float32)
        seeds = np.zeros((R,), np.int32)
        steps = np.zeros((R,), np.int32)
        for r in ready:
            row = r.row
            bt[row, :len(r.blocks)] = r.blocks
            lengths[row] = r.length
            tokens[row] = r.pending_token
            temps[row] = r.sampling.temperature
            topks[row] = r.sampling.top_k
            topps[row] = r.sampling.top_p
            seeds[row] = r.seed
            steps[row] = len(r.generated)   # output-token index: the
            #   sampling stream is (engine seed, request seed, index) —
            #   schedule-independent and preemption-stable
        obs = get_session()
        with mesh_mod.ambient(self.engine.mesh):
            with obs.span("serving/decode", batch=len(ready)):
                nxt, self._arena = self._decode(
                    self.engine.params, self._arena, bt, lengths, tokens,
                    temps, topks, topps, seeds, steps, self._base_rng)
                nxt = np.asarray(nxt)   # the iteration's one host sync
        for r in ready:
            r.length += 1
            self.sched.note_service(r, 1)
            self._emit(r, int(nxt[r.row]))
        return True

    def _emit(self, req: Request, token: int, first: bool = False) -> None:
        now = self.clock()
        obs = get_session()
        if first:
            req.first_token_s = now
            if obs.enabled:
                ttft_ms = (now - req.arrival_s) * 1e3
                self._ttft_samples.append(ttft_ms)
                obs.registry.histogram(
                    "serving/ttft_ms",
                    help="arrival → first streamed token, wall ms").observe(
                        ttft_ms, tenant=req.tenant)
        req.generated.append(token)
        req.pending_token = token
        self._tokens_out += 1
        handle = self._handles.get(req.rid)
        if handle is not None:
            handle._push(token)
        finished = (len(req.generated) >= req.max_new_tokens
                    or (req.eos_token_id is not None
                        and token == req.eos_token_id))
        if finished:
            self.sched.finish(req)
            if obs.enabled:
                obs.registry.counter(
                    "serving/requests_completed",
                    help="requests that finished generation").inc(
                        tenant=req.tenant)
                tpot = req.tpot_s
                if tpot is not None:
                    self._tpot_samples.append(tpot * 1e3)
                    obs.registry.histogram(
                        "serving/tpot_ms",
                        help="mean per-token wall ms after the first "
                             "token").observe(tpot * 1e3, tenant=req.tenant)
            self._handles.pop(req.rid, None)   # the client holds its own
            #   reference; keeping ours would leak one handle per request
            #   over a server's lifetime
            if handle is not None:
                handle._wake()

    def _publish_iteration(self) -> None:
        obs = get_session()
        if not obs.enabled:
            return
        reg = obs.registry
        reg.gauge("serving/queue_depth",
                  help="requests waiting for admission").set(
                      self.sched.queue_depth())
        reg.gauge("serving/kv_blocks_in_use",
                  help="allocated arena blocks").set(self.alloc.blocks_in_use)
        reg.gauge("serving/kv_blocks_peak",
                  help="peak allocated arena blocks").set(
                      self.alloc.peak_in_use)
        reg.gauge("serving/arena_occupancy",
                  help="allocated fraction of the block pool").set(
                      self.alloc.blocks_in_use / max(self.alloc.capacity, 1))
        reg.gauge("serving/decode_batch_occupancy",
                  help="decoding rows / max_seqs").set(
                      len(self.sched.decode_requests())
                      / self.config.max_seqs)
        reg.gauge("serving/kv_blocks_shared",
                  help="arena blocks referenced by more than one "
                       "holder (prefix sharing)").set(
                      self.alloc.blocks_shared)
        reg.gauge("serving/kv_blocks_shared_peak",
                  help="peak concurrently-shared arena blocks").set(
                      self.alloc.peak_shared)
        if self.prefix is not None:
            reg.gauge("serving/prefix_hit_rate",
                      help="prompt tokens served from the prefix cache / "
                           "prompt tokens of admitted requests").set(
                          self.sched.prefix_hit_tokens
                          / max(self.sched.prefix_lookup_tokens, 1))
            reg.gauge("serving/prefix_cache_blocks",
                      help="blocks pinned by the prefix cache").set(
                          self.prefix.cached_blocks)
        new_cow = self._cow_copies - self._published_cow
        if new_cow:
            reg.counter("serving/cow_copies",
                        help="copy-on-write block duplications (first "
                             "write into a shared block)").inc(new_cow)
            self._published_cow = self._cow_copies
        new_preempt = self.sched.preemption_count \
            - self._published_preemptions
        if new_preempt:
            reg.counter("serving/preemptions",
                        help="requests evicted from the arena "
                             "(recompute on re-admission)").inc(new_preempt)
            self._published_preemptions = self.sched.preemption_count
        # steady-state marker for the recompile watchdog: past warmup, a
        # recompile under a serving span is a shape-discipline bug
        obs.note_step(self._iterations)

    # -- drivers -----------------------------------------------------------
    def run(self, max_steps: Optional[int] = None) -> int:
        """Step until every in-flight request is terminal (tests/benches).
        Returns the number of iterations run."""
        steps = 0
        starved = 0
        while self.in_flight():
            progress = self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            if progress:
                starved = 0
            else:
                starved += 1
                if starved > 2 * self.config.max_queue + 4:
                    raise RuntimeError(
                        "serving stalled: no request can make progress "
                        f"({self.sched.queue_depth()} queued, "
                        f"{self.alloc.blocks_free} free blocks) — the block "
                        "pool or row count is too small for the workload")
        return steps

    def start(self) -> None:
        """Background driver thread (the 'server' mode): steps while work is
        in flight, idles cheaply otherwise."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._drive,
                                        name="dstpu-serving", daemon=True)
        self._thread.start()

    def _drive(self) -> None:
        while not self._stop.is_set():
            try:
                if self.in_flight():
                    self.step()
                else:
                    self._stop.wait(0.002)
            except Exception:
                logger.exception("serving driver step failed")
                get_session().crash_dump("serving-step-exception")
                self._stop.wait(0.05)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.stop()
        self.publish_latency_gauges()

    def publish_latency_gauges(self) -> None:
        """Host-side percentile gauges (the registry histogram keeps only
        count/sum/min/max): serving/ttft_p50_ms, p99, tpot p50/p99, and the
        end-to-end tokens/s — the ``report`` CLI's ``== serving ==``
        inputs."""
        obs = get_session()
        if not obs.enabled:
            return
        reg = obs.registry
        for name, samples in (("ttft", self._ttft_samples),
                              ("tpot", self._tpot_samples)):
            if samples:
                reg.gauge(f"serving/{name}_p50_ms").set(
                    _percentile(list(samples), 0.50))
                reg.gauge(f"serving/{name}_p99_ms").set(
                    _percentile(list(samples), 0.99))
        wall = max(self.clock() - self._started_s, 1e-9)
        reg.gauge("serving/tokens_per_sec",
                  help="generated tokens / wall seconds").set(
                      self._tokens_out / wall)

    def reset_latency_stats(self) -> None:
        """Drop the host-side latency reservoirs and restart the
        tokens/s window — benches call this after their warmup request so
        the published p50/p99/tokens_per_sec describe the measured load,
        not program compilation."""
        with self._lock:
            self._ttft_samples.clear()
            self._tpot_samples.clear()
            self._tokens_out = 0
            self._started_s = self.clock()

    # -- tpuaudit ----------------------------------------------------------
    def _audit_args_prefill(self):
        import jax
        import jax.numpy as jnp

        cfg = self.engine.model.config
        C, MAXB = self.config.prefill_chunk, self.blocks_per_seq
        i32 = jnp.int32
        return (self.engine._params_sds(),
                self._arena_sds(),
                jax.ShapeDtypeStruct((1, MAXB), i32),
                jax.ShapeDtypeStruct((1, C), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((1,), jnp.float32),
                jax.ShapeDtypeStruct((1,), i32),
                jax.ShapeDtypeStruct((1,), jnp.float32),
                jax.ShapeDtypeStruct((1,), i32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))

    def _arena_sds(self):
        from ..inference.kv_cache import paged_cache_shape_struct

        return paged_cache_shape_struct(
            self.engine.model.config, self.config.pool_blocks() + 1,
            self.config.block_size, self._dtype)

    def _register_audit_entries(self) -> List[str]:
        try:
            from tools.tpuaudit.registry import (StaleEntryError,
                                                 register_entry_point)
        except ImportError:
            return []
        try:
            import weakref

            import jax
            import jax.numpy as jnp

            wself = weakref.ref(self)
            expected = self.engine._audit_expected_collectives()
            R, MAXB = self.config.max_seqs, self.blocks_per_seq
            C = self.config.prefill_chunk

            def build_prefill():
                eng = wself()
                if eng is None:
                    raise StaleEntryError("serving/prefill_chunk: "
                                          "engine gone")
                return eng._prefill, eng._audit_args_prefill(), {}

            def build_decode():
                eng = wself()
                if eng is None:
                    raise StaleEntryError("serving/decode: engine gone")
                i32 = jnp.int32
                args = (eng.engine._params_sds(), eng._arena_sds(),
                        jax.ShapeDtypeStruct((R, MAXB), i32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((R,), jnp.float32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((R,), jnp.float32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((2,), jnp.uint32))
                return eng._decode, args, {}

            register_entry_point(
                "serving/prefill_chunk", build=build_prefill,
                donate_argnums=(1,), expected_collectives=expected,
                mesh=self.engine.mesh,
                tags={"engine": "ServingEngine", "chunk": C,
                      "max_blocks": MAXB, "paged_impl": self._paged_impl,
                      # one chunked-prefill run ingests C prompt tokens
                      "tokens_per_step": C})
            register_entry_point(
                "serving/decode", build=build_decode, donate_argnums=(1,),
                expected_collectives=expected, mesh=self.engine.mesh,
                tags={"engine": "ServingEngine", "rows": R,
                      "max_blocks": MAXB, "paged_impl": self._paged_impl,
                      # one decode iteration emits one token per row
                      "tokens_per_step": R})

            def build_cow():
                eng = wself()
                if eng is None:
                    raise StaleEntryError("serving/cow_copy: engine gone")
                i32 = jnp.int32
                return (eng._cow, (eng._arena_sds(),
                                   jax.ShapeDtypeStruct((), i32),
                                   jax.ShapeDtypeStruct((), i32)), {})

            # pure arena block copy: slice-select + slice-update along the
            # (replicated) block axis — no resharding, hence no collectives
            # regardless of the engine's TP/EP declarations
            register_entry_point(
                "serving/cow_copy", build=build_cow, donate_argnums=(0,),
                expected_collectives=(), mesh=self.engine.mesh,
                tags={"engine": "ServingEngine",
                      "block_size": self.config.block_size})
            return ["serving/prefill_chunk", "serving/decode",
                    "serving/cow_copy"]
        except Exception:   # registration must never take serving down
            logger.warning("tpuaudit serving registration failed",
                           exc_info=True)
            return []


def init_serving(model=None, serving_config: Optional[Any] = None,
                 clock: Callable[[], float] = time.monotonic,
                 **init_inference_kwargs) -> ServingEngine:
    """Build an ``InferenceEngine`` (same surface as ``init_inference``) and
    wrap it in a ``ServingEngine``. ``serving_config``: a ``ServingConfig``
    or plain dict."""
    from ..inference import init_inference

    if isinstance(serving_config, dict):
        serving_config = ServingConfig.from_dict(serving_config)
    scfg = serving_config or ServingConfig()
    # the offline arena is unused by serving, but a shared engine may still
    # serve generate() calls — keep its budget at least the serving budget
    init_inference_kwargs.setdefault("max_out_tokens", scfg.max_model_len)
    engine = init_inference(model=model, **init_inference_kwargs)
    return ServingEngine(engine, scfg, clock=clock)
