"""ServingEngine — the continuous-batching request front end.

The MII/FastGen analog for this stack: wraps an ``InferenceEngine`` (which
owns params, mesh and dtype discipline) with the paged KV arena
(``paged_kv.py``), the iteration-level scheduler (``scheduler.py``) and a
streaming session API (``session.py``).

One *iteration* (``step()``) is: admit queued requests onto free decode
rows → run at most one prefill chunk → run one decode step over every
decoding row → host-materialize the sampled tokens (the iteration's one
sync), stream them to handles, grow/free blocks. Both device programs are
compiled exactly once per (shape) configuration: occupancy, request mix and
sampling settings are all *data* (see ``docs/serving.md`` for the jit-cache
discipline rationale).

Telemetry flows through the PR-2 observability substrate: ``serving/*``
metrics in the MetricsRegistry (ttft_ms, tpot_ms, queue_depth,
kv_blocks_in_use, preemptions, ...), spans ``serving/prefill_chunk`` and
``serving/decode`` (which also give the recompile watchdog its attribution
site), and tpuaudit entries of the same names.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..config.config import ServingConfig
from ..observability import get_session
from ..parallel import mesh as mesh_mod
from ..utils.logging import log_dist, logger
from . import paged_kv
from .scheduler import (CANCELLED, DECODE, Request, SamplingParams,
                        Scheduler)
from .session import RequestHandle

__all__ = ["ServingEngine", "init_serving"]


def _percentile(samples: List[float], q: float) -> float:
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


class ServingEngine:
    """Continuous-batching serving over an ``InferenceEngine``'s params.

    ``draft_engine`` (an ``InferenceEngine`` over a smaller model) is
    required only for ``speculative.mode='draft'`` — its paged KV shares
    this engine's block pool (see ``speculative.py``)."""

    def __init__(self, engine, config: Optional[ServingConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 draft_engine=None):
        self.engine = engine
        self.config = config or ServingConfig()
        self.config.validate()
        cfg = engine.model.config
        if cfg.attention_layers or cfg.attention_scale is not None:
            raise NotImplementedError(
                "serving does not support sliding-window/custom-scale "
                "attention models (GPT-Neo family) yet — the paged read "
                "path has no window operand")
        if cfg.attention_impl is not None:
            # custom impls are served through the dense gathered-view path
            # (the impl has no block-table operand); the Pallas paged
            # kernels only engage for attention_impl=None
            log_dist("serving: custom attention_impl set — the paged read "
                     "uses the dense gather view, not the paged kernels")
        if cfg.position == "learned" and \
                self.config.max_model_len > cfg.max_seq_len:
            raise ValueError(
                f"serving.max_model_len={self.config.max_model_len} exceeds "
                f"the model's learned-position table ({cfg.max_seq_len})")
        self.blocks_per_seq = paged_kv.assert_block_divisible(
            self.config.max_model_len, self.config.block_size)
        # bucketing unification (the _bucket satellite): align the wrapped
        # engine's prompt buckets to the serving block size, so a prompt
        # padded for compile-bucket reasons never implies arena blocks the
        # true prompt cannot use
        engine.config.prompt_bucket = self.config.block_size
        self.clock = clock
        self._lock = threading.RLock()
        self.alloc = paged_kv.BlockAllocator(self.config.pool_blocks())
        self.prefix = (paged_kv.PrefixCache(self.alloc,
                                            self.config.block_size)
                       if self.config.prefix_cache else None)
        self.sched = Scheduler(self.config, allocator=self.alloc,
                               clock=clock, prefix_cache=self.prefix)
        # fleet identity on traces / serving-goodput labels (the router
        # overwrites it with the replica index before stepping)
        self.trace_tag = "0"
        # lazy ServeGoodput accountant (see _accountant: the bench builds
        # engines BEFORE enabling observability, so the gate is consulted
        # at step time, not construction)
        self._serve_acct = None
        self.sched.on_preempt = self._trace_preempt
        self._dtype = engine.config.dtype
        with mesh_mod.ambient(engine.mesh):
            self._arena = paged_kv.init_paged_cache(
                cfg, self.config.pool_blocks() + 1, self.config.block_size,
                self._dtype)
        # 'off' pins the dense gather-view read (the A/B baseline);
        # 'auto' = Pallas paged kernels on TPU, jnp paged reference on CPU
        self._paged_impl = ("gather" if self.config.paged_kernel == "off"
                            else "auto")
        self._prefill = paged_kv.build_prefill_program(cfg, self._paged_impl)
        self._decode = paged_kv.build_decode_program(cfg, self._paged_impl)
        self._cow = paged_kv.build_cow_program()
        # teacher-forced scoring over the same arena (the RLHF second
        # serving pass — docs/rlhf.md); jit is lazy, so an engine that
        # never scores pays nothing
        self._score = paged_kv.build_score_program(cfg, self._paged_impl)
        self._cow_copies = 0
        self._published_cow = 0
        # rollout accounting: prefill dispatches + real tokens they
        # ingested — the fork/prefix reuse ratio's denominator-side
        # evidence (a candidate group of n samples must cost ONE prefill)
        self.prefill_chunks_run = 0
        self.prefill_tokens_run = 0
        self.weight_refreshes = 0
        # -- speculative decoding (off → the plain R×1 decode path) --
        from .speculative import make_drafter

        # kept for fleet replica revival: a rebuilt engine needs the same
        # drafter inputs the original was constructed with
        self._draft_engine = draft_engine
        # fleet degraded-mode rung 1: True skips the drafter (the verify
        # path with zero proposals IS the plain decode, so flipping this
        # mid-stream is bit-exact by construction)
        self.spec_suspended = False
        # prefill chunks per scheduler iteration — the live tuner's
        # chunked-prefill budget knob. Scheduling-only: N > 1 runs the
        # SAME compiled chunk program N times before the decode phase,
        # pulling TTFT forward under prefill backlog at some TPOT cost;
        # streams stay bit-exact at any setting
        self.prefill_chunks_per_iter = 1
        # set by FleetRouter: replicas are tuned fleet-wide, never solo
        self._fleet_managed = False
        # lazy live-tuner hook (single-engine deployments; see
        # FleetRouter._maybe_tuner for the fleet path); latched per
        # OBSERVABILITY SESSION, not once — benches replace the session
        # after warmup
        self._tuner = None
        self._tuner_obs = None
        self._drafter = make_drafter(self.config, engine, self.alloc,
                                     self.blocks_per_seq,
                                     draft_engine=draft_engine,
                                     paged_impl=self._paged_impl)
        self._verify = None
        if self._drafter is not None:
            self._verify = paged_kv.build_verify_program(
                cfg, self.config.speculative.num_draft_tokens + 1,
                self._paged_impl)
            # one release point covers finish/cancel/preempt: the drafter
            # must drop its draft-arena blocks whenever the scheduler
            # releases the request's target blocks, or a preempted
            # request's draft KV would squat on the pool from the queue
            self.sched.on_release = self._drafter.release
        self._spec_dispatches = 0
        self._spec_emitted = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_disabled_rows = 0
        self._spec_draft_s = 0.0
        self._spec_verify_s = 0.0
        self._forks = 0
        self._published_spec = (0, 0, 0, 0)   # proposed/accepted/disp/disabled
        self._published_forks = 0
        import jax

        self._base_rng = jax.random.PRNGKey(self.config.seed)
        self._rid = 0
        self._iterations = 0
        # rid -> handle for requests still in flight; pruned at finish/
        # cancel (the client keeps its own reference) so a long-running
        # server never accumulates per-request state
        self._handles: Dict[int, RequestHandle] = {}
        self._published_preemptions = 0
        # bounded latency reservoirs: percentiles over the most recent
        # window, constant memory at serving lifetimes
        import collections

        self._ttft_samples = collections.deque(maxlen=8192)
        self._tpot_samples = collections.deque(maxlen=8192)
        # per-request acceptance rates, recorded at finish (report p50)
        self._accept_samples = collections.deque(maxlen=8192)
        # parent rid -> sibling Requests awaiting the COW fork point
        # (parent prefill completion)
        self._pending_forks: Dict[int, List[Request]] = {}
        self._tokens_out = 0
        self._started_s = clock()
        # fleet seam (serving/fleet): called with the request right after
        # its LAST prefill chunk completed and the first token was emitted,
        # while the engine lock is held. The disaggregation router uses it
        # to hand the sequence's KV blocks to a decode-pool engine; the
        # hook may release the request from this engine entirely
        # (``release_for_handoff``). None = single-engine serving.
        self.on_prefill_complete: Optional[Callable[[Request], None]] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False
        self._register_audit_entries()
        log_dist(
            f"serving engine ready: rows={self.config.max_seqs}, "
            f"blocks={self.config.pool_blocks()}x{self.config.block_size} "
            f"(+scratch), max_model_len={self.config.max_model_len}, "
            f"chunk={self.config.prefill_chunk}, arena="
            f"{paged_kv.paged_cache_memory_bytes(cfg, self.config.pool_blocks() + 1, self.config.block_size, self._dtype) / 2 ** 20:.0f}"
            " MiB")

    # -- client API --------------------------------------------------------
    @property
    def threaded(self) -> bool:
        return self._thread is not None

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_token_id: Optional[int] = None, tenant: str = "default",
               deadline_s: Optional[float] = None,
               seed: int = 0, n: int = 1):
        """Enqueue one prompt; returns a streaming handle immediately.
        ``deadline_s`` is relative to now (scheduler-clock seconds) and
        drives EDF ordering within the tenant. ``seed`` selects the
        request's sampling stream: draws depend only on (engine seed,
        request seed, output-token index) — reproducible regardless of how
        the scheduler batched the request, and stable across
        preemption/recompute. Raises ``scheduler.QueueFull`` past
        ``serving.max_queue`` in-flight requests (backpressure) and
        ``ValueError`` for prompts that cannot fit the ``max_model_len``
        budget.

        ``n > 1`` is parallel sampling: ONE prefill serves all ``n``
        samples — when it completes, ``n-1`` siblings fork the request's
        block table through the refcounted COW machinery (shared blocks,
        incref on fork; the first divergent write copies exactly one
        block). Sibling ``i`` samples with ``seed + i``, so each sample is
        bit-identical to a separately submitted request with that seed.
        Returns a list of ``n`` handles instead of one."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if n < 1:
            raise ValueError(f"submit(n={n}): need n >= 1")
        with self._lock:
            # pending (not-yet-forked) siblings hold real queue capacity:
            # submit_forked bypasses the scheduler's max_queue check, so
            # the reservation must be enforced here, against scheduler
            # occupancy PLUS every sibling still waiting for its fork
            in_flight = self.sched.in_flight() + self._pending_fork_count()
            if in_flight + n > self.config.max_queue:
                from .scheduler import QueueFull

                raise QueueFull(
                    f"serving queue cannot take {n} more request(s) "
                    f"({in_flight} in flight incl. pending forks, "
                    f"max_queue={self.config.max_queue})")

            def make(rid, sd, fork_of=None):
                return Request(
                    rid=rid, prompt=prompt.copy(),
                    max_new_tokens=(max_new_tokens
                                    if max_new_tokens is not None
                                    else self.config.default_max_new_tokens),
                    sampling=SamplingParams(temperature=float(temperature),
                                            top_k=int(top_k),
                                            top_p=float(top_p)),
                    eos_token_id=eos_token_id, tenant=tenant, seed=sd,
                    fork_of=fork_of,
                    deadline_s=(self.clock() + deadline_s
                                if deadline_s is not None else None))

            req = make(self._rid, seed)
            self.sched.submit(req)   # raises before rid is consumed
            self._rid += 1
            self._trace_start(req)
            handle = RequestHandle(self, req)
            self._handles[req.rid] = handle
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "serving/requests_submitted",
                    help="requests accepted into the serving queue").inc(
                        n, tenant=tenant)
            if n == 1:
                return handle
            sibs, handles = [], [handle]
            for i in range(1, n):
                sib = make(self._rid, seed + i, fork_of=req.rid)
                sib.arrival_s = req.arrival_s   # TTFT from the client's
                #   submit — the wait through the parent's prefill counts
                self._rid += 1
                self._trace_start(sib, parent_trace=req.trace)
                sibs.append(sib)
                h = RequestHandle(self, sib)
                self._handles[sib.rid] = h
                handles.append(h)
            self._pending_forks[req.rid] = sibs
            return handles

    def cancel(self, handle: RequestHandle) -> bool:
        cancelled = 0   # every cancellation this call caused — pre-fork
        #   siblings and parent-cascaded siblings included, so the
        #   requests_{submitted,completed,cancelled} ledger balances
        with self._lock:
            req = handle._req
            # a sibling cancelled before its fork point never reached the
            # scheduler — cancel it directly
            if req.fork_of is not None:
                sibs = self._pending_forks.get(req.fork_of, [])
                if req in sibs:
                    sibs.remove(req)
                    req.state = CANCELLED
                    req.finish_s = self.clock()
                    self.sched.cancelled_count += 1
                    self._handles.pop(req.rid, None)
                    self._count_cancelled(1)
                    self._trace_finish(req, "cancelled")
                    handle._wake()
                    return True
            ok = self.sched.cancel(req)
            cancelled += int(ok)
            if ok:
                self._trace_finish(req, "cancelled")
            # a cancelled parent takes its un-forked siblings with it
            for sib in self._pending_forks.pop(req.rid, []):
                sh = self._handles.pop(sib.rid, None)
                sib.state = CANCELLED
                sib.finish_s = self.clock()
                self.sched.cancelled_count += 1
                cancelled += 1
                self._trace_finish(sib, "cancelled")
                if sh is not None:
                    sh._wake()
            self._handles.pop(req.rid, None)
        self._count_cancelled(cancelled)
        handle._wake()
        return ok

    @staticmethod
    def _count_cancelled(n: int) -> None:
        if n:
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "serving/requests_cancelled",
                    help="requests cancelled before completion").inc(n)

    def _pending_fork_count(self) -> int:
        return sum(len(v) for v in self._pending_forks.values())

    # -- request tracing + serving goodput (observability) -----------------
    def _accountant(self):
        """Lazy ServeGoodput lookup: None until an enabled session with the
        ``serve_goodput`` gate exists (the disabled path wires nothing)."""
        acct = self._serve_acct
        if acct is None:
            obs = get_session()
            if obs.enabled and getattr(obs.config, "serve_goodput", False):
                from ..observability.servegoodput import ServeGoodput

                acct = self._serve_acct = ServeGoodput(
                    registry=obs.registry, replica=self.trace_tag,
                    clock=self.clock,
                    ttft_slo_ms=obs.config.serve_ttft_slo_ms,
                    tpot_slo_ms=obs.config.serve_tpot_slo_ms,
                    slo_budget=obs.config.serve_slo_budget)
        return acct

    def _maybe_tuner(self):
        """Lazy live-tuner lookup for SINGLE-engine deployments — fleet
        replicas return None unconditionally (the router owns the fleet's
        controller). Same discipline as :meth:`_accountant`: the disabled
        path is one cached-bool check, nothing allocated."""
        if self._fleet_managed:
            return None
        if self._tuner is None:
            obs = get_session()
            if obs is not self._tuner_obs:
                # probe once per session object: configure_observability
                # always builds a new session, so identity tracks
                # enable/replace without re-probing every iteration
                with self._lock:
                    self._tuner_obs = obs
                    if obs.enabled:
                        from ..autotuning.livetuner import maybe_make_tuner

                        self._tuner = maybe_make_tuner(self, obs)
        return self._tuner

    def _trace_start(self, req: Request, parent_trace=None) -> None:
        rt = get_session().reqtrace
        if rt is None:
            return
        req.trace = rt.start(
            tenant=req.tenant, t=self.clock(),
            fork_of=(parent_trace.trace_id if parent_trace is not None
                     else None),
            attrs={"rid": req.rid, "seed": req.seed,
                   "n_prompt": req.n_prompt,
                   "max_new_tokens": req.max_new_tokens})
        if parent_trace is not None:
            rt.link_fork(parent_trace, req.trace)

    def _trace_admitted(self, admitted: List[Request]) -> None:
        rt = get_session().reqtrace
        if rt is None:
            return
        now = self.clock()
        for req in admitted:
            if req.trace is not None:
                rt.admitted(req.trace, now, self.trace_tag, row=req.row)

    def _trace_preempt(self, req: Request) -> None:
        if req.trace is not None:
            rt = get_session().reqtrace
            if rt is not None:
                rt.preempted(req.trace, self.clock(), self.trace_tag)

    def _trace_finish(self, req: Request, state: str, **attrs: Any) -> None:
        if req.trace is None:
            return
        rt = get_session().reqtrace
        if rt is not None:
            rt.finish(req.trace, state, t=self.clock(), ttft_s=req.ttft_s,
                      tokens=len(req.generated), replica=self.trace_tag,
                      **attrs)

    def _trace_dispatch(self, rt, trace):
        """Context manager marking ``trace`` as the compile-attribution
        target while a device dispatch is open (nullcontext when tracing
        is off)."""
        if rt is None:
            return contextlib.nullcontext()
        return rt.active(trace)

    def in_flight(self) -> int:
        """Requests holding queue capacity: queued + running + parallel-
        sampling siblings still waiting for their parent's fork point."""
        with self._lock:
            return self.sched.in_flight() + self._pending_fork_count()

    # -- fleet seams (serving/fleet: router resubmission + KV handoff) -----
    def submit_recovered(self, prompt, generated, *,
                         max_new_tokens: int, temperature: float = 0.0,
                         top_k: int = 0, top_p: float = 1.0,
                         eos_token_id: Optional[int] = None,
                         tenant: str = "default",
                         deadline_s: Optional[float] = None,
                         seed: int = 0) -> RequestHandle:
        """Resubmit a request that was mid-stream on a DEAD engine: enqueue
        it in exactly the state the preemption machinery leaves a
        recompute-mode request in — prefill source is the original prompt
        plus every already-streamed token except the last, which becomes
        the authoritative ``pending_token`` — so decode resumes at
        output-token index ``len(generated)`` under the identical
        (engine seed, request seed, token index) sampling stream and the
        continued output is bit-identical to an uninterrupted run.
        Already-streamed tokens are never re-emitted (the fleet handle
        holds them); does NOT count ``serving/requests_submitted`` — the
        dead engine already did, and the fleet-wide ledger must balance."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        generated = [int(t) for t in generated]
        with self._lock:
            if (self.sched.in_flight() + self._pending_fork_count() + 1
                    > self.config.max_queue):
                from .scheduler import QueueFull

                raise QueueFull(
                    "serving queue cannot take the recovered request "
                    f"(max_queue={self.config.max_queue})")
            req = Request(
                rid=self._rid, prompt=prompt.copy(),
                max_new_tokens=max_new_tokens,
                sampling=SamplingParams(temperature=float(temperature),
                                        top_k=int(top_k),
                                        top_p=float(top_p)),
                eos_token_id=eos_token_id, tenant=tenant, seed=seed,
                deadline_s=(self.clock() + deadline_s
                            if deadline_s is not None else None))
            if generated:
                req.prompt = np.concatenate(
                    [prompt, np.asarray(generated[:-1],
                                        np.int32)]).astype(np.int32)
                req.generated = list(generated)
                req.pending_token = generated[-1]
                req.resume = True
            self.sched.submit(req)    # raises before rid is consumed
            self._rid += 1
            if generated:
                # TTFT already happened on the dead engine — the unset-
                # timestamp catch in _emit must not restamp it here
                req.first_token_s = req.arrival_s
            handle = RequestHandle(self, req)
            self._handles[req.rid] = handle
            return handle

    def adopt_prefilled(self, *, prompt, n_prompt: int, generated,
                        pending_token: int, length: int, blocks: List[int],
                        seed: int, sampling: SamplingParams,
                        max_new_tokens: int,
                        eos_token_id: Optional[int] = None,
                        tenant: str = "default",
                        deadline_s: Optional[float] = None) -> RequestHandle:
        """Adopt a request whose KV already sits in THIS engine's arena
        (fleet KV handoff): ``blocks`` must be blocks of this engine's
        allocator, freshly imported with the request's first ``length``
        positions resident. The request joins the queue fully prefilled —
        admission only needs a decode row — and its decode continues at
        output-token index ``len(generated)``, bit-identical to never
        having moved. ``prompt`` is the ORIGINAL prompt (a later preemption
        rebuilds the recompute source from prompt[:n_prompt] + generated).
        Raises ``QueueFull`` when this engine cannot take the request —
        the caller still owns ``blocks`` and must free them."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            if (self.sched.in_flight() + self._pending_fork_count() + 1
                    > self.config.max_queue):
                from .scheduler import QueueFull

                raise QueueFull(
                    "serving queue cannot adopt the handed-off request "
                    f"(max_queue={self.config.max_queue})")
            req = Request(
                rid=self._rid, prompt=prompt.copy(),
                max_new_tokens=max_new_tokens, sampling=sampling,
                eos_token_id=eos_token_id, tenant=tenant, seed=seed,
                n_prompt=int(n_prompt),
                deadline_s=(self.clock() + deadline_s
                            if deadline_s is not None else None))
            self._rid += 1
            req.generated = [int(t) for t in generated]
            req.pending_token = int(pending_token)
            req.length = int(length)
            req.prefill_pos = int(req.prompt.size)
            req.blocks = list(blocks)
            # every emitted token (incl. the prefill-completion one) was
            # streamed by the source engine; TTFT belongs to it
            req.first_token_s = self.clock()
            self.sched.submit_forked(req)
            handle = RequestHandle(self, req)
            self._handles[req.rid] = handle
            return handle

    def release_for_handoff(self, req: Request) -> None:
        """Release a request whose KV was exported to another engine:
        terminal for this engine (row/blocks freed, handle dropped)
        without touching the completion ledger."""
        with self._lock:
            self.sched.release_handoff(req)
            self._handles.pop(req.rid, None)
            if req.trace is not None:
                rt = get_session().reqtrace
                if rt is not None:
                    rt.event(req.trace, "handoff_release", t=self.clock(),
                             replica=self.trace_tag)

    # -- weight flip (RLHF hybrid engine) ----------------------------------
    def note_weights_updated(self) -> int:
        """The wrapped engine's params were just refreshed in place (the
        hybrid-engine train→serve flip). The arena ALLOCATION survives —
        block pool, compiled prefill/decode/verify/cow/score programs and
        scheduler state are all keyed on shapes, which a weight refresh
        never changes — but cached KV CONTENT is a function of the params,
        so every prefix-cache entry is invalidated (its content hash
        describes bytes that no longer exist). Requires an idle engine:
        in-flight requests hold KV computed under the OLD weights and
        cannot be continued coherently. Returns the number of prefix-cache
        entries dropped."""
        with self._lock:
            if self.sched.in_flight() or self._pending_fork_count():
                raise RuntimeError(
                    "weight flip with requests in flight "
                    f"({self.sched.in_flight()} scheduled, "
                    f"{self._pending_fork_count()} pending forks) — drain "
                    "the engine before refresh (their KV was computed "
                    "under the old weights)")
            self.weight_refreshes += 1
            dropped = 0
            if self.prefix is not None:
                dropped = self.prefix.clear()
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "serving/weight_refreshes",
                    help="hybrid-engine weight flips absorbed without "
                         "arena realloc").inc()
                if dropped:
                    obs.registry.counter(
                        "serving/prefix_invalidations",
                        help="prefix-cache entries dropped by weight "
                             "flips (stale content hashes)").inc(dropped)
            return dropped

    # -- teacher-forced scoring (the RLHF second serving pass) -------------
    def score_logprobs(self, tokens, params: Optional[Any] = None
                       ) -> np.ndarray:
        """Per-position log-probabilities of a full sequence under
        ``params`` (default: the engine's current weights): returns
        ``logp`` of shape ``(len(tokens) - 1,)`` where ``logp[p]`` is the
        model's log-probability of ``tokens[p + 1]`` given
        ``tokens[:p + 1]``. Runs through the SAME paged arena in
        prefill-chunk-sized pieces over scratch blocks allocated from the
        pool (evicting unpinned prefix-cache entries under pressure, never
        preempting) and freed before returning. Passing a resharded
        frozen-reference tree as ``params`` reuses the one compiled score
        program — the RLHF reference-logprob pass costs zero extra
        compiles."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        T = int(tokens.size)
        if T < 2:
            raise ValueError(f"score_logprobs needs >= 2 tokens, got {T}")
        if T > self.config.max_model_len:
            raise ValueError(
                f"score_logprobs: sequence of {T} tokens exceeds "
                f"serving.max_model_len={self.config.max_model_len}")
        C = self.config.prefill_chunk
        with self._lock:
            need = paged_kv.blocks_for_tokens(T, self.config.block_size)
            ids = self.sched._alloc_evicting_cache(need)
            if ids is None:
                raise RuntimeError(
                    f"score_logprobs: cannot allocate {need} scratch "
                    f"blocks ({self.alloc.blocks_free} free) — score after "
                    "rollouts drain, or grow serving.num_blocks")
            try:
                bt = np.zeros((1, self.blocks_per_seq), np.int32)
                bt[0, :need] = ids
                if params is None:
                    params = self.engine.params
                out = np.zeros((T - 1,), np.float32)
                obs = get_session()
                with mesh_mod.ambient(self.engine.mesh):
                    for start in range(0, T, C):
                        n_valid = min(C, T - start)
                        chunk = np.zeros((1, C), np.int32)
                        chunk[0, :n_valid] = tokens[start:start + n_valid]
                        # the target for position p is tokens[p + 1]; the
                        # final sequence position has none
                        nt = min(n_valid, T - 1 - start)
                        tgt = np.zeros((1, C), np.int32)
                        if nt > 0:
                            tgt[0, :nt] = tokens[start + 1:start + 1 + nt]
                        with obs.span("serving/score_chunk",
                                      tokens=int(n_valid)):
                            lp, self._arena = self._score(
                                params, self._arena, bt, chunk, tgt,
                                np.asarray(start, np.int32),
                                np.asarray(n_valid, np.int32))
                            lp = np.asarray(lp)   # fence: chunk really ran
                        if nt > 0:
                            out[start:start + nt] = lp[0, :nt]
            finally:
                self.alloc.free(ids)
        return out

    # -- the iteration -----------------------------------------------------
    def step(self) -> bool:
        """One continuous-batching iteration; returns True when any request
        made progress (admission, a prefill chunk, a decode token, or a
        deadline expiry reclaiming its resources)."""
        with self._lock:
            acct = self._accountant()
            if acct is not None:
                acct.iteration_begin(self.clock())
            try:
                # before admit: an already-expired queued request must
                # never take a decode row first
                progress = self._expire_deadlines()
                admitted = self.sched.admit()
                progress |= bool(admitted)
                if admitted:
                    self._trace_admitted(admitted)
                for _ in range(max(int(self.prefill_chunks_per_iter), 1)):
                    # tpusync: disable=lock-order-inversion — the SE->FR
                    # edge (prefill-complete handoff) and the FR->SE edge
                    # (router submit/step) are both RLock re-entries on the
                    # one thread that drives a fleet: engines under a
                    # router are stepped only from FleetRouter.step, which
                    # already holds FR
                    ran_chunk = self._step_prefill()
                    progress |= ran_chunk
                    if not ran_chunk:
                        break
                progress |= (self._step_verify()
                             if self._drafter is not None
                             and not self.spec_suspended
                             else self._step_decode())
                self._publish_iteration()
                it = self._iterations
                self._iterations += 1
            finally:
                if acct is not None:
                    acct.iteration_end(self.clock())
                    # gauge refresh at a cadence, always AFTER the window
                    # closed (wall and buckets stay consistent): per-
                    # iteration publishing would put O(window) breach-deque
                    # scans on the decode loop's critical path. close()
                    # publishes the final snapshot.
                    if acct.iterations % 16 == 1:
                        acct.publish()
        # the live tuner's decision tick runs OUTSIDE the engine lock: the
        # controller is foreign code with its own lock, and its knob writes
        # are plain scheduling attributes — keeping it out of the critical
        # section keeps the lock graph acyclic (tools/tpusync)
        tuner = self._maybe_tuner()
        if tuner is not None:
            tuner.on_iteration(it)
        # deep-profiler tick, same discipline: trigger polling and window
        # open/close do their own locking and may dispatch (start_trace)
        prof = get_session().profiler
        if prof is not None:
            prof.on_iteration(it)
        return progress

    def _expire_deadlines(self) -> bool:
        """Deadline enforcement at decode time: a request whose absolute
        deadline passed finishes as ``deadline_exceeded`` NOW — rows and
        blocks free at this iteration boundary instead of decoding to its
        token budget — and its un-forked siblings (who could never fork
        anymore) expire with it. The ledger stays balanced:
        submitted == completed + cancelled + deadline_exceeded."""
        now = self.clock()
        expired = self.sched.expire_deadlines(now)
        if not expired:
            return False
        from .scheduler import DEADLINE_EXCEEDED

        for req in list(expired):
            for sib in self._pending_forks.pop(req.rid, []):
                sib.state = DEADLINE_EXCEEDED
                sib.finish_s = now
                self.sched.deadline_exceeded_count += 1
                expired.append(sib)
        obs = get_session()
        for req in expired:
            if obs.enabled:
                obs.registry.counter(
                    "serving/requests_deadline_exceeded",
                    help="requests terminated at an iteration boundary "
                         "after their deadline passed").inc(
                             tenant=req.tenant)
            # the ring carries the victim's id even with tracing disabled:
            # a crash bundle from a fleet incident names its requests
            obs.flight_event(
                "req_terminal", event="deadline_exceeded", rid=req.rid,
                tenant=req.tenant,
                trace_id=(req.trace.trace_id if req.trace is not None
                          else None))
            self._trace_finish(req, "deadline_exceeded")
            handle = self._handles.pop(req.rid, None)
            if handle is not None:
                handle._wake()
        return True

    def _table_for(self, reqs: List[Request]) -> np.ndarray:
        """(len(reqs), MAXB) block table; unfilled entries → scratch 0."""
        bt = np.zeros((len(reqs), self.blocks_per_seq), np.int32)
        for i, r in enumerate(reqs):
            if r.blocks:
                bt[i, :len(r.blocks)] = r.blocks
        return bt

    @staticmethod
    def _sampling_arrays(reqs: List[Request]):
        return (np.asarray([r.sampling.temperature for r in reqs],
                           np.float32),
                np.asarray([r.sampling.top_k for r in reqs], np.int32),
                np.asarray([r.sampling.top_p for r in reqs], np.float32),
                np.asarray([r.seed for r in reqs], np.int32))

    def _make_writable(self, req: Request, start: int, end: int,
                       optional: bool = False) -> bool:
        """Copy-on-write: every block covering write positions
        [start, end) must be exclusively owned before the jitted program
        scatters into it. Shared blocks (prefix sharing, refcount > 1) are
        duplicated on device and swapped into the request's table; the
        sharers keep the original. Returns False when the pool can't
        provide a private copy this iteration — the caller skips the
        request; copies already made stay (they are real private blocks,
        the retry skips them). ``optional`` marks speculative work: the
        copy comes from plain allocation only — no cache eviction, no
        preemption — because speculation must never cost anyone else
        their blocks."""
        for bi in self.sched.cow_block_indices(req, start, end):
            if optional:
                ids = self.alloc.alloc(1)
                nid = ids[0] if ids else None
            else:
                nid = self.sched.alloc_for_cow(req)
            if nid is None:
                return False
            old = req.blocks[bi]
            obs = get_session()
            with mesh_mod.ambient(self.engine.mesh):
                with obs.span("serving/cow_copy"):
                    self._arena = self._cow(self._arena,
                                            np.asarray(old, np.int32),
                                            np.asarray(nid, np.int32))
            req.blocks[bi] = nid
            self.alloc.free([old])   # drop THIS request's shared reference
            self._cow_copies += 1
        return True

    def _step_prefill(self) -> bool:
        req = self.sched.next_prefill()
        if req is None:
            return False
        C = self.config.prefill_chunk
        src = req.prompt
        start = req.prefill_pos
        n_valid = min(C, int(src.size) - start)
        if not self.sched.ensure_blocks(req, start + n_valid):
            return False    # pool dry, nothing evictable — wait a turn
        if not self._make_writable(req, start, start + n_valid):
            return False    # shared block needs a copy the pool can't give
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :n_valid] = src[start:start + n_valid]
        temps, topks, topps, seeds = self._sampling_arrays([req])
        obs = get_session()
        rt = obs.reqtrace
        acct = self._serve_acct
        timed = acct is not None or (rt is not None
                                     and req.trace is not None)
        t0 = self.clock() if timed else 0.0
        with self._trace_dispatch(rt, req.trace):
            with mesh_mod.ambient(self.engine.mesh):
                with obs.span("serving/prefill_chunk", batch=1,
                              tokens=int(n_valid)):
                    tok, _last, self._arena = self._prefill(
                        self.engine.params, self._arena,
                        self._table_for([req]), chunk,
                        np.asarray(start, np.int32),
                        np.asarray(n_valid, np.int32),
                        temps, topks, topps, seeds, self._base_rng)
                    tok = np.asarray(tok)   # the fence: chunk really ran
        if timed:
            t1 = self.clock()
            if acct is not None:
                acct.note_phase("prefill", t1 - t0)
            if rt is not None and req.trace is not None:
                rt.interval(req.trace, "prefill", t0, t1,
                            kind="prefill_chunk", tokens=int(n_valid),
                            chunk_start=int(start), replica=self.trace_tag)
        self.prefill_chunks_run += 1
        self.prefill_tokens_run += int(n_valid)
        req.prefill_pos += n_valid
        req.length = req.prefill_pos
        # newly completed full prompt blocks become shareable prefix cache
        self.sched.note_prefill_progress(req, start, req.prefill_pos)
        self.sched.note_service(req, n_valid)
        if req.prefill_pos == int(src.size):
            req.state = DECODE
            # the COW fork point for submit(n=...): siblings share the
            # freshly prefilled blocks BEFORE the parent can finish (a
            # max_new_tokens=1 parent releases its refs in _emit below;
            # the siblings' increfs keep the blocks alive)
            self._submit_pending_forks(req)
            if req.resume:
                # recompute after preemption: the stored pending token is
                # authoritative (identical under greedy; under temperature
                # sampling the resampled one may diverge) and was already
                # streamed — never re-emit
                req.resume = False
            else:
                self._emit(req, int(tok[0]), first=True)
            if (self.on_prefill_complete is not None
                    and req.state == DECODE):
                # still DECODE: a max_new_tokens=1 request already finished
                # in _emit above and has nothing left to hand off.
                # tpusync: disable=callback-under-lock — router-bound seam,
                # not user code; the handoff must see the request frozen at
                # prefill completion, so it runs under the engine lock
                self.on_prefill_complete(req)
        return True

    # -- parallel-sampling fork (COW) --------------------------------------
    def _submit_pending_forks(self, req: Request) -> None:
        """Parent finished prefill: attach each waiting sibling to the
        SAME physical blocks (incref — shared until first divergent write)
        and hand it to the scheduler, fully prefilled. The sibling's
        ``pending_token`` is the final prompt token at ``length =
        n_prompt - 1``: its first decode re-runs only that one position —
        a COW copy of at most one block — and samples its own first token
        with its own seed at output-token index 0, bit-identical to a
        separately submitted request."""
        sibs = self._pending_forks.pop(req.rid, None)
        if not sibs:
            return
        for sib in sibs:
            sib.blocks = list(req.blocks)
            self.alloc.incref(sib.blocks)
            sib.prefill_pos = int(sib.prompt.size)
            sib.length = sib.n_prompt - 1
            sib.pending_token = int(sib.prompt[-1])
            self.sched.submit_forked(sib)
            self._forks += 1

    def fork(self, handle: RequestHandle, n: int,
             seeds: Optional[List[int]] = None) -> List[RequestHandle]:
        """Mid-stream fork: ``n`` new samples branching off ``handle``'s
        request AT ITS CURRENT POSITION — shared prompt AND
        generated-so-far blocks (incref; first divergent write goes
        copy-on-write), inherited emitted tokens, divergence from the next
        token on (each sibling samples output-token index
        ``len(generated)`` with its own seed). The parent must be actively
        decoding. Returns the new handles."""
        if n < 1:
            raise ValueError(f"fork(n={n}): need n >= 1")
        if seeds is not None and len(seeds) < n:
            raise ValueError(f"fork(n={n}): seeds has {len(seeds)} "
                             "entries — need one per sibling")
        with self._lock:
            req = handle._req
            if req.state != DECODE:
                raise ValueError(
                    f"request {req.rid}: fork requires an actively "
                    f"decoding request (state='{req.state}')")
            if (self.sched.in_flight() + self._pending_fork_count() + n
                    > self.config.max_queue):
                from .scheduler import QueueFull

                raise QueueFull(
                    f"serving queue cannot take {n} forked samples")
            out: List[RequestHandle] = []
            now = self.clock()
            for i in range(n):
                sib = Request(
                    rid=self._rid, prompt=req.prompt.copy(),
                    max_new_tokens=req.max_new_tokens,
                    sampling=req.sampling, eos_token_id=req.eos_token_id,
                    tenant=req.tenant,
                    seed=(seeds[i] if seeds is not None
                          else req.seed + i + 1),
                    fork_of=req.rid, n_prompt=req.n_prompt)
                self._rid += 1
                self._trace_start(sib, parent_trace=req.trace)
                sib.generated = list(req.generated)
                sib.pending_token = req.pending_token
                sib.length = req.length
                sib.prefill_pos = int(sib.prompt.size)
                sib.blocks = list(req.blocks)
                self.alloc.incref(sib.blocks)
                if sib.generated:
                    sib.first_token_s = now   # inherited tokens are
                    #   already streamed below — TTFT is fork-time
                self.sched.submit_forked(sib)
                h = RequestHandle(self, sib)
                for t in sib.generated:
                    h._push(t)
                self._handles[sib.rid] = h
                out.append(h)
                self._forks += 1
            return out

    def _ready_decode_rows(self) -> List[Request]:
        """The decode-readiness discipline shared by the plain and
        speculative iterations: guarantee the pending token's block for
        every decoding row (this may evict), then keep only rows that are
        still DECODE, have block coverage for the incoming write, and
        whose write block is exclusively owned."""
        dec = self.sched.decode_requests()
        if not dec:
            return []
        for r in dec:
            # re-check state INSIDE the loop: an earlier ensure_blocks may
            # have evicted this very request — growing a now-QUEUED request
            # would hand pool blocks to a non-running request (and, pool
            # dry, let it evict an active one)
            if r.state == DECODE:
                self.sched.ensure_blocks(r, r.length + 1)
        ready = []
        for r in dec:
            if r.state != DECODE:
                continue
            if len(r.blocks) * self.config.block_size <= r.length:
                continue
            # the incoming token's block must be exclusively owned —
            # writing into a prefix-shared block would corrupt the sharers
            if not self._make_writable(r, r.length, r.length + 1):
                continue
            ready.append(r)
        # a later row's COW may have preempted an earlier accepted row
        return [r for r in ready if r.state == DECODE]

    def _step_decode(self) -> bool:
        ready = self._ready_decode_rows()
        if not ready:
            return False
        R = self.config.max_seqs
        bt = np.zeros((R, self.blocks_per_seq), np.int32)
        lengths = np.zeros((R,), np.int32)
        tokens = np.zeros((R,), np.int32)
        temps = np.zeros((R,), np.float32)
        topks = np.zeros((R,), np.int32)
        topps = np.ones((R,), np.float32)
        seeds = np.zeros((R,), np.int32)
        steps = np.zeros((R,), np.int32)
        for r in ready:
            row = r.row
            bt[row, :len(r.blocks)] = r.blocks
            lengths[row] = r.length
            tokens[row] = r.pending_token
            temps[row] = r.sampling.temperature
            topks[row] = r.sampling.top_k
            topps[row] = r.sampling.top_p
            seeds[row] = r.seed
            steps[row] = len(r.generated)   # output-token index: the
            #   sampling stream is (engine seed, request seed, index) —
            #   schedule-independent and preemption-stable
        obs = get_session()
        rt = obs.reqtrace
        acct = self._serve_acct
        timed = acct is not None or rt is not None
        t0 = self.clock() if timed else 0.0
        first_trace = (next((r.trace for r in ready
                             if r.trace is not None), None)
                       if rt is not None else None)
        with self._trace_dispatch(rt, first_trace):
            with mesh_mod.ambient(self.engine.mesh):
                with obs.span("serving/decode", batch=len(ready)):
                    nxt, self._arena = self._decode(
                        self.engine.params, self._arena, bt, lengths,
                        tokens, temps, topks, topps, seeds, steps,
                        self._base_rng)
                    nxt = np.asarray(nxt)  # the iteration's one host sync
        t1 = self.clock() if timed else 0.0
        if acct is not None:
            acct.note_phase("decode", t1 - t0)
        if rt is not None:
            for r in ready:
                if r.trace is not None:
                    rt.note_decode(r.trace, t0, t1, batch=len(ready),
                                   replica=self.trace_tag)
        for r in ready:
            r.length += 1
            self.sched.note_service(r, 1)
            self._emit(r, int(nxt[r.row]))
        if acct is not None:
            acct.note_phase("sample_host", self.clock() - t1)
        return True

    def _step_verify(self) -> bool:
        """The speculative iteration: one R×(K+1) verify dispatch replaces
        the R×1 decode. Every decoding row rides it — rows with no (or
        pressure-disabled) proposals verify only their pending token,
        which IS the plain decode — so per-row proposal counts and
        acceptance mixes are data under ONE compiled program. Accepted
        tokens advance lengths/blocks on the host; rejected draft KV rolls
        back by position (whole blocks past the accepted length return to
        the pool)."""
        # the guaranteed (pending-token) block may evict via
        # _ready_decode_rows — speculation itself never does
        ready = self._ready_decode_rows()
        if not ready:
            return False
        spec = self.config.speculative
        K = spec.num_draft_tokens
        S = K + 1
        # per-row proposal budget: output budget (the verify emits up to
        # cap+1 tokens), model-length budget, and the global pool guard
        low_pool = self.alloc.blocks_free < spec.min_free_blocks
        caps = []
        for r in ready:
            cap = min(K,
                      r.max_new_tokens - len(r.generated) - 1,
                      self.config.max_model_len - r.length - 1)
            caps.append(0 if low_pool else max(cap, 0))
        t0 = self.clock()
        proposals = self._drafter.propose(ready, caps)
        draft_s = self.clock() - t0
        self._spec_draft_s += draft_s
        if self._serve_acct is not None:
            self._serve_acct.note_phase("draft", draft_s)
        # speculating may preempt nothing, but the drafter's catch-up runs
        # under the engine lock with live state — re-check anyway
        plan = []
        for r, cap, prop in zip(ready, caps, proposals):
            prop = np.asarray(prop, np.int32).reshape(-1)[:cap]
            n = int(prop.size)
            if n > 0 and not self.sched.try_extend_blocks(
                    r, r.length + 1 + n):
                # pool says no: speculate only as far as already-held
                # blocks reach (possibly 0) — never evict for speculation
                held = len(r.blocks) * self.config.block_size \
                    - (r.length + 1)
                n = max(min(n, held), 0)
                self._spec_disabled_rows += 1
            if n > 0 and not self._make_writable(
                    r, r.length + 1, r.length + 1 + n, optional=True):
                n = 0   # shared draft-range block with no COW budget
            plan.append((r, prop[:n]))
        # a later row's COW/extension bookkeeping may have preempted an
        # earlier planned row — plan only rows still decoding
        plan = [(r, p) for r, p in plan if r.state == DECODE]
        if not plan:
            return False
        R = self.config.max_seqs
        bt = np.zeros((R, self.blocks_per_seq), np.int32)
        lengths = np.zeros((R,), np.int32)
        tokens = np.zeros((R, S), np.int32)
        n_valid = np.zeros((R,), np.int32)
        temps = np.zeros((R,), np.float32)
        topks = np.zeros((R,), np.int32)
        topps = np.ones((R,), np.float32)
        seeds = np.zeros((R,), np.int32)
        steps = np.zeros((R,), np.int32)
        for r, prop in plan:
            row = r.row
            bt[row, :len(r.blocks)] = r.blocks
            lengths[row] = r.length
            tokens[row, 0] = r.pending_token
            if prop.size:
                tokens[row, 1:1 + prop.size] = prop
            n_valid[row] = 1 + prop.size
            temps[row] = r.sampling.temperature
            topks[row] = r.sampling.top_k
            topps[row] = r.sampling.top_p
            seeds[row] = r.seed
            steps[row] = len(r.generated)   # first output-token index of
            #   this dispatch — position j samples index steps+j, the
            #   exact key the non-speculative path uses
        obs = get_session()
        rt = obs.reqtrace
        acct = self._serve_acct
        first_trace = (next((r.trace for r, _ in plan
                             if r.trace is not None), None)
                       if rt is not None else None)
        t0 = self.clock()
        with self._trace_dispatch(rt, first_trace):
            with mesh_mod.ambient(self.engine.mesh):
                with obs.span("serving/verify", batch=len(plan),
                              tokens=int(n_valid.sum())):
                    sampled, self._arena = self._verify(
                        self.engine.params, self._arena, bt, lengths,
                        tokens, n_valid, temps, topks, topps, seeds, steps,
                        self._base_rng)
                    sampled = np.asarray(sampled)  # the iteration's 1 sync
        t1 = self.clock()
        self._spec_verify_s += t1 - t0
        if acct is not None:
            acct.note_phase("verify", t1 - t0)
        if rt is not None:
            for r, _ in plan:
                if r.trace is not None:
                    rt.note_decode(r.trace, t0, t1, kind="verify",
                                   batch=len(plan), replica=self.trace_tag)
        self._spec_dispatches += 1
        for r, prop in plan:
            x = sampled[r.row]
            a = 0   # accepted drafts: x[j] (the sample after draft j) must
            #   CONFIRM draft j — first mismatch emits x[a] as the
            #   correction, full acceptance emits x[cap] as the bonus
            while a < prop.size and int(x[a]) == int(prop[a]):
                a += 1
            r.spec_proposed += int(prop.size)
            r.spec_accepted += a
            self._spec_proposed += int(prop.size)
            self._spec_accepted += a
            for t in x[:a + 1]:
                r.length += 1
                self.sched.note_service(r, 1)
                self._emit(r, int(t))
                self._spec_emitted += 1
                if r.done:
                    break   # EOS/budget mid-verify: later samples are
                    #   beyond the request's end — never emitted
            if not r.done:
                # positional rollback: whole blocks past the accepted
                # length go back to the pool; the drafter rolls its arena
                # back the same way
                self.sched.truncate_blocks(r, r.length)
                self._drafter.commit(r)
        if acct is not None:
            acct.note_phase("sample_host", self.clock() - t1)
        return True

    def _emit(self, req: Request, token: int, first: bool = False) -> None:
        now = self.clock()
        obs = get_session()
        # ``first`` marks the prefill-completion emit; a submit(n=...)
        # sibling skips prefill entirely (admitted straight to DECODE with
        # the parent's KV) and its first token arrives through the
        # decode/verify path — catch it by the unset timestamp so TTFT/
        # TPOT cover forked samples too
        if first or req.first_token_s is None:
            req.first_token_s = now
            if obs.enabled:
                ttft_ms = (now - req.arrival_s) * 1e3
                self._ttft_samples.append(ttft_ms)
                obs.registry.histogram(
                    "serving/ttft_ms",
                    help="arrival → first streamed token, wall ms").observe(
                        ttft_ms, tenant=req.tenant)
        req.generated.append(token)
        req.pending_token = token
        self._tokens_out += 1
        if req.trace is not None:
            # live progress marker: a crash dump's in-flight tail must say
            # how far each stuck request got (finish() re-stamps the
            # authoritative count from len(generated))
            req.trace.tokens += 1
        if self._serve_acct is not None:
            self._serve_acct.note_tokens(1)
        handle = self._handles.get(req.rid)
        if handle is not None:
            handle._push(token)
        finished = (len(req.generated) >= req.max_new_tokens
                    or (req.eos_token_id is not None
                        and token == req.eos_token_id))
        if finished:
            self.sched.finish(req)
            if self._drafter is not None and req.spec_proposed:
                self._accept_samples.append(
                    req.spec_accepted / req.spec_proposed)
            if obs.enabled:
                obs.registry.counter(
                    "serving/requests_completed",
                    help="requests that finished generation").inc(
                        tenant=req.tenant)
                tpot = req.tpot_s
                if tpot is not None:
                    self._tpot_samples.append(tpot * 1e3)
                    obs.registry.histogram(
                        "serving/tpot_ms",
                        help="mean per-token wall ms after the first "
                             "token").observe(tpot * 1e3, tenant=req.tenant)
            if self._serve_acct is not None:
                ttft, tpot = req.ttft_s, req.tpot_s
                self._serve_acct.note_request(
                    ttft_ms=ttft * 1e3 if ttft is not None else None,
                    tpot_ms=tpot * 1e3 if tpot is not None else None)
            self._trace_finish(req, "finished")
            self._handles.pop(req.rid, None)   # the client holds its own
            #   reference; keeping ours would leak one handle per request
            #   over a server's lifetime
            if handle is not None:
                handle._wake()

    def _publish_iteration(self) -> None:
        obs = get_session()
        if not obs.enabled:
            return
        reg = obs.registry
        reg.gauge("serving/queue_depth",
                  help="requests waiting for admission").set(
                      self.sched.queue_depth())
        reg.gauge("serving/kv_blocks_in_use",
                  help="allocated arena blocks").set(self.alloc.blocks_in_use)
        reg.gauge("serving/kv_blocks_peak",
                  help="peak allocated arena blocks").set(
                      self.alloc.peak_in_use)
        reg.gauge("serving/arena_occupancy",
                  help="allocated fraction of the block pool").set(
                      self.alloc.blocks_in_use / max(self.alloc.capacity, 1))
        reg.gauge("serving/decode_batch_occupancy",
                  help="decoding rows / max_seqs").set(
                      len(self.sched.decode_requests())
                      / self.config.max_seqs)
        reg.gauge("serving/kv_blocks_shared",
                  help="arena blocks referenced by more than one "
                       "holder (prefix sharing)").set(
                      self.alloc.blocks_shared)
        reg.gauge("serving/kv_blocks_shared_peak",
                  help="peak concurrently-shared arena blocks").set(
                      self.alloc.peak_shared)
        if self.prefix is not None:
            reg.gauge("serving/prefix_hit_rate",
                      help="prompt tokens served from the prefix cache / "
                           "prompt tokens of admitted requests").set(
                          self.sched.prefix_hit_tokens
                          / max(self.sched.prefix_lookup_tokens, 1))
            reg.gauge("serving/prefix_cache_blocks",
                      help="blocks pinned by the prefix cache").set(
                          self.prefix.cached_blocks)
        new_cow = self._cow_copies - self._published_cow
        if new_cow:
            reg.counter("serving/cow_copies",
                        help="copy-on-write block duplications (first "
                             "write into a shared block)").inc(new_cow)
            self._published_cow = self._cow_copies
        new_preempt = self.sched.preemption_count \
            - self._published_preemptions
        if new_preempt:
            reg.counter("serving/preemptions",
                        help="requests evicted from the arena "
                             "(recompute on re-admission)").inc(new_preempt)
            self._published_preemptions = self.sched.preemption_count
        new_forks = self._forks - self._published_forks
        if new_forks:
            reg.counter("serving/forks",
                        help="parallel-sampling siblings forked through "
                             "the COW block tables").inc(new_forks)
            self._published_forks = self._forks
        if self._drafter is not None:
            p0, a0, d0, x0 = self._published_spec
            dp = self._spec_proposed - p0
            da = self._spec_accepted - a0
            dd = self._spec_dispatches - d0
            dx = self._spec_disabled_rows - x0
            if dp:
                reg.counter("serving/spec_proposed_tokens",
                            help="draft tokens sent to verify").inc(dp)
            if da:
                reg.counter("serving/spec_accepted_tokens",
                            help="draft tokens the verify confirmed").inc(da)
            if dd:
                reg.counter("serving/spec_verify_dispatches",
                            help="R×(K+1) verify program dispatches").inc(dd)
            if dx:
                reg.counter("serving/spec_disabled_rows",
                            help="row-iterations that skipped speculation "
                                 "under pool pressure").inc(dx)
            self._published_spec = (self._spec_proposed,
                                    self._spec_accepted,
                                    self._spec_dispatches,
                                    self._spec_disabled_rows)
            reg.gauge("serving/spec_acceptance_rate",
                      help="accepted / proposed draft tokens").set(
                          self._spec_accepted
                          / max(self._spec_proposed, 1))
            reg.gauge("serving/spec_emitted_per_dispatch",
                      help="tokens emitted per target verify dispatch "
                           "(> 1 is the speculative win)").set(
                          self._spec_emitted
                          / max(self._spec_dispatches, 1))
            spent = self._spec_draft_s + self._spec_verify_s
            if spent > 0:
                reg.gauge("serving/spec_draft_time_share",
                          help="drafter wall share of the speculative "
                               "decode loop").set(self._spec_draft_s
                                                  / spent)
        # steady-state marker for the recompile watchdog: past warmup, a
        # recompile under a serving span is a shape-discipline bug
        obs.note_step(self._iterations)

    # -- drivers -----------------------------------------------------------
    def run(self, max_steps: Optional[int] = None) -> int:
        """Step until every in-flight request is terminal (tests/benches).
        Returns the number of iterations run."""
        steps = 0
        starved = 0
        while self.in_flight():
            progress = self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            if progress:
                starved = 0
            else:
                starved += 1
                if starved > 2 * self.config.max_queue + 4:
                    raise RuntimeError(
                        "serving stalled: no request can make progress "
                        f"({self.sched.queue_depth()} queued, "
                        f"{self.alloc.blocks_free} free blocks) — the block "
                        "pool or row count is too small for the workload")
        return steps

    def start(self) -> None:
        """Background driver thread (the 'server' mode): steps while work is
        in flight, idles cheaply otherwise."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._drive,
                                        name="dstpu-serving", daemon=True)
        self._thread.start()

    def _drive(self) -> None:
        while not self._stop.is_set():
            try:
                if self.in_flight():
                    self.step()
                else:
                    self._stop.wait(0.002)
            except Exception:
                logger.exception("serving driver step failed")
                get_session().crash_dump("serving-step-exception")
                self._stop.wait(0.05)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.stop()
        if self._tuner is not None:
            self._tuner.finalize()     # recommendations artifact
        if self._drafter is not None:
            self._drafter.close()
        if self._serve_acct is not None:
            self._serve_acct.publish()   # final bucket snapshot
        self.publish_latency_gauges()

    def publish_latency_gauges(self) -> None:
        """Host-side percentile gauges (the registry histogram keeps only
        count/sum/min/max): serving/ttft_p50_ms, p99, tpot p50/p99, and the
        end-to-end tokens/s — the ``report`` CLI's ``== serving ==``
        inputs."""
        obs = get_session()
        if not obs.enabled:
            return
        reg = obs.registry
        for name, samples in (("ttft", self._ttft_samples),
                              ("tpot", self._tpot_samples)):
            if samples:
                reg.gauge(f"serving/{name}_p50_ms").set(
                    _percentile(list(samples), 0.50))
                reg.gauge(f"serving/{name}_p99_ms").set(
                    _percentile(list(samples), 0.99))
        if self._accept_samples:
            reg.gauge("serving/spec_acceptance_p50",
                      help="per-request draft acceptance rate, median "
                           "over finished requests").set(
                          _percentile(list(self._accept_samples), 0.50))
        wall = max(self.clock() - self._started_s, 1e-9)
        reg.gauge("serving/tokens_per_sec",
                  help="generated tokens / wall seconds").set(
                      self._tokens_out / wall)

    def reset_latency_stats(self) -> None:
        """Drop the host-side latency reservoirs and restart the
        tokens/s window — benches call this after their warmup request so
        the published p50/p99/tokens_per_sec describe the measured load,
        not program compilation. The speculative ledger resets too: the
        warmup's verify/draft dispatches JIT-compile inside the timed
        accumulators, which would otherwise dominate draft_time_share and
        skew acceptance/emitted-per-dispatch."""
        with self._lock:
            self._ttft_samples.clear()
            self._tpot_samples.clear()
            self._accept_samples.clear()
            self._tokens_out = 0
            self._started_s = self.clock()
            self._spec_dispatches = 0
            self._spec_emitted = 0
            self._spec_proposed = 0
            self._spec_accepted = 0
            self._spec_disabled_rows = 0
            self._spec_draft_s = 0.0
            self._spec_verify_s = 0.0
            self._forks = 0
            # published snapshots must rewind with the raw counts or the
            # next _publish_iteration would compute negative counter deltas
            self._published_spec = (0, 0, 0, 0)
            self._published_forks = 0
            if self._serve_acct is not None:
                # warmup iterations carry compile-scale phases — the
                # published buckets must describe the measured load
                self._serve_acct.reset()

    # -- tpuaudit ----------------------------------------------------------
    def _audit_args_prefill(self):
        import jax
        import jax.numpy as jnp

        cfg = self.engine.model.config
        C, MAXB = self.config.prefill_chunk, self.blocks_per_seq
        i32 = jnp.int32
        return (self.engine._params_sds(),
                self._arena_sds(),
                jax.ShapeDtypeStruct((1, MAXB), i32),
                jax.ShapeDtypeStruct((1, C), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((1,), jnp.float32),
                jax.ShapeDtypeStruct((1,), i32),
                jax.ShapeDtypeStruct((1,), jnp.float32),
                jax.ShapeDtypeStruct((1,), i32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))

    def _arena_sds(self):
        from ..inference.kv_cache import paged_cache_shape_struct

        return paged_cache_shape_struct(
            self.engine.model.config, self.config.pool_blocks() + 1,
            self.config.block_size, self._dtype)

    def _register_audit_entries(self) -> List[str]:
        try:
            from tools.tpuaudit.registry import (StaleEntryError,
                                                 register_entry_point)
        except ImportError:
            return []
        try:
            import weakref

            import jax
            import jax.numpy as jnp

            wself = weakref.ref(self)
            expected = self.engine._audit_expected_collectives()
            R, MAXB = self.config.max_seqs, self.blocks_per_seq
            C = self.config.prefill_chunk
            # all params-consuming programs here serve the SAME weight tree
            # as the underlying InferenceEngine — same policy, same
            # exchange group (tools/tpushard cross-checks the chain)
            shard = self.engine._shard_tag()

            def build_prefill():
                eng = wself()
                if eng is None:
                    raise StaleEntryError("serving/prefill_chunk: "
                                          "engine gone")
                return eng._prefill, eng._audit_args_prefill(), {}

            def build_decode():
                eng = wself()
                if eng is None:
                    raise StaleEntryError("serving/decode: engine gone")
                i32 = jnp.int32
                args = (eng.engine._params_sds(), eng._arena_sds(),
                        jax.ShapeDtypeStruct((R, MAXB), i32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((R,), jnp.float32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((R,), jnp.float32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((R,), i32),
                        jax.ShapeDtypeStruct((2,), jnp.uint32))
                return eng._decode, args, {}

            register_entry_point(
                "serving/prefill_chunk", build=build_prefill,
                donate_argnums=(1,), expected_collectives=expected,
                mesh=self.engine.mesh,
                tags={"engine": "ServingEngine", "chunk": C,
                      "max_blocks": MAXB, "paged_impl": self._paged_impl,
                      # one chunked-prefill run ingests C prompt tokens
                      "tokens_per_step": C, "shard": shard,
                      # lowered module name ("jit_<program>") — the deep
                      # profiler keys measured device time back to this
                      # entry through it
                      "program": "prefill_chunk"})
            register_entry_point(
                "serving/decode", build=build_decode, donate_argnums=(1,),
                expected_collectives=expected, mesh=self.engine.mesh,
                tags={"engine": "ServingEngine", "rows": R,
                      "max_blocks": MAXB, "paged_impl": self._paged_impl,
                      # one decode iteration emits one token per row
                      "tokens_per_step": R, "shard": shard,
                      "program": "decode"})

            def build_cow():
                eng = wself()
                if eng is None:
                    raise StaleEntryError("serving/cow_copy: engine gone")
                i32 = jnp.int32
                return (eng._cow, (eng._arena_sds(),
                                   jax.ShapeDtypeStruct((), i32),
                                   jax.ShapeDtypeStruct((), i32)), {})

            # pure arena block copy: slice-select + slice-update along the
            # (replicated) block axis — no resharding, hence no collectives
            # regardless of the engine's TP/EP declarations
            register_entry_point(
                "serving/cow_copy", build=build_cow, donate_argnums=(0,),
                expected_collectives=(), mesh=self.engine.mesh,
                tags={"engine": "ServingEngine",
                      "block_size": self.config.block_size,
                      "program": "cow_copy"})
            def build_score():
                eng = wself()
                if eng is None:
                    raise StaleEntryError("serving/score_chunk: engine gone")
                i32 = jnp.int32
                args = (eng.engine._params_sds(), eng._arena_sds(),
                        jax.ShapeDtypeStruct((1, MAXB), i32),
                        jax.ShapeDtypeStruct((1, C), i32),
                        jax.ShapeDtypeStruct((1, C), i32),
                        jax.ShapeDtypeStruct((), i32),
                        jax.ShapeDtypeStruct((), i32))
                return eng._score, args, {}

            # the RLHF teacher-forced scoring pass: prefill-shaped forward
            # returning target logprobs instead of samples — same engine
            # collectives, same arena donation
            register_entry_point(
                "serving/score_chunk", build=build_score,
                donate_argnums=(1,), expected_collectives=expected,
                mesh=self.engine.mesh,
                tags={"engine": "ServingEngine", "chunk": C,
                      "max_blocks": MAXB, "paged_impl": self._paged_impl,
                      # one scoring chunk ingests C sequence tokens
                      "tokens_per_step": C, "shard": shard,
                      "program": "score_chunk"})
            names = ["serving/prefill_chunk", "serving/decode",
                     "serving/cow_copy", "serving/score_chunk"]
            if self._drafter is not None:
                names += self._register_spec_audit_entries(
                    register_entry_point, StaleEntryError, wself, expected)
            return names
        except Exception:   # registration must never take serving down
            logger.warning("tpuaudit serving registration failed",
                           exc_info=True)
            return []

    def _register_spec_audit_entries(self, register_entry_point,
                                     StaleEntryError, wself,
                                     expected) -> List[str]:
        import jax
        import jax.numpy as jnp

        R, MAXB = self.config.max_seqs, self.blocks_per_seq
        S = self.config.speculative.num_draft_tokens + 1
        i32, f32 = jnp.int32, jnp.float32

        def build_verify():
            eng = wself()
            if eng is None:
                raise StaleEntryError("serving/verify: engine gone")
            args = (eng.engine._params_sds(), eng._arena_sds(),
                    jax.ShapeDtypeStruct((R, MAXB), i32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((R, S), i32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((R,), f32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((R,), f32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((2,), jnp.uint32))
            return eng._verify, args, {}

        register_entry_point(
            "serving/verify", build=build_verify, donate_argnums=(1,),
            expected_collectives=expected, mesh=self.engine.mesh,
            tags={"engine": "ServingEngine", "rows": R, "spec_tokens": S,
                  "max_blocks": MAXB, "paged_impl": self._paged_impl,
                  # conservative floor: one verify dispatch emits AT LEAST
                  # one token per row (acceptance only adds to this)
                  "tokens_per_step": R,
                  "shard": self.engine._shard_tag(),
                  "program": "verify"})
        names = ["serving/verify"]
        drafter = self._drafter
        if not hasattr(drafter, "_decode"):    # host-side drafter: no
            return names                       # device programs to audit
        from ..inference.kv_cache import paged_cache_shape_struct

        dcfg = drafter.engine.model.config
        dexp = drafter.engine._audit_expected_collectives()
        C = drafter.draft_chunk
        # the draft model is a separate weight tree — its own shard group so
        # tpushard never cross-compares draft params with target params
        from ..parallel.rules import shard_tag
        dshard = shard_tag("serving", axes=drafter.engine.model.axes,
                           params_arg=0, expert_parallel=True,
                           group="serving-draft")

        def draft_arena_sds(eng):
            return paged_cache_shape_struct(
                dcfg, self.config.pool_blocks() + 1,
                self.config.block_size, eng._drafter._dtype)

        def build_draft_decode():
            eng = wself()
            if eng is None:
                raise StaleEntryError("serving/draft_decode: engine gone")
            args = (eng._drafter.engine._params_sds(), draft_arena_sds(eng),
                    jax.ShapeDtypeStruct((R, MAXB), i32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((R,), f32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((R,), f32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((R,), i32),
                    jax.ShapeDtypeStruct((2,), jnp.uint32))
            return eng._drafter._decode, args, {}

        def build_draft_prefill():
            eng = wself()
            if eng is None:
                raise StaleEntryError("serving/draft_prefill: engine gone")
            args = (eng._drafter.engine._params_sds(), draft_arena_sds(eng),
                    jax.ShapeDtypeStruct((1, MAXB), i32),
                    jax.ShapeDtypeStruct((1, C), i32),
                    jax.ShapeDtypeStruct((), i32),
                    jax.ShapeDtypeStruct((), i32),
                    jax.ShapeDtypeStruct((1,), f32),
                    jax.ShapeDtypeStruct((1,), i32),
                    jax.ShapeDtypeStruct((1,), f32),
                    jax.ShapeDtypeStruct((1,), i32),
                    jax.ShapeDtypeStruct((2,), jnp.uint32))
            return eng._drafter._prefill, args, {}

        register_entry_point(
            "serving/draft_decode", build=build_draft_decode,
            donate_argnums=(1,), expected_collectives=dexp,
            mesh=drafter.engine.mesh,
            tags={"engine": "ServingEngine", "rows": R,
                  "draft_model": True, "tokens_per_step": R,
                  # the drafter's decode lowers to the same jit_decode
                  # module name as the target's — the profiler attributes
                  # the program to the target entry and marks it shared
                  "shard": dshard, "program": "decode"})
        register_entry_point(
            "serving/draft_prefill", build=build_draft_prefill,
            donate_argnums=(1,), expected_collectives=dexp,
            mesh=drafter.engine.mesh,
            tags={"engine": "ServingEngine", "chunk": C,
                  "draft_model": True, "tokens_per_step": C,
                  "shard": dshard, "program": "prefill_chunk"})
        return names + ["serving/draft_decode", "serving/draft_prefill"]


def _apply_boot_recommendations(scfg: ServingConfig,
                                recommendations: Any) -> "tuple":
    """Resolve + apply a ``tune_recommendations.json`` to the serving
    config before engine construction (boot is the only recompile-safe
    moment for shape knobs). ``recommendations``: a path, an already-loaded
    artifact dict, or ``"auto"`` (newest artifact in the run dir). Returns
    ``(applied, refused)`` provenance lists and publishes
    ``tune/recommendations_{applied,refused}`` counters; a bad artifact is
    refused with a named reason, never a boot failure."""
    from ..autotuning.livetuner import (apply_recommendations,
                                        discover_recommendations,
                                        load_recommendations)
    from ..observability import get_registry

    applied: List[dict] = []
    refused: List[dict] = []
    artifact: Optional[dict] = None
    if isinstance(recommendations, dict):
        artifact = recommendations
    else:
        path = recommendations
        if path == "auto":
            path = discover_recommendations()
            if path is None:
                logger.info("tune recommendations: auto-discovery found "
                            "no artifact; booting with configured shapes")
                return applied, refused
        try:
            artifact = load_recommendations(str(path))
        except ValueError as e:
            refused.append({"knob": "*", "reason": str(e),
                            "path": str(path)})
            logger.warning(
                f"tune recommendations: REFUSED artifact {path}: {e}")
    if artifact is not None:
        applied, refused2 = apply_recommendations(scfg, artifact)
        refused += refused2
    reg = get_registry()
    for row in applied:
        reg.counter(
            "tune/recommendations_applied",
            help="offline shape recommendations applied at engine "
                 "boot").inc(knob=row["knob"])
    for row in refused:
        reg.counter(
            "tune/recommendations_refused",
            help="offline shape recommendations refused at boot (named "
                 "reason)").inc(knob=row["knob"],
                                reason=row["reason"].split(":", 1)[0])
    return applied, refused


def init_serving(model=None, serving_config: Optional[Any] = None,
                 clock: Callable[[], float] = time.monotonic,
                 draft_model=None, recommendations: Optional[Any] = None,
                 **init_inference_kwargs) -> ServingEngine:
    """Build an ``InferenceEngine`` (same surface as ``init_inference``) and
    wrap it in a ``ServingEngine``. ``serving_config``: a ``ServingConfig``
    or plain dict. ``draft_model`` (for ``speculative.mode='draft'``): a
    model name/instance for the drafter — built on the same dtype so its
    paged arena shares the serving block pool cleanly. ``recommendations``:
    a ``tune_recommendations.json`` path, loaded artifact dict, or
    ``"auto"`` — the previous run's offline shape advice (speculative K,
    block pool, chunk width) applied to the config at boot with provenance
    (``engine.recommendations_applied``)."""
    from ..inference import init_inference

    if isinstance(serving_config, dict):
        serving_config = ServingConfig.from_dict(serving_config)
    scfg = serving_config or ServingConfig()
    rec_applied: List[dict] = []
    rec_refused: List[dict] = []
    if recommendations is not None:
        rec_applied, rec_refused = _apply_boot_recommendations(
            scfg, recommendations)
        if rec_applied:
            scfg.validate()   # applied shapes must still be a legal config
    # the offline arena is unused by serving, but a shared engine may still
    # serve generate() calls — keep its budget at least the serving budget
    init_inference_kwargs.setdefault("max_out_tokens", scfg.max_model_len)
    engine = init_inference(model=model, **init_inference_kwargs)
    draft_engine = None
    if draft_model is not None:
        draft_engine = init_inference(
            model=draft_model, dtype=engine.config.dtype,
            max_out_tokens=scfg.max_model_len)
    serving = ServingEngine(engine, scfg, clock=clock,
                            draft_engine=draft_engine)
    serving.recommendations_applied = rec_applied
    serving.recommendations_refused = rec_refused
    return serving
