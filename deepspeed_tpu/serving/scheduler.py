"""Iteration-level continuous-batching scheduler (Orca, Yu et al. OSDI '22).

Device-free by design: the scheduler manipulates ``Request`` state, rows and
blocks; ``ServingEngine`` (api.py) executes the device programs it plans.
That split keeps every policy decision testable with an injectable clock and
zero sleeps (the ``hangdetect.py`` testing convention).

Policies:

* **Admission** — iteration-level: whenever a decode row is free and the
  pool can hold the request's first prefill chunk, a queued request joins
  the running batch. Under ``fairness='fair'``, the next request comes from
  the tenant with the least accumulated service (tokens processed), and
  within a tenant earliest-deadline-first (requests without a deadline sort
  last, then by arrival). ``'fcfs'`` is plain arrival order.
* **Chunked prefill** — one prompt chunk per iteration, interleaved with
  the decode step, so a long prompt cannot freeze time-to-first-token for
  everyone else (Sarathi-style).
* **Preemption by block eviction** — when the pool runs dry mid-decode, the
  most recently admitted other request is evicted: its blocks free
  immediately, and it re-queues in *recompute* mode (its re-prefill source
  is prompt + tokens generated so far; already-streamed tokens are never
  re-emitted). LIFO victim choice protects the oldest requests' latency.
  Freeing drops REFERENCES — blocks shared through the prefix cache stay
  resident for their other holders, and unpinned cache entries are evicted
  before any running request is.
* **Prefix sharing** — admission consults the content-hashed
  ``PrefixCache``: cached full prompt blocks are mapped straight into the
  new request's table (refcount++) and their prefill chunks never run.
  Writes into a shared block go copy-on-write (``cow_block_indices`` +
  ``alloc_for_cow``; the engine runs the device-side copy).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from .paged_kv import (BlockAllocator, PrefixCache, blocks_for_tokens,
                       extend_block_list, truncate_block_list)

__all__ = ["Request", "SamplingParams", "Scheduler", "QueueFull",
           "QUEUED", "PREFILL", "DECODE", "FINISHED", "CANCELLED",
           "DEADLINE_EXCEEDED"]

QUEUED = "queued"
PREFILL = "prefill"
DECODE = "decode"
FINISHED = "finished"
CANCELLED = "cancelled"
DEADLINE_EXCEEDED = "deadline_exceeded"


class QueueFull(RuntimeError):
    """Backpressure: the serving queue is at ``max_queue`` in-flight
    requests — callers shed load or retry later."""


@dataclasses.dataclass
class SamplingParams:
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0


@dataclasses.dataclass
class Request:
    """One in-flight generation request. ``prompt`` is the CURRENT prefill
    source — after a preemption it becomes prompt+generated-so-far
    (recompute mode); ``n_prompt`` keeps the original prompt length for
    TTFT/budget accounting."""

    rid: int
    prompt: np.ndarray                       # (S,) int32 prefill source
    max_new_tokens: int
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    eos_token_id: Optional[int] = None
    tenant: str = "default"
    deadline_s: Optional[float] = None       # absolute (scheduler clock)
    seed: int = 0
    arrival_s: float = 0.0
    # -- runtime state (scheduler-owned) --
    state: str = QUEUED
    row: Optional[int] = None                # decode-batch row while running
    blocks: List[int] = dataclasses.field(default_factory=list)
    prefill_pos: int = 0                     # tokens of `prompt` prefilled
    length: int = 0                          # KV tokens written for this row
    pending_token: Optional[int] = None      # sampled, not yet in the cache
    generated: List[int] = dataclasses.field(default_factory=list)
    n_prompt: int = 0                        # ORIGINAL prompt length
    resume: bool = False                     # recompute after preemption
    # incremental prefix-cache chain digest: key of the last registered
    # block + how many prompt blocks it covers (rebuilt on mismatch, e.g.
    # after preemption resets prefill_pos)
    chain_key: bytes = b""
    chain_blocks: int = 0
    preemptions: int = 0
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None
    # -- parallel-sampling fork (COW) --
    prefilled: bool = False            # blocks/KV pre-attached at fork:
    #   admission skips allocation AND prefill (straight to DECODE);
    #   cleared on preemption (recompute goes the normal path)
    fork_of: Optional[int] = None      # parent rid, for metrics/debugging
    # -- speculative decoding accounting (engine-owned) --
    spec_proposed: int = 0             # draft tokens this request verified
    spec_accepted: int = 0             # ... and accepted
    # -- request tracing (engine-owned; None unless the session's
    #    request_tracing gate is on — the disabled path carries a None) --
    trace: Optional[object] = None     # observability.reqtrace.ReqTrace

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.n_prompt == 0:
            self.n_prompt = int(self.prompt.size)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, CANCELLED, DEADLINE_EXCEEDED)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_s is None:
            return None
        return self.first_token_s - self.arrival_s

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time per output token after the first."""
        if (self.finish_s is None or self.first_token_s is None
                or len(self.generated) < 2):
            return None
        return ((self.finish_s - self.first_token_s)
                / (len(self.generated) - 1))


class Scheduler:
    """Owns the queue, the decode rows, and the block pool accounting."""

    def __init__(self, config, allocator: Optional[BlockAllocator] = None,
                 clock: Callable[[], float] = time.monotonic,
                 prefix_cache: Optional[PrefixCache] = None):
        config.validate()
        self.config = config
        self.alloc = allocator or BlockAllocator(config.pool_blocks())
        self.prefix = prefix_cache
        self.clock = clock
        self.queued: List[Request] = []
        self.running: Dict[int, Request] = {}      # row -> request
        # called with the request on EVERY release (finish/cancel/preempt)
        # — the speculative drafter's device-state teardown hook
        self.on_release: Optional[Callable[[Request], None]] = None
        # called with the victim AFTER a preemption re-queued it — the
        # request tracer's eviction event (None costs one attribute check)
        self.on_preempt: Optional[Callable[[Request], None]] = None
        self._free_rows: List[int] = list(range(config.max_seqs))[::-1]
        self.service: Dict[str, float] = {}        # tenant -> tokens served
        self._admit_seq = 0
        # rid -> admission order, for RUNNING requests only (pruned on
        # release so a long-lived server's memory stays bounded)
        self._admit_index: Dict[int, int] = {}
        import collections

        # bounded trace of admission order (tests + debugging)
        self.admitted_log = collections.deque(maxlen=4096)
        self.preemption_count = 0
        self.finished_count = 0
        self.cancelled_count = 0
        self.deadline_exceeded_count = 0
        # deadline-bearing requests currently queued/running: the O(1)
        # fast path for expire_deadlines — a no-deadline workload must not
        # pay a per-iteration scan for a feature it never uses
        self._deadline_reqs = 0
        self.handoffs_out = 0          # requests handed to another engine
        self.prefix_hits = 0           # admissions that reused ≥1 block
        self.prefix_hit_tokens = 0     # prompt tokens whose prefill was skipped
        self.prefix_lookup_tokens = 0  # prompt tokens of COMMITTED admissions

    # -- intake ------------------------------------------------------------
    def submit(self, req: Request) -> None:
        if len(self.queued) + len(self.running) >= self.config.max_queue:
            raise QueueFull(
                f"serving queue full ({self.config.max_queue} in-flight); "
                "shed load or raise serving.max_queue")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid}: max_new_tokens must be >= 1, got "
                f"{req.max_new_tokens}")
        limit = self.config.max_model_len
        if req.n_prompt + req.max_new_tokens > limit:
            raise ValueError(
                f"request {req.rid}: prompt ({req.n_prompt}) + "
                f"max_new_tokens ({req.max_new_tokens}) exceeds "
                f"serving.max_model_len={limit}")
        if req.n_prompt < 1:
            raise ValueError(f"request {req.rid}: empty prompt")
        req.arrival_s = self.clock()
        req.state = QUEUED
        if req.deadline_s is not None:
            self._deadline_reqs += 1
        self.queued.append(req)

    def submit_forked(self, req: Request) -> None:
        """Enqueue a COW-forked sibling: its blocks (shared, incref'd by
        the caller) and KV are already attached, so admission only needs a
        free decode row. Bypasses the ``max_queue`` check — the engine
        reserved fork capacity when the parent's ``submit(n=...)`` was
        accepted (pending siblings count toward its in_flight). A caller
        that pre-set ``arrival_s`` keeps it: a submit(n=...) sibling's
        TTFT clock starts at the client's submit, not the fork point."""
        if req.arrival_s == 0.0:
            req.arrival_s = self.clock()
        req.state = QUEUED
        req.prefilled = True
        if req.deadline_s is not None:
            self._deadline_reqs += 1
        self.queued.append(req)

    def cancel(self, req: Request) -> bool:
        if req.done:
            return False
        if req.state == QUEUED:
            self.queued.remove(req)
        # _release is a no-op for row-less requests but still frees any
        # blocks a queued request may hold (a request evicted mid-iteration
        # can transiently carry blocks) — skipping it here leaked them for
        # the server's lifetime
        self._release(req)
        self._note_terminal(req)
        req.state = CANCELLED
        req.finish_s = self.clock()
        self.cancelled_count += 1
        return True

    # -- bookkeeping -------------------------------------------------------
    def queue_depth(self) -> int:
        return len(self.queued)

    def in_flight(self) -> int:
        return len(self.queued) + len(self.running)

    def note_service(self, req: Request, tokens: int) -> None:
        self.service[req.tenant] = self.service.get(req.tenant, 0.0) + tokens

    def _release(self, req: Request) -> None:
        """Free the request's row and blocks (state left to the caller)."""
        if req.row is not None:
            del self.running[req.row]
            self._free_rows.append(req.row)
            req.row = None
        if req.blocks:
            self.alloc.free(req.blocks)
            req.blocks = []
        self._admit_index.pop(req.rid, None)
        if self.on_release is not None:
            # tpusync: disable=callback-under-lock — engine-bound seam
            # (prefix-cache/drafter cleanup), not user code; block release
            # and its observers must be atomic
            self.on_release(req)

    def _note_terminal(self, req: Request) -> None:
        """Terminal-state bookkeeping shared by finish/cancel/handoff/
        expire (NOT preemption — a preempted request is still in flight)."""
        if req.deadline_s is not None:
            self._deadline_reqs = max(self._deadline_reqs - 1, 0)

    def finish(self, req: Request) -> None:
        self._release(req)
        self._note_terminal(req)
        req.state = FINISHED
        req.finish_s = self.clock()
        self.finished_count += 1

    def expire_deadlines(self, now: float) -> List[Request]:
        """Terminal-state the requests whose absolute deadline has passed —
        queued OR running: a request that can no longer meet its deadline
        must stop consuming decode rows and blocks to completion. Frees
        rows/blocks immediately (the bugfix: an expired request used to
        decode to its token budget while live requests waited on the pool)
        and returns the expired requests so the engine can count them and
        wake their handles. O(1) when no in-flight request carries a
        deadline — the common workload never pays for the scan."""
        if self._deadline_reqs == 0:
            return []
        expired = [r for r in list(self.queued) + list(self.running.values())
                   if r.deadline_s is not None and now > r.deadline_s]
        for req in expired:
            if req.state == QUEUED:
                self.queued.remove(req)
            self._release(req)
            self._note_terminal(req)
            req.state = DEADLINE_EXCEEDED
            req.finish_s = now
            self.deadline_exceeded_count += 1
        return expired

    def release_handoff(self, req: Request) -> None:
        """Terminal release for a request whose KV was handed to ANOTHER
        engine (fleet prefill/decode disaggregation): frees this engine's
        row/blocks like ``finish`` but counts as a handoff, not a
        completion — the destination engine finishes the request and owns
        its completion ledger entry."""
        self._release(req)
        self._note_terminal(req)
        req.state = FINISHED
        req.finish_s = self.clock()
        self.handoffs_out += 1

    # -- admission ---------------------------------------------------------
    def _pick_next(self) -> Optional[Request]:
        if not self.queued:
            return None
        if self.config.fairness == "fcfs":
            return min(self.queued, key=lambda r: (r.arrival_s, r.rid))
        # fair: least-service tenant first (stable tie-break on name), then
        # EDF within the tenant (no deadline sorts last), then arrival
        tenant = min({r.tenant for r in self.queued},
                     key=lambda t: (self.service.get(t, 0.0), t))
        cands = [r for r in self.queued if r.tenant == tenant]
        return min(cands, key=lambda r: (
            r.deadline_s if r.deadline_s is not None else math.inf,
            r.arrival_s, r.rid))

    def admit(self) -> List[Request]:
        """Move queued requests onto free decode rows while their first
        chunk's blocks fit in the pool (admission never preempts a running
        request — only progress for already-admitted requests may evict;
        it MAY evict unpinned prefix-cache entries under pressure).

        Prefix sharing: a request whose prompt prefix is content-cached
        maps the cached blocks into its table (refcount++) and starts
        prefill AFTER them — those chunks are never run. The cached blocks
        are incref'd BEFORE the fresh allocation so cache-pressure eviction
        can never free the very blocks the admission is about to use."""
        admitted: List[Request] = []
        while self._free_rows:
            req = self._pick_next()
            if req is None:
                break
            if req.prefilled:
                # COW-forked sibling: KV and (shared) blocks already
                # attached — it only needs the row
                self.queued.remove(req)
                req.row = self._free_rows.pop()
                req.state = DECODE
                self.running[req.row] = req
                self._admit_index[req.rid] = self._admit_seq
                self._admit_seq += 1
                self.admitted_log.append(req.rid)
                admitted.append(req)
                continue
            cached_ids: List[int] = []
            n_cached = 0
            if self.prefix is not None:
                cached_ids, n_cached = self.prefix.match(req.prompt)
            if cached_ids:
                self.alloc.incref(cached_ids)
            first_target = min(n_cached + self.config.prefill_chunk,
                               int(req.prompt.size))
            need = max(blocks_for_tokens(first_target, self.config.block_size)
                       - len(cached_ids), 0)
            ids = self._alloc_evicting_cache(need)
            if ids is None:
                if cached_ids:
                    self.alloc.free(cached_ids)   # roll the increfs back
                break
            if self.prefix is not None:
                # stats at the COMMIT point only: a rolled-back admission
                # re-matching every iteration must not inflate the rate
                self.prefix_lookup_tokens += int(req.prompt.size)
            if cached_ids:
                req.blocks.extend(cached_ids)
                req.prefill_pos = n_cached
                req.length = n_cached
                self.prefix_hits += 1
                self.prefix_hit_tokens += n_cached
            req.blocks.extend(ids)
            self.queued.remove(req)
            req.row = self._free_rows.pop()
            req.state = PREFILL
            self.running[req.row] = req
            self._admit_index[req.rid] = self._admit_seq
            self._admit_seq += 1
            self.admitted_log.append(req.rid)
            admitted.append(req)
        return admitted

    def _alloc_evicting_cache(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks, relieving pressure by evicting UNPINNED
        prefix-cache entries (LRU-first) — never by preempting a running
        request."""
        while True:
            ids = self.alloc.alloc(n)
            if ids is not None:
                return ids
            if self.prefix is None:
                return None
            if self.prefix.evict(n - self.alloc.blocks_free) <= 0:
                return None

    # -- block growth + preemption ----------------------------------------
    def ensure_blocks(self, req: Request, upto_tokens: int) -> bool:
        """Grow ``req``'s block list to cover positions [0, upto_tokens).
        When the pool is dry, relieves pressure in order of cost: first
        evict UNPINNED prefix-cache entries (no recompute anywhere), then
        evict the most recently admitted OTHER request and retry; returns
        False when nothing can be evicted (the caller skips this request
        for the iteration). Preempting a victim whose blocks are all
        shared may free nothing — the loop keeps evicting until the pool
        yields or the running set is exhausted."""
        need = blocks_for_tokens(upto_tokens, self.config.block_size) \
            - len(req.blocks)
        if need <= 0:
            return True
        while True:
            ids = self._alloc_evicting_cache(need)
            if ids is not None:
                req.blocks.extend(ids)
                return True
            if not self._preempt_one(exclude=req):
                return False

    def try_extend_blocks(self, req: Request, upto_tokens: int) -> bool:
        """Best-effort block growth for OPTIONAL work (the speculative
        verify extension): plain pool allocation — no cache eviction, no
        preemption. Speculation must never cost anyone else their blocks;
        a False here is the per-row auto-disable signal."""
        return extend_block_list(self.alloc, req.blocks, upto_tokens,
                                 self.config.block_size)

    def truncate_blocks(self, req: Request, upto_tokens: int) -> int:
        """Positional rollback: free blocks past the ones covering
        positions [0, upto_tokens) — rejected speculative KV beyond the
        accepted length returns to the pool (see
        ``paged_kv.truncate_block_list``). Returns references dropped."""
        return truncate_block_list(self.alloc, req.blocks, upto_tokens,
                                   self.config.block_size)

    def alloc_for_cow(self, req: Request) -> Optional[int]:
        """One private block for a copy-on-write replacement in ``req``'s
        table — same pressure ladder as ensure_blocks. Returns the block
        id, or None when the pool cannot provide one this iteration."""
        while True:
            ids = self._alloc_evicting_cache(1)
            if ids is not None:
                return ids[0]
            if not self._preempt_one(exclude=req):
                return None

    def cow_block_indices(self, req: Request, start: int, end: int
                          ) -> List[int]:
        """Positions [start, end) are about to be written: the table
        indices whose physical block is SHARED (refcount > 1) and must be
        copied first — a writer may only touch exclusively-owned blocks."""
        if end <= start:
            return []
        bs = self.config.block_size
        return [bi for bi in range(start // bs, (end - 1) // bs + 1)
                if bi < len(req.blocks)
                and self.alloc.refcount(req.blocks[bi]) > 1]

    def note_prefill_progress(self, req: Request, old_pos: int,
                              new_pos: int) -> None:
        """Prefill advanced [old_pos → new_pos): register newly COMPLETED
        full prompt blocks with the prefix cache (idempotent — an existing
        chain key keeps its block). The chain digest threads through the
        request (one hash step per block); a position reset (preemption
        recompute) rebuilds it once."""
        if self.prefix is None:
            return
        bs = self.config.block_size
        first, last = old_pos // bs, new_pos // bs
        if req.chain_blocks != first:
            key = b""
            for bi in range(first):
                key = self.prefix.chain_key(req.prompt, key, bi)
            req.chain_key, req.chain_blocks = key, first
        for bi in range(first, last):
            req.chain_key = self.prefix.chain_key(req.prompt,
                                                  req.chain_key, bi)
            req.chain_blocks = bi + 1
            self.prefix.insert_key(req.chain_key, req.blocks[bi])

    def _preempt_one(self, exclude: Request) -> bool:
        victims = [r for r in self.running.values() if r is not exclude]
        if not victims:
            return False
        victim = max(victims, key=lambda r: self._admit_index[r.rid])
        self.preempt(victim)
        return True

    def preempt(self, req: Request) -> None:
        """Evict ``req``'s blocks and re-queue it in recompute mode: the new
        prefill source is prompt + generated-so-far minus the pending token
        (whose KV was never written); the stored ``pending_token`` is
        re-used on resume so the client stream never sees a duplicate — or,
        under temperature sampling, a diverged — token."""
        self.preemption_count += 1
        req.preemptions += 1
        self._release(req)
        if req.generated:
            req.prompt = np.concatenate(
                [req.prompt[:req.n_prompt],
                 np.asarray(req.generated[:-1], np.int32)]).astype(np.int32)
            req.pending_token = req.generated[-1]
            req.resume = True
        req.prefill_pos = 0
        req.length = 0
        req.prefilled = False   # a forked sibling recomputes like anyone
        req.state = QUEUED
        self.queued.append(req)
        if self.on_preempt is not None:
            # tpusync: disable=callback-under-lock — engine-bound seam
            # (drafter/KV bookkeeping), not user code; the requeue and its
            # observers must see one consistent preemption
            self.on_preempt(req)

    # -- iteration planning ------------------------------------------------
    def next_prefill(self) -> Optional[Request]:
        """The PREFILL-state request to advance this iteration — oldest
        admission first, so a chunked long prompt finishes in order."""
        cands = [r for r in self.running.values() if r.state == PREFILL]
        if not cands:
            return None
        return min(cands, key=lambda r: self._admit_index[r.rid])

    def decode_requests(self) -> List[Request]:
        return [r for r in self.running.values() if r.state == DECODE]
