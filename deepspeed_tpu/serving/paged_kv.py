"""Paged KV arena — the device half of the serving layer.

The inference engine's arena reserves a full ``T_max`` row per sequence
(``inference/kv_cache.py``); at serving concurrency that wastes HBM
proportional to the spread of sequence lengths. Here the arena is a shared
pool of fixed-size **blocks** (vLLM's PagedAttention, Kwon et al. SOSP '23):

* ``BlockAllocator`` — host-side free list over the pool. Block 0 is a
  reserved scratch block (inactive decode rows and prompt-chunk padding
  write there); allocatable ids are 1..num_blocks.
* ``build_prefill_program`` / ``build_decode_program`` — the two jitted
  serving programs. Both are **shape-static**: the block table
  ``(rows, max_blocks)`` and per-row lengths are data, not shapes, so one
  compiled decode program serves every occupancy the scheduler produces
  (the jit-cache analog of the reference's CUDA-graph discipline). The
  attention read walks the block table: on TPU the Pallas paged kernels
  (``ops/paged_decode_attention.py``) DMA only each row's RESIDENT pages;
  ``paged_impl='gather'`` keeps the dense ``arena[block_table]`` view as
  the A/B baseline (``serving.paged_kernel='off'``).
* ``PrefixCache`` + refcounted ``BlockAllocator`` + ``build_cow_program``
  — prefix sharing: full prompt blocks are content-hash cached, a new
  request whose prompt prefix is cached maps those blocks into its table
  (refcount++) and skips their prefill chunks entirely; the first write
  into a shared block triggers a device-side copy-on-write.
* ``sample_rows`` — per-row greedy/temperature/top-k/top-p sampling with
  *array-valued* knobs, so requests with different sampling settings share
  one decode program. The greedy path is bit-identical to
  ``inference/engine._sample`` at ``temperature=0``.

The model-side write/read lives in ``models/transformer._layer_forward``
(paged branch): the layout is left-aligned — token at position ``p`` sits in
block ``table[p // BLOCK]`` offset ``p % BLOCK`` — so a key's gathered
column IS its position and causality over true positions is the entire
validity story.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..inference.kv_cache import (assert_block_divisible, blocks_for_tokens,
                                  init_paged_cache, paged_cache_memory_bytes)

__all__ = ["BlockAllocator", "BlockAllocatorError", "PrefixCache",
           "blocks_for_tokens", "assert_block_divisible", "init_paged_cache",
           "paged_cache_memory_bytes", "build_prefill_program",
           "build_decode_program", "build_verify_program",
           "build_score_program", "build_cow_program",
           "build_kv_export_program", "build_kv_import_program",
           "sample_rows", "extend_block_list", "truncate_block_list"]


class BlockAllocatorError(RuntimeError):
    """Allocator invariant violation (double free, foreign block)."""


class BlockAllocator:
    """Refcounted free-list allocator over the arena's allocatable blocks
    (1..capacity).

    Prefix sharing (copy-on-write block tables) makes one physical block
    appear in several sequences' tables, so every allocated block carries a
    reference count: ``alloc`` hands out blocks at refcount 1, ``incref``
    adds a sharer, and ``free`` DROPS ONE REFERENCE — the block returns to
    the free list only when its last reference is dropped. Callers that
    never share (the pre-COW code paths) see the exact PR-6 semantics.

    Invariants (tested in tests/unit/test_serving.py):
      * ``blocks_in_use + blocks_free == capacity`` at all times;
      * a block is never handed out twice without reaching refcount 0;
      * dropping a reference that is not held raises (double free);
      * block 0 (scratch) is never allocated.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.capacity = int(num_blocks)
        # LIFO free list, lowest ids first out — deterministic for tests
        self._free: List[int] = list(range(self.capacity, 0, -1))
        self._refs: Dict[int, int] = {}
        self.peak_in_use = 0
        self.peak_shared = 0
        self.total_allocs = 0

    @property
    def blocks_in_use(self) -> int:
        return len(self._refs)

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def blocks_shared(self) -> int:
        """Blocks referenced by more than one holder (the sharing win)."""
        return sum(1 for r in self._refs.values() if r > 1)

    def refcount(self, block_id: int) -> int:
        return self._refs.get(block_id, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh block ids at refcount 1, or None when the pool can't
        satisfy the request (caller decides whether to wait, evict cached
        prefixes, or preempt) — partial allocations never happen."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        for b in ids:
            self._refs[b] = 1
        self.total_allocs += n
        self.peak_in_use = max(self.peak_in_use, len(self._refs))
        return ids

    def incref(self, ids: List[int]) -> None:
        """Add one reference per id — a new sharer of an allocated block."""
        for b in ids:
            if b not in self._refs:
                raise BlockAllocatorError(
                    f"incref of block {b} which is not allocated")
        for b in ids:
            self._refs[b] += 1
        # tpusync: disable=unguarded-shared-write — engine-owned: every
        # runtime path holds ServingEngine._lock; the allocator itself is
        # documented single-owner and takes no lock of its own
        self.peak_shared = max(self.peak_shared, self.blocks_shared)

    def free(self, ids: List[int]) -> None:
        """Drop one reference per id; a block is recycled only when its
        LAST reference goes — freeing a shared block never takes it away
        from the other holders."""
        for b in ids:
            if b not in self._refs:
                raise BlockAllocatorError(
                    f"free of block {b} which is not allocated "
                    "(double free or foreign id)")
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                # tpusync: disable=unguarded-shared-write — engine-owned,
                # synchronized under ServingEngine._lock (see incref)
                self._free.append(b)


def extend_block_list(alloc: BlockAllocator, blocks: List[int],
                      upto_tokens: int, block_size: int) -> bool:
    """Grow ``blocks`` (a block-table list) to cover ``upto_tokens``
    positions by PLAIN pool allocation — no cache eviction, no preemption.
    This is the optional-work discipline shared by the speculative verify
    extension and the draft arena: speculation must never cost anyone
    else their blocks. Returns False when the pool says no (the per-row
    auto-disable signal); ``blocks`` is unchanged in that case."""
    need = blocks_for_tokens(upto_tokens, block_size) - len(blocks)
    if need <= 0:
        return True
    ids = alloc.alloc(need)
    if ids is None:
        return False
    blocks.extend(ids)
    return True


def truncate_block_list(alloc: BlockAllocator, blocks: List[int],
                        upto_tokens: int, block_size: int) -> int:
    """Positional rollback shared by the target and draft arenas: drop one
    reference on every block of ``blocks`` past the ones covering
    positions [0, upto_tokens) — rejected speculative KV beyond the
    accepted length is dead weight (never read: causality over true
    positions). A shared (prefix-cache/fork) block stays resident for its
    other holders. Returns the number of references dropped."""
    keep = blocks_for_tokens(upto_tokens, block_size)
    dropped = len(blocks) - keep
    if dropped > 0:
        alloc.free(blocks[keep:])
        del blocks[keep:]
        return dropped
    return 0


class PrefixCache:
    """Content-hashed prompt-prefix → physical-block cache (vLLM/SGLang
    automatic prefix caching).

    Keys are CHAIN hashes: block i's key digests block i-1's key plus block
    i's tokens, so a block is reusable only under the exact same prefix.
    Only FULL prompt blocks are cached — their KV content is immutable once
    written (the arena layout is position-exact, so identical tokens at
    identical positions produce identical KV bytes under fixed params).

    The cache holds ONE pin reference per cached block. Entries whose block
    no request references (allocator refcount == 1) are evictable LRU-first
    under pool pressure; entries shared with live requests are pinned —
    eviction never frees a block somebody still reads.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self.alloc = allocator
        self.block_size = int(block_size)
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()
        self.inserts = 0
        self.evictions = 0
        self.invalidations = 0   # entries dropped by weight-flip clear()

    @property
    def cached_blocks(self) -> int:
        return len(self._entries)

    @property
    def reclaimable_blocks(self) -> int:
        """Cached blocks held ONLY by the cache pin (allocator refcount 1):
        evictable on demand, so load/occupancy signals must not count them
        as pressure — a warm cache deliberately fills the pool."""
        return sum(1 for b in self._entries.values()
                   if self.alloc.refcount(b) == 1)

    @staticmethod
    def _chain(prev: bytes, tokens: np.ndarray) -> bytes:
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
        return h.digest()

    def chain_key(self, prompt: np.ndarray, prev: bytes,
                  block_index: int) -> bytes:
        """One incremental chain step: the key of block ``block_index``
        given its predecessor's key — callers registering blocks in order
        thread the digest instead of rehashing from block 0 (O(P) per
        request, not O(P^2))."""
        BS = self.block_size
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        return self._chain(prev, prompt[block_index * BS:
                                        (block_index + 1) * BS])

    def match(self, prompt) -> Tuple[List[int], int]:
        """Longest cached chain of full blocks for ``prompt``. Returns
        ``(block_ids, n_tokens)`` with ``n_tokens`` capped at
        ``len(prompt) - 1``: at least one prompt token always re-prefills,
        because the request's first sampled token needs the final prompt
        position's logits. When the cap bites (every prompt block cached),
        the last block is handed back SHARED and the re-prefilled token's
        write triggers copy-on-write. Does NOT take references or count
        hit statistics — the caller does both when it COMMITS to using
        the blocks (a rolled-back admission must not inflate the hit
        rate)."""
        BS = self.block_size
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ids: List[int] = []
        key = b""
        for i in range(int(prompt.size) // BS):
            key = self._chain(key, prompt[i * BS:(i + 1) * BS])
            bid = self._entries.get(key)
            if bid is None:
                break
            self._entries.move_to_end(key)     # LRU recency
            ids.append(bid)
        n = min(len(ids) * BS, int(prompt.size) - 1)
        if n < 1:
            return [], 0
        return ids, n

    def insert_key(self, key: bytes, block_id: int) -> bool:
        """Register a fully-prefilled block under its (caller-threaded)
        chain key, pinning it with one cache reference. A key that is
        already cached keeps its existing block (no double pin)."""
        if key in self._entries:
            self._entries.move_to_end(key)
            return False
        self.alloc.incref([block_id])
        self._entries[key] = block_id
        # tpusync: disable=unguarded-shared-write — engine-owned cache,
        # synchronized under ServingEngine._lock like its allocator
        self.inserts += 1
        return True

    def insert(self, prompt: np.ndarray, block_index: int,
               block_id: int) -> bool:
        """Convenience form of ``insert_key`` that rehashes the chain from
        block 0 — tests and one-off callers; the scheduler threads the
        digest incrementally instead."""
        key = b""
        for i in range(block_index + 1):
            key = self.chain_key(prompt, key, i)
        return self.insert_key(key, block_id)

    def clear(self) -> int:
        """Drop EVERY entry, pinned or not, releasing the cache's one pin
        reference per block — the weight-flip invalidation rule
        (``docs/rlhf.md``): cached KV bytes are a pure function of
        (tokens, positions, params), so a parameter refresh makes every
        content hash stale at once. Blocks shared with a live request stay
        resident for that request (``free`` drops one reference); callers
        flip with the engine idle, so normally the whole cache returns to
        the free list. Returns the number of entries dropped."""
        n = len(self._entries)
        for bid in self._entries.values():
            self.alloc.free([bid])
        self._entries.clear()
        self.invalidations += n
        return n

    def evict(self, need: int) -> int:
        """Drop up to ``need`` UNPINNED entries (blocks only the cache
        holds), LRU-first, returning their blocks to the free list.
        Returns the number actually freed — pinned entries (shared with a
        live request) are never touched."""
        freed = 0
        for key in list(self._entries):
            if freed >= need:
                break
            bid = self._entries[key]
            if self.alloc.refcount(bid) == 1:
                del self._entries[key]
                self.alloc.free([bid])
                freed += 1
                self.evictions += 1
        return freed


# ---------------------------------------------------------------------------
# per-row sampling
# ---------------------------------------------------------------------------


def sample_rows(logits: jax.Array, base_key: jax.Array,
                temperature: jax.Array, top_k: jax.Array, top_p: jax.Array,
                seeds: jax.Array, steps: jax.Array) -> jax.Array:
    """Per-row sampling with array-valued knobs: ``logits`` (R, V);
    ``temperature``/``top_p`` (R,) float32; ``top_k`` (R,) int32 (0 = off).
    Rows with ``temperature <= 0`` take the greedy branch — the same
    fp32 argmax as ``inference/engine._sample``, so serving greedy output
    is bit-identical to offline ``generate()``.

    Each row draws from ``fold_in(fold_in(base_key, seeds[r]), steps[r])``
    — ``seeds`` the request's sampling seed, ``steps`` its output-token
    index — so a request's stream depends only on (engine seed, request
    seed, token index), NOT on how the scheduler batched it: reproducible
    across runs and bit-stable across preemption/recompute."""
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # top-k: keep scores >= the k-th largest (per row, traced k)
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        desc, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=1)
    scaled = jnp.where((top_k[:, None] > 0) & (scaled < kth),
                       -jnp.inf, scaled)
    # top-p over the (possibly top-k-filtered) scores; top-1 always survives
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs < top_p[:, None]).at[:, 0].set(True)
    cutoff = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    scaled = jnp.where(scaled >= cutoff, scaled, -jnp.inf)
    keys = jax.vmap(
        lambda s, t: jax.random.fold_in(jax.random.fold_in(base_key, s), t)
    )(seeds, steps)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


# ---------------------------------------------------------------------------
# the two serving programs
# ---------------------------------------------------------------------------


def build_prefill_program(cfg, paged_impl: str = "auto"):
    """Jitted prefill-chunk program over the paged arena.

    Args (all shapes static per (C, max_blocks) pair):
      params, cache          — model params / paged arena (arena DONATED)
      block_table (1, MAXB)  — the request's physical block ids
      chunk (1, C) int32     — prompt tokens, zero-padded past ``n_valid``
      start () int32         — absolute position of chunk[0]
      n_valid () int32       — real tokens in this chunk (pad writes land in
                               the scratch block; pad logits are never read)
      temperature/top_k/top_p/seeds (1,) — the request's sampling knobs
      base_key               — the engine's sampling key (constant)

    Returns (token (1,), last_logits (1, V) f32, cache): ``token`` samples
    the position-``n_valid-1`` logits at output-token index 0 — the
    request's FIRST generated token when this was the final chunk, ignored
    otherwise.
    """
    from ..models.transformer import forward as model_forward

    def prefill_chunk(params, cache, block_table, chunk, start, n_valid,
                      temperature, top_k, top_p, seeds, base_key):
        C = chunk.shape[1]
        offs = jnp.arange(C, dtype=jnp.int32)
        write_mask = (offs < n_valid)[None]
        # pad queries ride position -1 (the inactive convention): a pad
        # position past the written range would otherwise widen the read
        # path's residency window onto scratch/recycled pages, whose
        # nonfinite residue must never touch live rows
        pos = jnp.where(write_mask, (start + offs)[None], -1)
        logits, cache, _ = model_forward(params, chunk, cfg, cache=cache,
                                         positions=pos,
                                         block_table=block_table,
                                         paged_write_mask=write_mask,
                                         paged_impl=paged_impl,
                                         paged_chunk=True)
        last = jnp.take_along_axis(
            logits, jnp.maximum(n_valid - 1, 0)[None, None, None],
            axis=1)[:, 0].astype(jnp.float32)
        tok = sample_rows(last, base_key, temperature, top_k, top_p,
                          seeds, jnp.zeros((1,), jnp.int32))
        return tok, last, cache

    return jax.jit(prefill_chunk, donate_argnums=(1,))


def build_decode_program(cfg, paged_impl: str = "auto"):
    """Jitted one-token decode step over the paged arena for a fixed row
    count R. Inactive rows carry an all-zero block table and length 0 — their
    writes land in the scratch block and their sampled tokens are ignored by
    the host — so occupancy changes never respecialize the program.

    Args: params, cache (DONATED), block_table (R, MAXB), lengths (R,) int32
    (tokens already in cache per row — the incoming token's position),
    tokens (R,) int32, temperature/top_k/top_p/seeds (R,), steps (R,) int32
    (each row's output-token index, for the schedule-independent sampling
    stream), base_key.
    Returns (next_token (R,), cache).
    """
    from ..models.transformer import forward as model_forward

    def decode(params, cache, block_table, lengths, tokens,
               temperature, top_k, top_p, seeds, steps, base_key):
        logits, cache, _ = model_forward(params, tokens[:, None], cfg,
                                         cache=cache,
                                         positions=lengths[:, None],
                                         block_table=block_table,
                                         paged_impl=paged_impl)
        nxt = sample_rows(logits[:, -1], base_key, temperature, top_k,
                          top_p, seeds, steps)
        return nxt, cache

    return jax.jit(decode, donate_argnums=(1,))


def build_verify_program(cfg, num_tokens: int, paged_impl: str = "auto"):
    """Jitted speculative-decoding verify step: the R×1 decode program
    generalized to R×S (S = ``num_tokens`` = K+1 draft slots + the pending
    token). Row r feeds ``tokens[r] = [pending, d_1 .. d_K]`` at absolute
    positions ``lengths[r] + 0..S-1`` — the left-aligned column==position
    invariant makes the causal read over drafted positions exact — and the
    target model scores ALL of them in one dispatch.

    Speculation is data, not shape: ``n_valid`` (R,) int32 is each row's
    real token count this iteration (1 = plain decode, 1+k = k proposed
    drafts, 0 = inactive row riding scratch); positions past ``n_valid``
    write to the scratch block and their samples are ignored by the host.
    One compiled program serves every per-row proposal/acceptance mix.

    Sampling: position j of row r draws through the SAME
    ``fold_in(fold_in(base_key, seeds[r]), steps[r] + j)`` key the
    non-speculative decode would use for that output-token index — so the
    host's accept rule (keep sampled tokens while they equal the draft,
    emit the first divergence as the correction) is lossless rejection
    sampling whose emitted stream is BIT-IDENTICAL to the non-speculative
    path at any temperature, greedy included (see
    ``serving/speculative.py`` for the acceptance math).

    Args: params, cache (DONATED), block_table (R, MAXB), lengths (R,)
    int32, tokens (R, S) int32, n_valid (R,) int32,
    temperature/top_k/top_p/seeds (R,), steps (R,) int32 (each row's FIRST
    output-token index this iteration), base_key.
    Returns (sampled (R, S) int32, cache): ``sampled[r, j]`` is the target
    sample after token j — the host emits ``sampled[r, 0..a]`` where ``a``
    is the accepted-draft count.
    """
    from ..models.transformer import forward as model_forward

    def verify(params, cache, block_table, lengths, tokens, n_valid,
               temperature, top_k, top_p, seeds, steps, base_key):
        R, S = tokens.shape
        offs = jnp.arange(S, dtype=jnp.int32)
        write_mask = offs[None] < n_valid[:, None]
        # invalid slots (beyond the row's proposal count, and every slot
        # of an inactive row) ride position -1 — see prefill_chunk: pad
        # positions past the written range would widen the residency
        # window onto scratch/recycled pages
        pos = jnp.where(write_mask, lengths[:, None] + offs[None], -1)
        logits, cache, _ = model_forward(params, tokens, cfg, cache=cache,
                                         positions=pos,
                                         block_table=block_table,
                                         paged_write_mask=write_mask,
                                         paged_impl=paged_impl,
                                         paged_chunk=True)
        flat = logits.reshape(R * S, logits.shape[-1]).astype(jnp.float32)
        sampled = sample_rows(flat, base_key,
                              jnp.repeat(temperature, S),
                              jnp.repeat(top_k, S), jnp.repeat(top_p, S),
                              jnp.repeat(seeds, S),
                              (steps[:, None] + offs[None]).reshape(-1))
        return sampled.reshape(R, S), cache

    if num_tokens < 2:
        raise ValueError(f"build_verify_program(num_tokens={num_tokens}): "
                         "need the pending token plus >= 1 draft slot")
    return jax.jit(verify, donate_argnums=(1,))


def build_score_program(cfg, paged_impl: str = "auto"):
    """Jitted teacher-forced scoring chunk over the paged arena — the RLHF
    second serving pass (``docs/rlhf.md``): instead of sampling, it returns
    the log-probability the model assigns to given TARGET tokens. Same
    chunked discipline and block-table shapes as the prefill program, so it
    rides the SAME arena and pool (scratch blocks allocated per scored
    sequence, freed after) with zero extra HBM and one compiled program per
    chunk width.

    Args (shapes static per (C, max_blocks) pair):
      params, cache          — scoring params / paged arena (arena DONATED).
                               ``params`` is an argument, not a capture, so
                               the policy pass (π_old logprobs) and the
                               frozen-reference pass share ONE compiled
                               program
      block_table (1, MAXB)  — the scoring scratch blocks
      chunk (1, C) int32     — sequence tokens, zero-padded past ``n_valid``
      targets (1, C) int32   — targets[0, j] is the token whose logprob
                               position ``start + j`` should yield (the
                               next sequence token); pad entries score
                               garbage the host never reads
      start/n_valid () int32 — chunk position / real token count

    Returns (logp (1, C) f32, cache): per-position log softmax mass on the
    target token (``transformer.gather_target_logprobs`` — the TP-safe
    one-hot contraction).
    """
    from ..models.transformer import forward as model_forward
    from ..models.transformer import gather_target_logprobs

    def score_chunk(params, cache, block_table, chunk, targets, start,
                    n_valid):
        C = chunk.shape[1]
        offs = jnp.arange(C, dtype=jnp.int32)
        write_mask = (offs < n_valid)[None]
        # pad queries at position -1 — see prefill_chunk
        pos = jnp.where(write_mask, (start + offs)[None], -1)
        logits, cache, _ = model_forward(params, chunk, cfg, cache=cache,
                                         positions=pos,
                                         block_table=block_table,
                                         paged_write_mask=write_mask,
                                         paged_impl=paged_impl,
                                         paged_chunk=True)
        return gather_target_logprobs(logits, targets), cache

    return jax.jit(score_chunk, donate_argnums=(1,))


def build_kv_export_program():
    """Jitted KV-handoff export: gather one request's resident blocks out of
    the (NOT donated — other requests keep reading it) source arena into a
    dense ``(L, MAXB, BLOCK, K, D)`` transfer buffer, one program for any
    block count. ``ids`` is the request's block list padded to MAXB with the
    scratch block 0 — pad lanes carry scratch garbage the import writes
    straight back into the destination's scratch block, so residency is
    data, never shape. On a shared mesh this plus ``build_kv_import_program``
    is an in-HBM copy; a cross-host transport later replaces only the
    buffer's journey between the two programs (the ``KVHandoff`` seam in
    ``serving/fleet/disagg.py``)."""

    def kv_export(cache, ids):
        return cache["k"][:, ids], cache["v"][:, ids]

    return jax.jit(kv_export)


def build_kv_import_program():
    """Jitted KV-handoff import: scatter an exported transfer buffer into
    freshly allocated blocks of the (donated) destination arena. ``ids`` is
    the destination block list padded to MAXB with scratch 0 — duplicate
    pad writes land in the scratch block, whose content is never read."""

    def kv_import(cache, buf_k, buf_v, ids):
        return {"k": cache["k"].at[:, ids].set(buf_k),
                "v": cache["v"].at[:, ids].set(buf_v)}

    return jax.jit(kv_import, donate_argnums=(0,))


def build_cow_program():
    """Jitted copy-on-write block copy: duplicate physical block ``src``
    into ``dst`` across every layer of the (donated) arena. ``src``/``dst``
    are traced int32 scalars, so ONE compiled program serves every copy —
    the scheduler runs it before the first write into a block whose
    refcount is > 1 (prefix sharing), giving the writer a private copy
    while readers keep the original."""

    def cow_copy(cache, src, dst):
        return {"k": cache["k"].at[:, dst].set(cache["k"][:, src]),
                "v": cache["v"].at[:, dst].set(cache["v"][:, src])}

    return jax.jit(cow_copy, donate_argnums=(0,))
