"""Paged KV arena — the device half of the serving layer.

The inference engine's arena reserves a full ``T_max`` row per sequence
(``inference/kv_cache.py``); at serving concurrency that wastes HBM
proportional to the spread of sequence lengths. Here the arena is a shared
pool of fixed-size **blocks** (vLLM's PagedAttention, Kwon et al. SOSP '23):

* ``BlockAllocator`` — host-side free list over the pool. Block 0 is a
  reserved scratch block (inactive decode rows and prompt-chunk padding
  write there); allocatable ids are 1..num_blocks.
* ``build_prefill_program`` / ``build_decode_program`` — the two jitted
  serving programs. Both are **shape-static**: the block table
  ``(rows, max_blocks)`` and per-row lengths are data, not shapes, so one
  compiled decode program serves every occupancy the scheduler produces
  (the jit-cache analog of the reference's CUDA-graph discipline). The
  attention read gathers ``arena[block_table]`` — an XLA gather; a Pallas
  paged-decode kernel with per-page async DMA is the TPU-native follow-up
  (see ``docs/serving.md``).
* ``sample_rows`` — per-row greedy/temperature/top-k/top-p sampling with
  *array-valued* knobs, so requests with different sampling settings share
  one decode program. The greedy path is bit-identical to
  ``inference/engine._sample`` at ``temperature=0``.

The model-side write/read lives in ``models/transformer._layer_forward``
(paged branch): the layout is left-aligned — token at position ``p`` sits in
block ``table[p // BLOCK]`` offset ``p % BLOCK`` — so a key's gathered
column IS its position and causality over true positions is the entire
validity story.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..inference.kv_cache import (assert_block_divisible, blocks_for_tokens,
                                  init_paged_cache, paged_cache_memory_bytes)

__all__ = ["BlockAllocator", "BlockAllocatorError", "blocks_for_tokens",
           "assert_block_divisible", "init_paged_cache",
           "paged_cache_memory_bytes", "build_prefill_program",
           "build_decode_program", "sample_rows"]


class BlockAllocatorError(RuntimeError):
    """Allocator invariant violation (double free, foreign block)."""


class BlockAllocator:
    """Free-list allocator over the arena's allocatable blocks (1..capacity).

    Invariants (tested in tests/unit/test_serving.py):
      * ``blocks_in_use + blocks_free == capacity`` at all times;
      * a block is never handed out twice without an intervening free;
      * freeing a block that is not held raises (double free / foreign id);
      * block 0 (scratch) is never allocated.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.capacity = int(num_blocks)
        # LIFO free list, lowest ids first out — deterministic for tests
        self._free: List[int] = list(range(self.capacity, 0, -1))
        self._held: set = set()
        self.peak_in_use = 0
        self.total_allocs = 0

    @property
    def blocks_in_use(self) -> int:
        return len(self._held)

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh block ids, or None when the pool can't satisfy the
        request (caller decides whether to wait or preempt) — partial
        allocations never happen."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        self._held.update(ids)
        self.total_allocs += n
        self.peak_in_use = max(self.peak_in_use, len(self._held))
        return ids

    def free(self, ids: List[int]) -> None:
        for b in ids:
            if b not in self._held:
                raise BlockAllocatorError(
                    f"free of block {b} which is not allocated "
                    "(double free or foreign id)")
            self._held.remove(b)
            self._free.append(b)


# ---------------------------------------------------------------------------
# per-row sampling
# ---------------------------------------------------------------------------


def sample_rows(logits: jax.Array, base_key: jax.Array,
                temperature: jax.Array, top_k: jax.Array, top_p: jax.Array,
                seeds: jax.Array, steps: jax.Array) -> jax.Array:
    """Per-row sampling with array-valued knobs: ``logits`` (R, V);
    ``temperature``/``top_p`` (R,) float32; ``top_k`` (R,) int32 (0 = off).
    Rows with ``temperature <= 0`` take the greedy branch — the same
    fp32 argmax as ``inference/engine._sample``, so serving greedy output
    is bit-identical to offline ``generate()``.

    Each row draws from ``fold_in(fold_in(base_key, seeds[r]), steps[r])``
    — ``seeds`` the request's sampling seed, ``steps`` its output-token
    index — so a request's stream depends only on (engine seed, request
    seed, token index), NOT on how the scheduler batched it: reproducible
    across runs and bit-stable across preemption/recompute."""
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # top-k: keep scores >= the k-th largest (per row, traced k)
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        desc, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=1)
    scaled = jnp.where((top_k[:, None] > 0) & (scaled < kth),
                       -jnp.inf, scaled)
    # top-p over the (possibly top-k-filtered) scores; top-1 always survives
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs < top_p[:, None]).at[:, 0].set(True)
    cutoff = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    scaled = jnp.where(scaled >= cutoff, scaled, -jnp.inf)
    keys = jax.vmap(
        lambda s, t: jax.random.fold_in(jax.random.fold_in(base_key, s), t)
    )(seeds, steps)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


# ---------------------------------------------------------------------------
# the two serving programs
# ---------------------------------------------------------------------------


def build_prefill_program(cfg):
    """Jitted prefill-chunk program over the paged arena.

    Args (all shapes static per (C, max_blocks) pair):
      params, cache          — model params / paged arena (arena DONATED)
      block_table (1, MAXB)  — the request's physical block ids
      chunk (1, C) int32     — prompt tokens, zero-padded past ``n_valid``
      start () int32         — absolute position of chunk[0]
      n_valid () int32       — real tokens in this chunk (pad writes land in
                               the scratch block; pad logits are never read)
      temperature/top_k/top_p/seeds (1,) — the request's sampling knobs
      base_key               — the engine's sampling key (constant)

    Returns (token (1,), last_logits (1, V) f32, cache): ``token`` samples
    the position-``n_valid-1`` logits at output-token index 0 — the
    request's FIRST generated token when this was the final chunk, ignored
    otherwise.
    """
    from ..models.transformer import forward as model_forward

    def prefill_chunk(params, cache, block_table, chunk, start, n_valid,
                      temperature, top_k, top_p, seeds, base_key):
        C = chunk.shape[1]
        pos = (start + jnp.arange(C, dtype=jnp.int32))[None]
        write_mask = (jnp.arange(C, dtype=jnp.int32) < n_valid)[None]
        logits, cache, _ = model_forward(params, chunk, cfg, cache=cache,
                                         positions=pos,
                                         block_table=block_table,
                                         paged_write_mask=write_mask)
        last = jnp.take_along_axis(
            logits, jnp.maximum(n_valid - 1, 0)[None, None, None],
            axis=1)[:, 0].astype(jnp.float32)
        tok = sample_rows(last, base_key, temperature, top_k, top_p,
                          seeds, jnp.zeros((1,), jnp.int32))
        return tok, last, cache

    return jax.jit(prefill_chunk, donate_argnums=(1,))


def build_decode_program(cfg):
    """Jitted one-token decode step over the paged arena for a fixed row
    count R. Inactive rows carry an all-zero block table and length 0 — their
    writes land in the scratch block and their sampled tokens are ignored by
    the host — so occupancy changes never respecialize the program.

    Args: params, cache (DONATED), block_table (R, MAXB), lengths (R,) int32
    (tokens already in cache per row — the incoming token's position),
    tokens (R,) int32, temperature/top_k/top_p/seeds (R,), steps (R,) int32
    (each row's output-token index, for the schedule-independent sampling
    stream), base_key.
    Returns (next_token (R,), cache).
    """
    from ..models.transformer import forward as model_forward

    def decode(params, cache, block_table, lengths, tokens,
               temperature, top_k, top_p, seeds, steps, base_key):
        logits, cache, _ = model_forward(params, tokens[:, None], cfg,
                                         cache=cache,
                                         positions=lengths[:, None],
                                         block_table=block_table)
        nxt = sample_rows(logits[:, -1], base_key, temperature, top_k,
                          top_p, seeds, steps)
        return nxt, cache

    return jax.jit(decode, donate_argnums=(1,))
