"""KV handoff — the prefill/decode disaggregation seam.

DistServe-style disaggregation (Zhong et al., OSDI '24) splits serving into
a compute-bound prefill pool (flash prefill, batched by token budget) and a
bandwidth-bound decode pool (paged gather, batched by rows), so each scales
and batches independently. The seam between them is the **KV handoff**: a
sequence prefilled on engine A must continue decoding on engine B, which
means A's resident arena blocks become B's.

:class:`KVHandoff` is the transport interface; :class:`ArenaHandoff` is the
shared-mesh implementation — two jitted programs over the existing paged
arena abstraction:

* ``serving/kv_export`` gathers the request's blocks out of the source
  arena into a dense ``(L, MAXB, BLOCK, K, D)`` transfer buffer (source
  arena NOT donated — its other requests keep decoding from it);
* ``serving/kv_import`` scatters the buffer into freshly allocated blocks
  of the (donated) destination arena.

Both are shape-static: the block lists ride as int32 operands padded to
``MAXB`` with the scratch block 0, so ONE compiled program pair serves any
residency. On one mesh the pair is an in-HBM copy; a cross-host transport
later replaces only the buffer's journey between the two programs — the
``transfer()`` signature (and everything in ``router.py``) is unchanged.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...parallel import mesh as mesh_mod
from ...utils.logging import logger
from .. import paged_kv

__all__ = ["KVHandoff", "ArenaHandoff", "HandoffGeometryError",
           "HandoffTransferError", "register_handoff_audit_entries"]


class HandoffGeometryError(ValueError):
    """Source and destination engines disagree on arena geometry — their
    blocks are not interchangeable."""


class HandoffTransferError(RuntimeError):
    """The KV transfer itself failed mid-flight (a cross-host link drop, a
    device error out of kv_import — or the chaos harness's ``handoff_fail``
    fault standing in for either). Destination blocks are already freed
    when this propagates; the router retries on another decode replica,
    then falls back to decoding in place."""


def _check_geometry(src, dst) -> None:
    scfg, dcfg = src.engine.model.config, dst.engine.model.config
    s = (scfg.num_layers, scfg.num_kv_heads, scfg.head_dim,
         src.config.block_size, src.blocks_per_seq, src._dtype)
    d = (dcfg.num_layers, dcfg.num_kv_heads, dcfg.head_dim,
         dst.config.block_size, dst.blocks_per_seq, dst._dtype)
    if s != d:
        raise HandoffGeometryError(
            f"KV handoff needs identical arena geometry "
            f"(L, KV heads, head dim, block size, blocks/seq, dtype): "
            f"source {s} vs destination {d} — a fleet config this "
            f"mismatched is caught statically by `python -m tools.tpushard` "
            f"(finding serving/kv_export::cross-program-mismatch)")


class KVHandoff:
    """Transport interface: move ``blocks`` (source-engine block ids) into
    the destination engine's arena. Returns the destination block ids —
    same count, request-order preserved — or None when the destination
    pool cannot take them right now (the router's fallback signal). A
    transfer that starts and then FAILS raises ``HandoffTransferError``
    with the destination blocks already freed.
    Implementations own their device programs; the router owns policy.

    ``inject_fail_next`` is the chaos seam: each unit makes the next
    ``transfer`` fail AFTER destination allocation (and, for
    ``ArenaHandoff``, after the export) — exercising the exact
    free-on-failure path a real mid-flight loss takes. The router arms it
    from the ``handoff_fail`` fault plan."""

    inject_fail_next: int = 0

    def transfer(self, src, dst, blocks: List[int],
                 trace=None) -> Optional[List[int]]:
        """``trace`` (an ``observability.reqtrace.ReqTrace``, or None) is
        the request-trace context riding the seam: implementations record
        the export → transfer → import stages onto it so a handoff's
        timeline carries BOTH replicas."""
        raise NotImplementedError

    def _maybe_inject_failure(self) -> None:
        if self.inject_fail_next > 0:
            self.inject_fail_next -= 1
            raise HandoffTransferError(
                "injected handoff_fail fault (chaos harness)")


class ArenaHandoff(KVHandoff):
    """Shared-mesh handoff: jitted gather out of the source arena, jitted
    scatter into the destination arena (an in-HBM copy on one mesh)."""

    def __init__(self):
        self._export = paged_kv.build_kv_export_program()
        self._import = paged_kv.build_kv_import_program()
        self.transfers = 0
        self.inject_fail_next = 0

    def transfer(self, src, dst, blocks: List[int],
                 trace=None) -> Optional[List[int]]:
        """``src``/``dst`` are ServingEngines (callers hold whatever locks
        protect them — the router runs this inside its iteration). The
        destination blocks come from PLAIN allocation: a handoff never
        evicts or preempts the decode pool's residents. When ``trace`` is
        set, the export and import stages land on the request's trace with
        their replica identities — the handoff timeline spans both ends of
        the seam."""
        _check_geometry(_EngineView(src), _EngineView(dst))
        dst_ids = dst.alloc.alloc(len(blocks))
        if dst_ids is None:
            return None
        maxb = src.blocks_per_seq
        src_pad = np.zeros((maxb,), np.int32)
        src_pad[:len(blocks)] = blocks
        dst_pad = np.zeros((maxb,), np.int32)
        dst_pad[:len(dst_ids)] = dst_ids
        from ...observability import get_session

        obs = get_session()
        rt = obs.reqtrace if trace is not None else None
        clock = src.clock
        try:
            with obs.span("fleet/kv_handoff", blocks=len(blocks)):
                t0 = clock() if rt is not None else 0.0
                with mesh_mod.ambient(src.engine.mesh):
                    buf_k, buf_v = self._export(src._arena, src_pad)
                    if rt is not None:
                        import jax

                        # tpusync: disable=blocking-under-lock — tracing
                        # mode only; the sync buys stage-honest export/
                        # import timings and the handoff must be atomic
                        # with arena state anyway
                        jax.block_until_ready(buf_k)   # stage-honest split
                if rt is not None:
                    t1 = clock()
                    rt.interval(trace, "handoff", t0, t1,
                                kind="handoff_export",
                                replica=src.trace_tag, blocks=len(blocks))
                # mid-flight: after the export left the source, before the
                # import commits to the destination — the window a real
                # cross-host transfer dies in
                self._maybe_inject_failure()
                t2 = clock() if rt is not None else 0.0
                with mesh_mod.ambient(dst.engine.mesh):
                    dst._arena = self._import(dst._arena, buf_k, buf_v,
                                              dst_pad)
                import jax

                # tpusync: disable=blocking-under-lock — the import must
                # commit before the request rebinds to the decode replica;
                # a torn arena is worse than a stalled lock, and the copy
                # is bounded (one request's blocks, layer-chunked)
                jax.block_until_ready(dst._arena["k"])   # honest latency
                if rt is not None:
                    rt.interval(trace, "handoff", t2, clock(),
                                kind="handoff_import",
                                replica=dst.trace_tag, blocks=len(dst_ids))
        except Exception:
            # a failed transfer must not leak destination blocks; a partial
            # import is harmless garbage once its blocks return to the pool
            dst.alloc.free(dst_ids)
            raise
        self.transfers += 1
        return dst_ids


class _EngineView:
    """Geometry-check adapter (``_check_geometry`` predates the router's
    Replica wrapper and is also used engine-to-engine)."""

    def __init__(self, engine):
        self.engine = engine.engine
        self.config = engine.config
        self.blocks_per_seq = engine.blocks_per_seq
        self._dtype = engine._dtype


def register_handoff_audit_entries(engine, handoff: ArenaHandoff
                                   ) -> List[str]:
    """Register ``serving/kv_export`` / ``serving/kv_import`` with tpuaudit
    (and therefore tpucost): pure block gather/scatter along the replicated
    block axis — zero collectives whatever the engine's TP/EP layout; the
    import donates the destination arena. ``engine`` supplies the arena
    shapes (source and destination pools share geometry by construction)."""
    try:
        from tools.tpuaudit.registry import (StaleEntryError,
                                             register_entry_point)
    except ImportError:
        return []
    try:
        import weakref

        import jax
        import jax.numpy as jnp

        weng = weakref.ref(engine)
        maxb = engine.blocks_per_seq
        cfg = engine.engine.model.config
        bs = engine.config.block_size

        def _shapes(eng):
            arena = eng._arena_sds()
            buf = jax.ShapeDtypeStruct(
                (cfg.num_layers, maxb, bs, cfg.num_kv_heads, cfg.head_dim),
                eng._dtype)
            ids = jax.ShapeDtypeStruct((maxb,), jnp.int32)
            return arena, buf, ids

        def build_export():
            eng = weng()
            if eng is None:
                raise StaleEntryError("serving/kv_export: engine gone")
            arena, _, ids = _shapes(eng)
            return handoff._export, (arena, ids), {}

        def build_import():
            eng = weng()
            if eng is None:
                raise StaleEntryError("serving/kv_import: engine gone")
            arena, buf, ids = _shapes(eng)
            return handoff._import, (arena, buf, buf, ids), {}

        # no params in these programs — the "handoff" tag is tpushard's
        # geometry seam: export OUTPUT buffers must land exactly like
        # import's staging-buffer ARGS (args 1, 2), else the fleet would
        # reshard every migrated request's KV mid-flight
        register_entry_point(
            "serving/kv_export", build=build_export,
            expected_collectives=(), mesh=engine.engine.mesh,
            tags={"engine": "FleetRouter", "max_blocks": maxb,
                  "block_size": bs,
                  "handoff": {"role": "export"}})
        register_entry_point(
            "serving/kv_import", build=build_import, donate_argnums=(0,),
            expected_collectives=(), mesh=engine.engine.mesh,
            tags={"engine": "FleetRouter", "max_blocks": maxb,
                  "block_size": bs,
                  "handoff": {"role": "import", "buffer_args": (1, 2)}})
        return ["serving/kv_export", "serving/kv_import"]
    except Exception:   # registration must never take serving down
        logger.warning("tpuaudit handoff registration failed", exc_info=True)
        return []
