"""Serving fleet — scale the single-arena serving stack out.

One ``ServingEngine`` is one arena on one mesh; this package is the
deployment layer over N of them (the DeepSpeed-MII/FastGen analog taken
past one engine, ROADMAP item 2):

  replica.py   Replica + the cheap ReplicaHealth snapshot the router
               polls between scheduler iterations, plus the replica
               lifecycle state machine (quarantine → probation →
               graduation, death → revival, circuit-breaker retirement)
  router.py    FleetRouter: same submit()/stream()/result()/cancel()
               surface as ServingEngine, pluggable routing policies
               (queue-depth / KV-occupancy / prefix-affinity with
               cross-replica admission hints), replica-death drain +
               bit-exact resubmission, health verdicts (slow/TTFT-SLO
               quarantine), replica revival with probation, overload
               admission control (Overloaded/retry_after_s) and the
               degraded-mode ladder
  disagg.py    prefill/decode disaggregation: the KVHandoff seam and the
               in-HBM ArenaHandoff (jitted block gather/scatter —
               serving/kv_export + serving/kv_import), with a
               deterministic transfer-failure seam for the chaos gate

See docs/serving.md ("Fleet serving & disaggregation", "Fleet
self-healing & overload").
"""

from .disagg import (ArenaHandoff, HandoffGeometryError,  # noqa: F401
                     HandoffTransferError, KVHandoff)
from .replica import (ROLE_DECODE, ROLE_MIXED, ROLE_PREFILL,  # noqa: F401
                      Replica, ReplicaDead, ReplicaHealth, build_replicas)
from .router import (FleetHandle, FleetRouter,  # noqa: F401
                     FleetUnavailable, Overloaded)

__all__ = [
    "FleetRouter", "FleetHandle", "FleetUnavailable", "Overloaded",
    "Replica", "ReplicaHealth", "ReplicaDead", "build_replicas",
    "ROLE_MIXED", "ROLE_PREFILL", "ROLE_DECODE",
    "KVHandoff", "ArenaHandoff", "HandoffGeometryError",
    "HandoffTransferError",
]
