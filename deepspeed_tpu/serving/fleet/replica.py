"""Replica — one ``ServingEngine`` behind the fleet router.

A ``Replica`` wraps a serving engine with the three things the router
needs that the engine itself does not expose: an identity + role (mixed /
prefill / decode for disaggregation), a liveness flag the chaos harness
can flip (``replica_kill``) and real death detection hooks onto, and a
cheap host-side :class:`ReplicaHealth` snapshot the router polls between
scheduler iterations — every field is a host counter read, no device sync.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

__all__ = ["Replica", "ReplicaHealth", "ReplicaDead",
           "ROLE_MIXED", "ROLE_PREFILL", "ROLE_DECODE", "build_replicas"]

ROLE_MIXED = "mixed"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


class ReplicaDead(RuntimeError):
    """The replica is not serving (killed by fault injection, a crashed
    driver thread, or an explicit drain)."""


@dataclasses.dataclass
class ReplicaHealth:
    """Cheap load/occupancy snapshot of one replica — the router's routing
    and drain decisions read THIS, never the engine's internals."""

    index: int
    role: str
    alive: bool
    queue_depth: int = 0            # requests waiting for admission
    in_flight: int = 0              # queued + running (+ pending forks)
    kv_blocks_in_use: int = 0
    kv_blocks_free: int = 0
    arena_occupancy: float = 0.0    # allocated fraction of the block pool
    decode_batch_occupancy: float = 0.0   # decoding rows / max_seqs

    @property
    def load_key(self):
        """Stable comparison key for occupancy-aware routing: fullest
        metric first, then queue pressure, then index (determinism)."""
        return (self.arena_occupancy, self.in_flight, self.index)


class Replica:
    """One fleet member. ``role`` partitions the fleet for prefill/decode
    disaggregation (``ROLE_MIXED`` replicas serve both phases)."""

    def __init__(self, engine, index: int, role: str = ROLE_MIXED):
        if role not in (ROLE_MIXED, ROLE_PREFILL, ROLE_DECODE):
            raise ValueError(f"unknown replica role '{role}'")
        self.engine = engine
        self.index = int(index)
        self.role = role
        self.alive = True
        self.drained = False        # router bookkeeping: dead AND resubmitted
        self.death_reason: Optional[str] = None

    def kill(self, reason: str = "killed") -> None:
        """Mark the replica dead. The router stops stepping it and its
        in-flight requests are resubmitted elsewhere on the next router
        iteration; the engine object's host state is NOT consulted again —
        a real process death leaves nothing to consult."""
        if self.alive:
            self.alive = False
            self.death_reason = reason

    def step(self) -> bool:
        if not self.alive:
            raise ReplicaDead(
                f"replica {self.index} is dead ({self.death_reason})")
        return self.engine.step()

    def health(self) -> ReplicaHealth:
        if not self.alive:
            return ReplicaHealth(index=self.index, role=self.role,
                                 alive=False)
        eng = self.engine
        alloc = eng.alloc
        sched = eng.sched
        return ReplicaHealth(
            index=self.index, role=self.role, alive=True,
            queue_depth=sched.queue_depth(),
            in_flight=eng.in_flight(),
            kv_blocks_in_use=alloc.blocks_in_use,
            kv_blocks_free=alloc.blocks_free,
            arena_occupancy=alloc.blocks_in_use / max(alloc.capacity, 1),
            decode_batch_occupancy=(len(sched.decode_requests())
                                    / eng.config.max_seqs))


def build_replicas(engine, serving_config, n: int,
                   roles: Optional[List[str]] = None,
                   clock=None, draft_engine=None) -> List[Replica]:
    """N serving replicas over ONE set of weights (the in-process fleet the
    tests and bench drive; a multi-host fleet builds one ServingEngine per
    host and wraps each the same way). The replicas share the underlying
    ``InferenceEngine``'s params and — since their arena/program shapes are
    identical — the first replica's compiled serving programs, so a fleet
    costs one compile set plus N arenas, not N compile sets."""
    import copy

    from ..api import ServingEngine

    if n < 1:
        raise ValueError(f"build_replicas(n={n}): need n >= 1")
    if roles is not None and len(roles) != n:
        raise ValueError(f"build_replicas: {len(roles)} roles for {n} "
                         "replicas")
    replicas: List[Replica] = []
    first = None
    for i in range(n):
        kw = {"clock": clock} if clock is not None else {}
        srv = ServingEngine(engine, copy.deepcopy(serving_config),
                            draft_engine=draft_engine, **kw)
        if first is None:
            first = srv
        else:
            # identical (cfg, shapes) → the jitted callables are
            # interchangeable; sharing them collapses N compiles into 1
            srv._prefill = first._prefill
            srv._decode = first._decode
            srv._cow = first._cow
            if srv._verify is not None:
                srv._verify = first._verify
        replicas.append(Replica(srv, index=i,
                                role=roles[i] if roles else ROLE_MIXED))
    return replicas
