"""Replica — one ``ServingEngine`` behind the fleet router.

A ``Replica`` wraps a serving engine with what the router needs that the
engine itself does not expose:

* identity + role (mixed / prefill / decode for disaggregation);
* the **lifecycle state machine** the self-healing loop drives::

      serving ──slow/TTFT-breach──▶ quarantined ──backoff──▶ probation
         ▲                                                      │
         │◀──────────────── N clean completions ────────────────┘
         │
         ├──kill/step-exception──▶ dead ──revive()──▶ probation
         │
         └──incidents > breaker──▶ retired (terminal)

  Quarantined replicas are alive — they keep stepping their in-flight
  work but take no new traffic until the backoff expires. Dead replicas
  are drained (requests resubmitted elsewhere) and may be **rebuilt**
  reusing the fleet's shared weights and already-compiled programs.
  Probation bounds a re-admitted replica's traffic share until it proves
  itself with clean completions. The circuit breaker retires a replica
  that keeps flapping — retirement is terminal, never revived.
* a cheap host-side :class:`ReplicaHealth` snapshot the router polls
  between scheduler iterations — every field is a host counter read, no
  device sync — now including a rolling step-time window the router
  feeds from its own wall-clock measurements (the slow-replica verdict
  input).
"""

from __future__ import annotations

import collections
import dataclasses
import statistics
from typing import TYPE_CHECKING, List, Optional

if TYPE_CHECKING:
    from ..api import ServingEngine

__all__ = ["Replica", "ReplicaHealth", "ReplicaDead", "ReplicaRetired",
           "ROLE_MIXED", "ROLE_PREFILL", "ROLE_DECODE", "build_replicas"]

ROLE_MIXED = "mixed"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


class ReplicaDead(RuntimeError):
    """The replica is not serving (killed by fault injection, a crashed
    driver thread, or an explicit drain)."""


def graft_programs(dst, src) -> None:
    """Share ``src``'s compiled serving programs into ``dst``: identical
    (config, shapes) by fleet construction make the jitted callables
    interchangeable, collapsing N compiles into 1 — the fact both
    fleet construction and replica revival are built on (ONE copy of the
    contract; a program added to ServingEngine joins the fleet here)."""
    dst._prefill = src._prefill
    dst._decode = src._decode
    dst._cow = src._cow
    dst._score = src._score
    if dst._verify is not None and src._verify is not None:
        dst._verify = src._verify


class ReplicaRetired(RuntimeError):
    """The replica tripped its circuit breaker (too many incidents) and is
    permanently out of the fleet — revival is refused."""


@dataclasses.dataclass
class ReplicaHealth:
    """Cheap load/occupancy snapshot of one replica — the router's routing
    and drain decisions read THIS, never the engine's internals."""

    index: int
    role: str
    alive: bool
    queue_depth: int = 0            # requests waiting for admission
    in_flight: int = 0              # queued + running (+ pending forks)
    kv_blocks_in_use: int = 0
    kv_blocks_free: int = 0
    arena_occupancy: float = 0.0    # allocated fraction of the block pool
    decode_batch_occupancy: float = 0.0   # decoding rows / max_seqs
    quarantined: bool = False       # alive but taking no new traffic
    probation_left: int = 0         # clean completions still owed (> 0 =
    #   on probation: traffic share bounded)
    step_time_median_s: Optional[float] = None  # rolling median of
    #   router-measured iteration wall times (None until window warm)

    @property
    def load_key(self):
        """Stable comparison key for occupancy-aware routing: fullest
        metric first, then queue pressure, then index (determinism)."""
        return (self.arena_occupancy, self.in_flight, self.index)


class Replica:
    """One fleet member. ``role`` partitions the fleet for prefill/decode
    disaggregation (``ROLE_MIXED`` replicas serve both phases)."""

    def __init__(self, engine: "ServingEngine", index: int,
                 role: str = ROLE_MIXED, health_window: int = 8):
        if role not in (ROLE_MIXED, ROLE_PREFILL, ROLE_DECODE):
            raise ValueError(f"unknown replica role '{role}'")
        self.engine = engine
        self.index = int(index)
        self.role = role
        self.alive = True
        self.drained = False        # router bookkeeping: dead AND resubmitted
        self.death_reason: Optional[str] = None
        # -- lifecycle state (router-driven; see module docstring) --
        self.quarantined = False
        self.quarantine_reason: Optional[str] = None
        self.quarantine_until = 0   # router iteration the backoff expires at
        self.revive_at = 0          # router iteration revival may be tried
        self.death_iteration = 0    # router iteration of the last kill
        #   (the bench's time-to-revival input)
        self.probation_left = 0     # clean completions owed before full
        #   routing weight (0 = full member)
        self.deaths = 0
        self.quarantines = 0
        self.revivals = 0
        self.retired = False        # circuit breaker tripped — terminal
        # rebuild inputs, captured NOW: once the replica is declared dead
        # its engine object is never consulted again, so revival needs the
        # construction recipe up front (the InferenceEngine — weights and
        # mesh — is fleet-shared and survives any replica's death)
        self._infer_engine = getattr(engine, "engine", None)
        self._draft_engine = getattr(engine, "_draft_engine", None)
        self._clock = getattr(engine, "clock", None)
        import copy

        self._cfg_template = (copy.deepcopy(engine.config)
                              if engine is not None else None)
        # router-measured iteration wall times (the slow-verdict input);
        # warmup_left steps are discarded first — the router sets it from
        # fleet.health_warmup_steps so compile jitter never convicts
        self.warmup_left = 0
        self.step_times: "collections.deque" = collections.deque(
            maxlen=max(int(health_window), 2))

    @property
    def incidents(self) -> int:
        """Circuit-breaker ledger: every death and every quarantine counts."""
        return self.deaths + self.quarantines

    def kill(self, reason: str = "killed") -> None:
        """Mark the replica dead. The router stops stepping it and its
        in-flight requests are resubmitted elsewhere on the next router
        iteration; the engine object's host state is NOT consulted again —
        a real process death leaves nothing to consult."""
        if self.alive:
            self.alive = False
            self.death_reason = reason
            self.deaths += 1
            self.quarantined = False
            self.quarantine_reason = None
            self.probation_left = 0
            self.step_times.clear()

    def quarantine(self, reason: str, until_iteration: int) -> None:
        """Alive but suspect: no new traffic until ``until_iteration``."""
        if not self.alive or self.quarantined:
            return
        self.quarantined = True
        self.quarantine_reason = reason
        self.quarantine_until = int(until_iteration)
        self.quarantines += 1
        self.step_times.clear()     # the window that convicted it is stale

    def retire(self) -> None:
        """Circuit breaker: permanently out — ``revive`` refuses."""
        self.retired = True
        self.kill("breaker")

    def routable(self) -> bool:
        """May receive NEW traffic (probation share is the router's call)."""
        return self.alive and not self.quarantined

    def note_step_time(self, dt_s: float) -> None:
        if self.warmup_left > 0:
            self.warmup_left -= 1
            return
        self.step_times.append(float(dt_s))

    def step_time_median(self) -> Optional[float]:
        """Rolling median once the window is warm (None before — a verdict
        off two samples would quarantine on compile jitter)."""
        if len(self.step_times) < self.step_times.maxlen:
            return None
        return statistics.median(self.step_times)

    def rebuild(self, donor: Optional["Replica"] = None):
        """Build a replacement ``ServingEngine`` from the captured recipe:
        the fleet-shared InferenceEngine (weights, mesh) plus a fresh copy
        of this replica's serving config — and graft the fleet's
        already-compiled program set from ``donor`` (any alive replica), so
        revival costs one arena allocation, not a compile set. Returns the
        new engine; the caller (router) swaps it in via :meth:`revive`."""
        if self.retired:
            raise ReplicaRetired(
                f"replica {self.index} is retired (circuit breaker) — "
                "refusing to rebuild")
        import copy

        from ..api import ServingEngine

        kw = {"clock": self._clock} if self._clock is not None else {}
        srv = ServingEngine(self._infer_engine,
                            copy.deepcopy(self._cfg_template),
                            draft_engine=self._draft_engine, **kw)
        if donor is not None and donor.alive:
            graft_programs(srv, donor.engine)
        return srv

    def revive(self, new_engine, probation_requests: int) -> None:
        """Swap in the rebuilt engine and re-enter the fleet ON PROBATION:
        the router bounds this replica's traffic share until
        ``probation_requests`` requests complete cleanly on it."""
        if self.retired:
            raise ReplicaRetired(
                f"replica {self.index} is retired — refusing to revive")
        self.engine = new_engine
        self.alive = True
        self.drained = False
        self.quarantined = False
        self.quarantine_reason = None
        self.probation_left = int(probation_requests)
        self.revivals += 1
        self.step_times.clear()

    def step(self) -> bool:
        if not self.alive:
            raise ReplicaDead(
                f"replica {self.index} is dead ({self.death_reason})")
        return self.engine.step()

    def health(self) -> ReplicaHealth:
        if not self.alive:
            return ReplicaHealth(index=self.index, role=self.role,
                                 alive=False)
        eng = self.engine
        alloc = eng.alloc
        sched = eng.sched
        return ReplicaHealth(
            index=self.index, role=self.role, alive=True,
            queue_depth=sched.queue_depth(),
            in_flight=eng.in_flight(),
            kv_blocks_in_use=alloc.blocks_in_use,
            kv_blocks_free=alloc.blocks_free,
            arena_occupancy=alloc.blocks_in_use / max(alloc.capacity, 1),
            decode_batch_occupancy=(len(sched.decode_requests())
                                    / eng.config.max_seqs),
            quarantined=self.quarantined,
            probation_left=self.probation_left,
            step_time_median_s=self.step_time_median())


def build_replicas(engine, serving_config, n: int,
                   roles: Optional[List[str]] = None,
                   clock=None, draft_engine=None) -> List[Replica]:
    """N serving replicas over ONE set of weights (the in-process fleet the
    tests and bench drive; a multi-host fleet builds one ServingEngine per
    host and wraps each the same way). The replicas share the underlying
    ``InferenceEngine``'s params and — since their arena/program shapes are
    identical — the first replica's compiled serving programs, so a fleet
    costs one compile set plus N arenas, not N compile sets. (Replica
    revival leans on the same fact: a rebuilt engine grafts a surviving
    replica's program set.)"""
    import copy

    from ..api import ServingEngine

    if n < 1:
        raise ValueError(f"build_replicas(n={n}): need n >= 1")
    if roles is not None and len(roles) != n:
        raise ValueError(f"build_replicas: {len(roles)} roles for {n} "
                         "replicas")
    replicas: List[Replica] = []
    first = None
    for i in range(n):
        kw = {"clock": clock} if clock is not None else {}
        srv = ServingEngine(engine, copy.deepcopy(serving_config),
                            draft_engine=draft_engine, **kw)
        if first is None:
            first = srv
        else:
            graft_programs(srv, first)
        replicas.append(Replica(srv, index=i,
                                role=roles[i] if roles else ROLE_MIXED))
    return replicas
