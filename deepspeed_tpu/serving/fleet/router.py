"""FleetRouter — the data-plane front end over N serving replicas.

Exposes the same ``submit()/stream()/result()/cancel()`` surface as one
``ServingEngine`` and dispatches to a fleet of them:

* **Routing** — pluggable policies over the per-replica
  :class:`~.replica.ReplicaHealth` snapshot the router polls between
  scheduler iterations: ``round_robin``, ``least_queue`` (fewest in-flight
  requests), ``kv_occupancy`` (lowest arena occupancy) and ``affinity``
  (prefix-cache locality: the router remembers which replica served each
  first-prompt-block hash, so requests sharing a system prompt follow the
  warm prefix cache instead of re-prefilling it N times — the
  cross-replica prefix-cache admission hint). Every decision is counted by
  reason in ``fleet_serving/routing_decisions``.
* **Disaggregation** — replicas carry roles (``prefill`` / ``decode``):
  a request prefills on the prefill pool, then its KV blocks move to a
  decode replica through the :class:`~.disagg.KVHandoff` seam and decoding
  continues there, bit-identically (the sampling stream depends only on
  (engine seed, request seed, token index), never on which engine runs
  it). A handoff the decode pool cannot take — or whose TRANSFER fails
  mid-flight (``handoff_fail`` chaos fault, kv_import raising) after one
  retry on another decode replica — falls back to decoding in place,
  with both sides' blocks freed exactly once.
* **Self-healing** — the full detect → remediate → verify loop, not just
  detect-and-drain:

  - a dead replica (chaos ``replica_kill``, an exception out of its
    scheduler iteration) is drained: every in-flight request resubmits to
    a surviving replica in recompute mode (``submit_recovered``), which
    re-prefills prompt + streamed-tokens and continues the stream
    bit-exactly. A resubmission that finds every survivor momentarily
    full PARKS and retries on later iterations instead of burning the
    ``max_resubmits`` budget (the budget counts replica deaths, not full
    queues).
  - health **verdicts** go beyond "step() raised": a replica whose
    rolling median step time exceeds ``slow_factor ×`` the other
    replicas' medians (or the absolute ``step_time_slo_s``), or that
    breaches the fleet ``ttft_slo_s``, is **quarantined** — alive,
    draining its own work, but receiving no new traffic — for an
    exponentially backed-off window (the elastic agent's ladder, in
    router iterations).
  - a dead replica is **revived**: ``revive_replica()`` rebuilds its
    engine reusing the fleet-shared weights and the already-compiled
    program set of a surviving replica (cheap by construction — one
    arena allocation, zero compiles), then re-admits it through
    **probation**: its traffic share stays bounded
    (``probation_share``) until ``probation_requests`` requests complete
    cleanly, at which point it graduates to full routing weight.
  - the per-replica **circuit breaker** retires a replica whose
    incidents (deaths + quarantines) exceed ``breaker_incidents`` —
    a flapping replica is removed for good instead of flapping forever.
* **Overload control** — ``submit()`` sheds deadline-infeasible work
  up front: when the measured fleet TPOT says ``deadline_s`` cannot be
  met at the target replica's queue depth, the request is rejected
  immediately with :class:`Overloaded` (``retry_after_s`` set) instead of
  admitted to die. Under sustained pressure the router walks a
  **degraded-mode ladder** (``fleet_serving/degraded_mode``): rung 1
  suspends speculative decoding fleet-wide (freeing the draft arenas'
  block traffic), rung 2 stops following prefix-affinity admission hints
  (load beats locality), rung 3 sheds queued work — no-deadline /
  latest-deadline first — one victim per iteration. Calm iterations walk
  the ladder back down with hysteresis.

The router DRIVES its replicas (one scheduler iteration per replica per
``step()``); replica engines must not run their own driver threads.
``start()`` provides the fleet's background thread.
"""

from __future__ import annotations

import collections
import statistics
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from ...config.config import FleetConfig
from ...observability import get_session
from ...utils.logging import log_dist, logger
from ..scheduler import DEADLINE_EXCEEDED, FINISHED, QUEUED, QueueFull
from .disagg import (ArenaHandoff, KVHandoff,
                     register_handoff_audit_entries)
from .replica import (ROLE_DECODE, ROLE_MIXED, ROLE_PREFILL, Replica,
                      ReplicaDead, ReplicaRetired)

__all__ = ["FleetRouter", "FleetHandle", "FleetUnavailable", "Overloaded"]

RUNNING = "running"
F_FINISHED = "finished"
F_CANCELLED = "cancelled"
F_SHED = "shed"
F_DEADLINE = "deadline_exceeded"

# degraded-mode ladder rungs (the fleet_serving/degraded_mode gauge)
DEGRADED_NONE = 0          # normal service
DEGRADED_NO_SPEC = 1       # speculation suspended fleet-wide
DEGRADED_NO_AFFINITY = 2   # prefix-affinity hints ignored (load > locality)
DEGRADED_SHED = 3          # queued work shed, latest-deadline first


class FleetUnavailable(RuntimeError):
    """No alive replica can take the request."""


class Overloaded(RuntimeError):
    """The fleet cannot serve this request in time: either its deadline is
    infeasible at current queue depth + measured TPOT (admission shed), or
    the degraded-mode ladder shed it from the queue. ``retry_after_s`` is
    the structured back-off hint — resubmitting sooner just gets shed
    again."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class _FleetRequest:
    """Router-side record of one client request: the original submission
    (the resubmit source of truth) plus the CURRENT engine binding."""

    def __init__(self, fid: int, prompt: np.ndarray, seed: int,
                 kwargs: Dict[str, Any], arrival_s: float):
        self.fid = fid
        self.prompt = prompt
        self.seed = seed
        self.kwargs = kwargs          # max_new_tokens/sampling/eos/tenant
        self.deadline_abs: Optional[float] = None
        self.state = RUNNING
        self.replica: Optional[Replica] = None
        self.u_req = None             # bound engine-side Request
        self.u_handle = None          # ... and its RequestHandle
        self.consumed = 0             # tokens drained off u_handle so far
        self.resubmits = 0
        self.handoffs = 0
        self.arrival_s = arrival_s
        self.first_token_s: Optional[float] = None
        self.finish_s: Optional[float] = None
        self.handle: Optional["FleetHandle"] = None
        self.retry_after_s = 0.0      # set when the ladder sheds this

    def bind(self, replica: Replica, u_handle) -> None:
        self.replica = replica
        self.u_handle = u_handle
        self.u_req = u_handle._req
        self.consumed = 0

    @property
    def done(self) -> bool:
        return self.state in (F_FINISHED, F_CANCELLED, F_SHED, F_DEADLINE)


class FleetHandle:
    """Client view of one fleet request: the same incremental streaming
    surface as ``RequestHandle``, stable across KV handoffs and replica
    deaths (the router rebinds the engine side underneath it)."""

    def __init__(self, router: "FleetRouter", fr: _FleetRequest):
        self._router = router
        self._fr = fr
        self._cond = threading.Condition()
        self._tokens: List[int] = []

    # -- router-side -------------------------------------------------------
    def _push(self, token: int) -> None:
        with self._cond:
            self._tokens.append(int(token))
            self._cond.notify_all()

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    # -- client-side -------------------------------------------------------
    @property
    def request_id(self) -> int:
        return self._fr.fid

    @property
    def state(self) -> str:
        return self._fr.state

    @property
    def done(self) -> bool:
        return self._fr.done

    @property
    def tokens(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        if self._fr.first_token_s is None:
            return None
        return self._fr.first_token_s - self._fr.arrival_s

    @property
    def tpot_s(self) -> Optional[float]:
        fr = self._fr
        if (fr.finish_s is None or fr.first_token_s is None
                or len(self._tokens) < 2):
            return None
        return (fr.finish_s - fr.first_token_s) / (len(self._tokens) - 1)

    @property
    def resubmits(self) -> int:
        return self._fr.resubmits

    @property
    def handoffs(self) -> int:
        return self._fr.handoffs

    def cancel(self) -> bool:
        return self._router.cancel(self)

    def stream(self, timeout_s: Optional[float] = None) -> Iterator[int]:
        """Yield tokens as generated; in step-driven mode this drives the
        ROUTER (one fleet iteration per starved pass)."""
        from ..session import drive_stream

        rt = self._router
        yield from drive_stream(
            self._cond, self._tokens, lambda: self._fr.done, rt.clock,
            lambda: rt.threaded, rt.step, lambda: rt._starvation_limit,
            f"fleet request {self._fr.fid}",
            "fleet stalled — no replica can make progress", timeout_s)

    def result(self, timeout_s: Optional[float] = None) -> np.ndarray:
        for _ in self.stream(timeout_s=timeout_s):
            pass
        if self._fr.state == F_CANCELLED:
            from ..session import RequestCancelled

            raise RequestCancelled(
                f"fleet request {self._fr.fid} was cancelled")
        if self._fr.state == F_DEADLINE:
            from ..session import DeadlineExceeded

            raise DeadlineExceeded(
                f"fleet request {self._fr.fid} missed its deadline")
        if self._fr.state == F_SHED:
            raise Overloaded(
                f"fleet request {self._fr.fid} was shed under overload "
                f"(degraded mode)", retry_after_s=self._fr.retry_after_s)
        return np.asarray(self.tokens, np.int32)


class FleetRouter:
    """Data-plane router over N serving replicas (see module docstring)."""

    def __init__(self, replicas: List[Replica],
                 config: Optional[FleetConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 fault_plan: Any = None,
                 handoff: Optional[KVHandoff] = None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(replicas)
        self.config = config or FleetConfig()
        self.config.validate()
        self.clock = clock
        geoms = {(r.engine.config.block_size, r.engine.config.max_model_len)
                 for r in self.replicas}
        if len(geoms) > 1:
            raise ValueError(
                f"fleet replicas disagree on block geometry {sorted(geoms)}"
                " — affinity keys and KV handoffs need one (block_size, "
                "max_model_len)")
        self._block_size = self.replicas[0].engine.config.block_size
        for r in self.replicas:
            # the verdict window length is fleet policy, not replica state
            r.step_times = collections.deque(
                r.step_times, maxlen=self.config.health_window)
            r.warmup_left = self.config.health_warmup_steps
            # request traces + serve_goodput gauges carry the replica index
            r.engine.trace_tag = str(r.index)
            # the router owns the fleet's live tuner; replica engines must
            # not each grow their own
            r.engine._fleet_managed = True
        roles = {r.role for r in self.replicas}
        self.disagg = roles != {ROLE_MIXED}
        self.prefill_pool = [r for r in self.replicas
                             if r.role in (ROLE_PREFILL, ROLE_MIXED)]
        self.decode_pool = [r for r in self.replicas
                            if r.role in (ROLE_DECODE, ROLE_MIXED)]
        if self.disagg and (not self.prefill_pool or not self.decode_pool):
            raise ValueError(
                "disaggregated fleet needs at least one prefill and one "
                f"decode replica (roles: {sorted(roles)})")
        self.handoff = handoff or (ArenaHandoff() if self.disagg else None)
        if self.disagg:
            # fail FAST on arena-geometry mismatch: every prefill replica
            # must be able to hand blocks to every decode replica. Checked
            # once here — a HandoffGeometryError surfacing at transfer
            # time would be swallowed by the mid-flight retry/fallback
            # path and silently disable disaggregation
            from .disagg import _check_geometry, _EngineView

            for p in self.prefill_pool:
                for d in self.decode_pool:
                    if p.engine is not d.engine:
                        _check_geometry(_EngineView(p.engine),
                                        _EngineView(d.engine))
        if self.disagg:
            for r in self.prefill_pool:
                if r.role != ROLE_PREFILL:
                    continue
                r.engine.on_prefill_complete = (
                    lambda req, _r=r: self._handoff_from(_r, req))
            register_handoff_audit_entries(self.replicas[0].engine,
                                           self.handoff)
        self._lock = threading.RLock()
        self._fid = 0
        self._iterations = 0
        # fid -> live request; terminal requests are pruned (the client
        # keeps its handle) so a long-running router stays bounded
        self._requests: Dict[int, _FleetRequest] = {}
        self._by_engine: Dict[tuple, int] = {}   # (replica_idx, rid) -> fid
        # first-prompt-block hash -> replica index (bounded LRU): the
        # cross-replica prefix-cache admission hint
        self._affinity: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._rr = 0
        # host-side (policy, reason) -> count mirror of the
        # fleet_serving/routing_decisions counter, for obs-less callers
        # (the bench A/B reads this)
        self._decisions: "collections.Counter" = collections.Counter()
        self._handoff_ms = collections.deque(maxlen=8192)
        self._resubmit_count = 0
        self._death_count = 0
        self._handoff_fallbacks = 0
        self._handoff_failures = 0
        # resubmissions parked on QueueFull (every survivor momentarily
        # full): fids retried each iteration WITHOUT spending budget
        self._parked: List[int] = []
        # -- self-healing ledger --
        self._quarantine_count = 0
        self._revival_count = 0
        self._graduation_count = 0
        self._ttft_breaches = 0
        # death→revival iteration gaps (the bench's time-to-revival)
        self._revive_iters: List[int] = []
        # engines replaced by revivals: their latency reservoirs and token
        # counts must still pool into the close-time fleet-wide gauges,
        # and their close() (drafter teardown) must still run. Bounded:
        # each replica retires after <= breaker_incidents revivals
        self._replaced_engines: List[Any] = []
        # -- overload control state --
        self._degraded = DEGRADED_NONE
        # admission-estimate pad: the live tuner's deadline knob.
        # _estimate_completion_s scales by (1 + pad), so pad > 0 sheds
        # deadline-infeasible work earlier. Data-only: admission policy,
        # never a dispatch shape.
        self.admission_pad = 0.0
        # lazy live-tuner hook (autotuning.livetuner), consulted at step
        # cadence like the engines' goodput accountant: benches enable
        # observability after construction, and the disabled path must
        # wire nothing
        self._tuner = None
        self._tuner_obs = None
        self._pressure_streak = 0
        self._calm_streak = 0
        self._shed_count = 0
        # measured fleet TPOT (per-token seconds over finished requests)
        # and submitted token budgets — the admission estimator's inputs
        self._tpot_obs = collections.deque(maxlen=512)
        self._mnt_obs = collections.deque(maxlen=512)
        # fleet-level request ledger over ADMITTED requests:
        # submitted == finished + cancelled + shed + deadline_exceeded
        # (+ in flight). Admission-shed requests never enter it — they
        # were rejected before a handle existed (the shed METRIC counts
        # both kinds, by reason).
        self.submitted_count = 0
        self.finished_count = 0
        self.cancelled_count = 0
        self.shed_count_total = 0
        self.deadline_exceeded_count = 0
        self._starvation_limit = 2 * sum(
            r.engine.config.max_queue for r in self.replicas) + 8
        self._injector = None
        if fault_plan is not None:
            from ...observability.faultinject import FaultInjector

            obs = get_session()
            self._injector = FaultInjector(
                plan=fault_plan, rank=0, restart=0,
                registry=obs.registry if obs.enabled else None)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False
        log_dist(f"fleet router ready: {len(self.replicas)} replicas "
                 f"(policy={self.config.policy}, "
                 f"disagg={'on' if self.disagg else 'off'}, "
                 f"auto_revive={'on' if self.config.auto_revive else 'off'})")

    # -- client API --------------------------------------------------------
    @property
    def threaded(self) -> bool:
        return self._thread is not None

    @property
    def degraded_mode(self) -> int:
        return self._degraded

    def in_flight(self) -> int:
        with self._lock:
            return len(self._requests)

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_token_id: Optional[int] = None, tenant: str = "default",
               deadline_s: Optional[float] = None, seed: int = 0,
               n: int = 1):
        """Route and enqueue one prompt; returns a :class:`FleetHandle`
        (a list of ``n`` for parallel sampling, non-disaggregated fleets
        only — a fork's shared blocks cannot span a handoff). Raises
        :class:`Overloaded` (with ``retry_after_s``) when ``deadline_s``
        is infeasible at the current queue depth and measured TPOT —
        shedding at admission instead of admitting the request to die."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if n < 1:
            raise ValueError(f"submit(n={n}): need n >= 1")
        if n > 1 and self.disagg:
            raise NotImplementedError(
                "parallel sampling (n > 1) is per-replica COW sharing — "
                "not supported through a disaggregated fleet")
        with self._lock:
            pool = self.prefill_pool if self.disagg else self.replicas
            replica, reason, hint = self._pick(pool, prompt)
            if replica is None:
                raise FleetUnavailable("no alive replica to route to")
            mnt = (max_new_tokens if max_new_tokens is not None
                   else replica.engine.config.default_max_new_tokens)
            if self.config.admission_control and deadline_s is not None:
                # all n parallel samples decode their own budget on the
                # picked replica — the feasibility estimate must carry it
                est = self._estimate_completion_s(replica, mnt * n)
                if est is not None and est > deadline_s:
                    self._count_shed("deadline_infeasible")
                    obs = get_session()
                    obs.flight_event("req_terminal", event="shed",
                                     reason="deadline_infeasible",
                                     tenant=tenant)
                    rt = obs.reqtrace
                    if rt is not None:
                        # a shed submission still leaves a (retained)
                        # trace: shed is a tail-retention outlier
                        t = rt.start(tenant=tenant, t=self.clock(),
                                     attrs={"deadline_s": deadline_s})
                        rt.finish(t, "shed", t=self.clock(),
                                  reason="deadline_infeasible",
                                  estimated_s=round(est, 4))
                    raise Overloaded(
                        f"deadline {deadline_s:.3f}s is infeasible: "
                        f"estimated completion {est:.3f}s at current "
                        "queue depth and measured TPOT",
                        retry_after_s=max(est - deadline_s,
                                          self._tpot_estimate() or 0.0))
            handles = replica.engine.submit(
                prompt, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_token_id=eos_token_id, tenant=tenant,
                deadline_s=deadline_s, seed=seed, n=n)
            # the affinity admission hint (and the routing-decision count)
            # commits only for requests that were actually admitted — a
            # shed submission or an engine QueueFull must not point later
            # prefix-sharers at a replica that never served it
            self._commit_affinity_hint(hint)
            self._count_decision(reason, replica)
            if n == 1:
                handles = [handles]
            rt = get_session().reqtrace
            if rt is not None:
                # the routing decision joins each request's causal chain
                # (the trace itself was minted by engine.submit)
                for h in handles:
                    if h._req.trace is not None:
                        rt.event(h._req.trace, "routed",
                                 t=self.clock(), policy=self.config.policy,
                                 reason=reason, replica=str(replica.index))
            # every admitted request weighs into the estimator's average
            self._mnt_obs.extend([mnt] * n)
            now = self.clock()
            out = []
            for i, h in enumerate(handles):
                fr = _FleetRequest(
                    fid=self._fid, prompt=prompt.copy(), seed=seed + i,
                    kwargs=dict(
                        max_new_tokens=h._req.max_new_tokens,
                        temperature=float(temperature), top_k=int(top_k),
                        top_p=float(top_p), eos_token_id=eos_token_id,
                        tenant=tenant),
                    arrival_s=now)
                if deadline_s is not None:
                    fr.deadline_abs = now + deadline_s
                self._fid += 1
                self.submitted_count += 1
                fr.bind(replica, h)
                fr.handle = FleetHandle(self, fr)
                self._requests[fr.fid] = fr
                self._by_engine[(replica.index, h._req.rid)] = fr.fid
                out.append(fr.handle)
            return out[0] if n == 1 else out

    def cancel(self, handle: FleetHandle) -> bool:
        with self._lock:
            fr = handle._fr
            if fr.done:
                return False
            self._drain_tokens(fr)
            if fr.u_req.done:        # finished just before the cancel
                self._settle(fr)
                return False
            if fr.replica.alive:
                fr.replica.engine.cancel(fr.u_handle)
            else:
                # the engine-side finish cannot run on a dead replica —
                # the router closes the trace itself
                self._trace_finish_fr(fr, "cancelled")
            self._finish_fr(fr, F_CANCELLED)
            return True

    # -- the fleet iteration ----------------------------------------------
    def step(self) -> bool:
        """One fleet iteration: apply scheduled faults, heal (revive dead
        replicas whose backoff expired, release quarantine into
        probation), drain dead replicas (resubmitting their requests,
        retrying parked ones), run one scheduler iteration on every alive
        replica with work — measuring its wall time for the health
        verdicts — then judge health, stream out newly emitted tokens and
        update the overload ladder."""
        with self._lock:
            if self._injector is not None:
                self._injector.before_router_step(self._iterations,
                                                  self.kill_replica)
            # drain strictly before heal: a revival must never resurrect a
            # replica whose stranded requests were not yet resubmitted —
            # the drain guard keys on r.alive
            self._drain_dead()
            self._heal()
            self._retry_parked()
            progress = False
            for r in self.replicas:
                if not r.alive or not r.engine.in_flight():
                    continue
                t0 = self.clock()
                try:
                    progress |= r.step()
                except ReplicaDead:
                    pass
                except Exception:
                    # a replica whose iteration raises is as dead as a
                    # crashed process: drain + resubmit next pass
                    logger.exception(
                        f"fleet replica {r.index} iteration failed — "
                        "marking dead")
                    self.kill_replica(r.index, reason="step-exception")
                else:
                    dt = self.clock() - t0
                    if self._injector is not None:
                        dt += self._injector.slow_penalty(self._iterations,
                                                          r.index)
                    r.note_step_time(dt)
            self._judge_health()
            for fr in list(self._requests.values()):
                if fr.replica.alive:
                    self._drain_tokens(fr)
                    self._settle(fr)
            self._update_overload()
            self._publish()
            it = self._iterations
            self._iterations += 1
        # the live tuner's decision tick runs OUTSIDE the router lock: the
        # controller takes its own lock and may re-enter router APIs
        # (set_replica_role), so in-lock invocation would knot the lock
        # graph (tools/tpusync). Still after _update_overload — the tuner
        # recomposes the spec flag on top of this iteration's ladder
        # verdict.
        tuner = self._maybe_tuner()
        if tuner is not None:
            tuner.on_iteration(it)
        return progress

    def _maybe_tuner(self):
        """The live tuner, created lazily once the observability session
        carries the ``tune.controller`` gate (benches enable it after
        warmup). Disabled path: one cached-bool check per iteration —
        nothing allocated, nothing dispatched."""
        if self._tuner is None:
            from ...observability import get_session

            obs = get_session()
            if obs is not self._tuner_obs:
                # probe once per session object: configure_observability
                # always builds a new session, so identity tracks
                # enable/replace without re-probing every iteration
                with self._lock:
                    self._tuner_obs = obs
                    if obs.enabled:
                        from ...autotuning.livetuner import maybe_make_tuner

                        self._tuner = maybe_make_tuner(self, obs)
        return self._tuner

    def reset_latency_stats(self) -> None:
        """Drop the router's handoff/decision/resubmit tallies AND every
        replica's latency reservoirs — benches call this after warmup so
        the published numbers (incl. the warmup handoff, which JIT-compiles
        kv_export/kv_import inside its timed span) describe the measured
        load, not compilation. The admission-control TPOT/budget estimator
        resets too: a warmup request's per-token time spans the decode
        compile, and one compile-scale sample in a small reservoir would
        declare every real deadline infeasible (shed requests never
        finish, so nothing would ever correct the poisoned median)."""
        with self._lock:
            self._handoff_ms.clear()
            self._handoff_fallbacks = 0
            self._handoff_failures = 0
            self._decisions.clear()
            self._resubmit_count = 0
            self._shed_count = 0
            self._revive_iters.clear()
            self._tpot_obs.clear()
            self._mnt_obs.clear()
        for r in self.replicas:
            if r.alive:
                r.engine.reset_latency_stats()
                r.engine.sched.handoffs_out = 0

    # -- replica lifecycle -------------------------------------------------
    def kill_replica(self, index: int, reason: str = "fault") -> None:
        """Mark a replica dead (chaos harness / health verdicts). Its
        in-flight requests resubmit on the next ``step()``; with
        ``auto_revive`` it is rebuilt after a backed-off wait and
        re-admitted through probation."""
        if not 0 <= index < len(self.replicas):
            raise ValueError(
                f"kill_replica({index}): fleet has "
                f"{len(self.replicas)} replicas (indices 0.."
                f"{len(self.replicas) - 1})")
        with self._lock:
            r = self.replicas[index]
            if not r.alive:
                return
            r.kill(reason)
            r.death_iteration = self._iterations
            r.revive_at = self._iterations + (
                self.config.revive_after_iterations
                * 2 ** min(r.deaths - 1, 5))
            self._death_count += 1
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "fleet_serving/replica_deaths",
                    help="replicas the router declared dead").inc(
                        reason=reason)
            logger.warning(f"fleet replica {index} dead ({reason}); "
                           "draining its requests")

    def quarantine_replica(self, index: int, reason: str) -> None:
        """Health-verdict remediation short of a kill: the replica keeps
        stepping its in-flight work but receives no new traffic until an
        exponentially backed-off window expires, after which it re-enters
        via probation. A replica past the circuit-breaker incident budget
        is retired instead."""
        with self._lock:
            r = self.replicas[index]
            if not r.alive or r.quarantined:
                return
            if r.incidents + 1 > self.config.breaker_incidents:
                self._retire(r, f"breaker({reason})")
                return
            backoff = (self.config.quarantine_iterations
                       * 2 ** min(r.quarantines, 5))
            r.quarantine(reason, self._iterations + backoff)
            self._quarantine_count += 1
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "fleet_serving/quarantines",
                    help="slow/SLO-breaching replicas quarantined (alive, "
                         "no new traffic)").inc(reason=reason)
            logger.warning(
                f"fleet replica {index} quarantined ({reason}) for "
                f"{backoff} iterations (incident "
                f"{r.incidents}/{self.config.breaker_incidents})")

    def revive_replica(self, index: int) -> bool:
        """Rebuild a dead replica's engine (fleet-shared weights + a
        surviving replica's compiled program set — one arena allocation,
        zero compiles) and re-admit it ON PROBATION. Returns False when
        the replica is already alive; raises :class:`ReplicaRetired` past
        the circuit breaker."""
        with self._lock:
            r = self.replicas[index]
            if r.alive:
                return False
            if r.retired:
                raise ReplicaRetired(
                    f"replica {index} is retired (circuit breaker)")
            if not r.drained:
                # a kill between iterations (or a caller racing the step
                # loop) may not have been drained yet — resubmit its
                # stranded requests BEFORE the engine is replaced, or they
                # would stay bound to the discarded incarnation forever
                self._drain_replica(r)
            donor = next((o for o in self.replicas
                          if o.alive and o is not r), None)
            engine = r.rebuild(donor)
            self._replaced_engines.append(r.engine)
            r.revive(engine, self.config.probation_requests)
            engine.trace_tag = str(r.index)   # the incarnation keeps the
            #   replica's identity on traces and serve_goodput gauges
            engine._fleet_managed = True
            # a fresh incarnation boots untuned; the live tuner's next
            # decision tick re-pushes its owned knobs fleet-wide
            # conservative: even with grafted programs, the incarnation's
            # first measured steps are not representative
            r.warmup_left = self.config.health_warmup_steps
            engine.spec_suspended = self._degraded >= DEGRADED_NO_SPEC
            if self.disagg and r.role == ROLE_PREFILL:
                engine.on_prefill_complete = (
                    lambda req, _r=r: self._handoff_from(_r, req))
            self._revival_count += 1
            death_it = getattr(r, "death_iteration", self._iterations)
            self._revive_iters.append(self._iterations - death_it)
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "fleet_serving/revivals",
                    help="dead replicas rebuilt (shared weights + compiled "
                         "programs) and re-admitted via probation").inc()
            logger.warning(
                f"fleet replica {index} revived (probation: "
                f"{r.probation_left} clean requests to graduate)")
            return True

    def _retire(self, r: Replica, reason: str) -> None:
        """Circuit breaker tripped: permanently out of the fleet."""
        was_alive = r.alive
        r.retire()
        r.death_reason = reason
        obs = get_session()
        if obs.enabled:
            obs.registry.counter(
                "fleet_serving/replica_retirements",
                help="replicas past the circuit-breaker incident budget — "
                     "permanently removed, never revived").inc()
            if was_alive:
                obs.registry.counter(
                    "fleet_serving/replica_deaths",
                    help="replicas the router declared dead").inc(
                        reason="breaker")
        if was_alive:
            self._death_count += 1
        logger.error(
            f"fleet replica {r.index} RETIRED ({reason}): "
            f"{r.incidents} incidents > breaker budget "
            f"{self.config.breaker_incidents}")

    def _heal(self) -> None:
        """The remediation half of the loop, run at the top of every
        iteration: expired quarantines re-enter via probation; dead
        replicas past their revival backoff are rebuilt (or retired when
        the breaker budget is spent)."""
        for r in self.replicas:
            if r.retired:
                continue
            if r.quarantined and self._iterations >= r.quarantine_until:
                r.quarantined = False
                r.quarantine_reason = None
                r.probation_left = self.config.probation_requests
                # the window sampled DURING quarantine includes the very
                # evidence that convicted it — probation is judged on
                # fresh samples, or exit would instantly re-convict
                r.step_times.clear()
                logger.warning(
                    f"fleet replica {r.index} quarantine expired — on "
                    f"probation ({r.probation_left} clean requests)")
            if (not r.alive and self.config.auto_revive
                    and self._iterations >= r.revive_at):
                # revival itself is NOT an incident: retire only when the
                # budget is already exceeded (matching quarantine_replica,
                # whose +1 is the incident being added, and the manual
                # revive_replica path)
                if r.incidents > self.config.breaker_incidents:
                    self._retire(r, "breaker(revive)")
                    continue
                try:
                    self.revive_replica(r.index)
                except Exception:
                    logger.exception(
                        f"fleet replica {r.index} revival failed — "
                        "backing off")
                    r.revive_at = self._iterations + (
                        self.config.revive_after_iterations
                        * 2 ** min(r.deaths, 5))

    def _judge_health(self) -> None:
        """Step-time verdicts from the windows the iteration just fed: a
        replica whose rolling median exceeds the absolute SLO, or
        ``slow_factor ×`` the median of the OTHER candidates' medians, is
        quarantined. (TTFT-SLO breaches are judged where TTFT is stamped,
        in ``_drain_tokens``.)"""
        cands = [r for r in self.replicas
                 if r.alive and not r.quarantined]
        meds = {r.index: r.step_time_median() for r in cands}
        for r in cands:
            med = meds[r.index]
            if med is None:
                continue
            slo = self.config.step_time_slo_s
            if slo > 0 and med > slo:
                self._count_verdict("step_slo")
                self.quarantine_replica(r.index, "step_slo")
                continue
            # the relative verdict needs an absolute floor: at sub-floor
            # step times, scheduler noise makes any ratio meaningless
            if med < self.config.slow_min_step_s:
                continue
            others = [m for i, m in meds.items()
                      if i != r.index and m is not None]
            if others and med > self.config.slow_factor \
                    * statistics.median(others):
                self._count_verdict("slow")
                self.quarantine_replica(r.index, "slow")

    def _count_verdict(self, verdict: str) -> None:
        obs = get_session()
        if obs.enabled:
            obs.registry.counter(
                "fleet_serving/health_verdicts",
                help="non-healthy health verdicts by kind").inc(
                    verdict=verdict)

    # -- internals ---------------------------------------------------------
    def _trace_finish_fr(self, fr: _FleetRequest, state: str,
                         **attrs: Any) -> None:
        """Router-level terminal for a trace whose engine binding cannot
        record it (dead replica, shed-from-queue). Idempotent with the
        engine's own finish — the first terminal state wins."""
        trace = (getattr(fr.u_req, "trace", None)
                 if fr.u_req is not None else None)
        if trace is None:
            return
        rt = get_session().reqtrace
        if rt is not None:
            rt.finish(trace, state, t=self.clock(),
                      ttft_s=(fr.first_token_s - fr.arrival_s
                              if fr.first_token_s is not None else None),
                      **attrs)

    def _count_decision(self, reason: str, replica: Replica) -> None:
        self._decisions[(self.config.policy, reason)] += 1
        obs = get_session()
        if obs.enabled:
            obs.registry.counter(
                "fleet_serving/routing_decisions",
                help="requests routed, by policy decision reason").inc(
                    policy=self.config.policy, reason=reason,
                    replica=str(replica.index))

    def _count_shed(self, reason: str) -> None:
        self._shed_count += 1
        obs = get_session()
        if obs.enabled:
            obs.registry.counter(
                "fleet_serving/shed",
                help="requests shed under overload (admission "
                     "deadline-infeasibility or the degraded ladder)").inc(
                    reason=reason)

    def _tpot_estimate(self) -> Optional[float]:
        """Measured fleet per-token seconds (median over recent finished
        requests) — None until the first finished request with >= 2
        tokens reports one."""
        if not self._tpot_obs:
            return None
        return statistics.median(self._tpot_obs)

    def _estimate_completion_s(self, replica: Replica,
                               max_new_tokens: int) -> Optional[float]:
        """The admission-control feasibility model, deliberately simple
        and documented: completion ≈ TPOT × (own token budget + the
        target replica's queued backlog × mean submitted budget). None
        (no TPOT data yet) admits — the estimator only ever sheds on
        MEASURED evidence."""
        tpot = self._tpot_estimate()
        if tpot is None:
            return None
        h = replica.health()
        avg_mnt = (statistics.fmean(self._mnt_obs)
                   if self._mnt_obs else float(max_new_tokens))
        return ((1.0 + self.admission_pad)
                * tpot * (max_new_tokens + h.queue_depth * avg_mnt))

    def set_replica_role(self, index: int, role: str) -> None:
        """Reassign a replica's pool membership at runtime — the live
        tuner's prefill:decode ratio knob. Data-plane only: roles gate
        which pool ``_pick`` routes NEW work to; in-flight requests finish
        where they sit. Pure-prefill handoff wiring is fixed at
        construction, so runtime moves are restricted to the
        DECODE <-> MIXED edge (a mixed replica decodes its own prefills in
        place — no handoff seam to rewire), and the fleet must keep at
        least one prefill-capable and one decode-capable replica."""
        allowed = (ROLE_DECODE, ROLE_MIXED)
        with self._lock:
            r = self.replicas[index]
            if role == r.role:
                return
            if r.role not in allowed or role not in allowed:
                raise ValueError(
                    f"set_replica_role({index}, {role!r}): runtime role "
                    "moves are decode<->mixed only (prefill handoff "
                    "wiring is fixed at construction)")
            prev = r.role
            r.role = role
            pp = [x for x in self.replicas
                  if x.role in (ROLE_PREFILL, ROLE_MIXED)]
            dp = [x for x in self.replicas
                  if x.role in (ROLE_DECODE, ROLE_MIXED)]
            if not pp or not dp:
                r.role = prev
                raise ValueError(
                    f"set_replica_role({index}, {role!r}) would leave the "
                    "fleet without a prefill- or decode-capable replica")
            self.prefill_pool, self.decode_pool = pp, dp
            log_dist(f"fleet replica {index} role: {prev} -> {role}")

    def _affinity_key(self, prompt: np.ndarray) -> Optional[bytes]:
        if int(prompt.size) < self._block_size:
            return None
        import hashlib

        return hashlib.blake2b(
            np.ascontiguousarray(prompt[:self._block_size],
                                 np.int32).tobytes(),
            digest_size=16).digest()

    def _routable(self, r: Replica) -> bool:
        """May this replica receive NEW traffic right now? Quarantine
        blocks it outright; probation caps its share of the fleet's
        in-flight requests at ``probation_share`` (floor of one — a
        probation replica must be able to prove itself)."""
        if not r.routable():
            return False
        if r.probation_left > 0:
            cap = max(1, int(self.config.probation_share
                             * max(len(self._requests), 1)))
            if r.engine.in_flight() >= cap:
                return False
        return True

    def _pick(self, pool: List[Replica], prompt: np.ndarray):
        """(replica, decision reason, deferred affinity hint) under the
        configured policy. The eligibility ladder degrades gracefully:
        routable members of the pool, then routable members of the whole
        fleet, then ANY alive replica (quarantined/probation-capped
        included — live beats pure). The affinity hint is RETURNED, not
        written — the caller commits it only once the request is actually
        admitted (an admission-shed submission must not point later
        prefix-sharers at a replica that never served it)."""
        alive = [r for r in pool if self._routable(r)]
        degraded = not alive
        if degraded:
            alive = ([r for r in self.replicas if self._routable(r)]
                     or [r for r in self.replicas if r.alive])
        if not alive:
            return None, "no_replica", None
        policy = self.config.policy
        health = {r.index: r.health() for r in alive}
        reason = policy
        hint = None
        if policy == "round_robin":
            pick = alive[self._rr % len(alive)]
            self._rr += 1
        elif policy == "least_queue":
            pick = min(alive, key=lambda r: (health[r.index].in_flight,
                                             r.index))
        elif policy == "kv_occupancy":
            pick = min(alive, key=lambda r: health[r.index].load_key)
        else:   # affinity
            key = self._affinity_key(prompt)
            pick = None
            if self._degraded >= DEGRADED_NO_AFFINITY:
                # ladder rung 2: stop following warm hints — spilling to
                # the least-loaded replica beats locality under pressure
                reason = "degraded_spill"
            elif key is None:
                reason = "affinity_short"
            else:
                warm = self._affinity.get(key)
                if warm is None:
                    reason = "affinity_cold"
                else:
                    cand = self.replicas[warm]
                    if cand not in alive:
                        reason = "affinity_dead"
                    elif (health[cand.index].arena_occupancy
                          > self.config.affinity_overload):
                        reason = "affinity_overload"
                    else:
                        pick, reason = cand, "affinity_warm"
            if pick is None:
                pick = min(alive, key=lambda r: health[r.index].load_key)
            if key is not None and self._degraded < DEGRADED_NO_AFFINITY:
                hint = (key, pick.index)
        if degraded:
            reason += "_degraded"
        return pick, reason, hint

    def _commit_affinity_hint(self, hint) -> None:
        """The admission hint: later requests with this prefix follow the
        replica whose cache is (about to be) warm."""
        if hint is None:
            return
        key, index = hint
        self._affinity[key] = index
        self._affinity.move_to_end(key)
        while len(self._affinity) > 4096:
            self._affinity.popitem(last=False)

    def _drain_tokens(self, fr: _FleetRequest) -> None:
        """Move newly emitted tokens from the bound engine handle into the
        fleet handle (and stamp the fleet-level TTFT, judging the TTFT SLO
        against the serving replica)."""
        toks = fr.u_handle.tokens
        new = toks[fr.consumed:]
        if not new:
            return
        if fr.first_token_s is None:
            fr.first_token_s = self.clock()
            ttft = fr.first_token_s - fr.arrival_s
            obs = get_session()
            if obs.enabled:
                obs.registry.histogram(
                    "fleet_serving/ttft_ms",
                    help="fleet submit → first streamed token, "
                         "wall ms").observe(ttft * 1e3)
            slo = self.config.ttft_slo_s
            if slo > 0 and ttft > slo and fr.resubmits == 0 \
                    and fr.handoffs == 0:
                # a resubmitted request's TTFT indicts the DEAD replica,
                # not the survivor that picked up the recompute — and a
                # handed-off one's indicts the prefill side, never the
                # decode replica it is now bound to
                self._ttft_breaches += 1
                if obs.enabled:
                    obs.registry.counter(
                        "fleet_serving/health_ttft_breaches",
                        help="first tokens that missed the fleet TTFT "
                             "SLO").inc()
                self._count_verdict("ttft_slo")
                if self._degraded == DEGRADED_NONE:
                    # under declared overload a late first token indicts
                    # the FLEET, not the serving replica — quarantining
                    # (and ratcheting its breaker) would retire healthy
                    # capacity exactly when it is scarcest
                    self.quarantine_replica(fr.replica.index, "ttft_slo")
        for t in new:
            fr.handle._push(t)
        fr.consumed = len(toks)

    def _settle(self, fr: _FleetRequest) -> None:
        """Terminal-state propagation for the CURRENT binding."""
        if fr.done or not fr.u_req.done:
            return
        if fr.u_req.state == FINISHED:
            state = F_FINISHED
        elif fr.u_req.state == DEADLINE_EXCEEDED:
            state = F_DEADLINE
        else:
            state = F_CANCELLED
        self._finish_fr(fr, state)

    def _finish_fr(self, fr: _FleetRequest, state: str) -> None:
        fr.state = state
        fr.finish_s = self.clock()
        self._requests.pop(fr.fid, None)
        if fr.replica is not None and fr.u_req is not None:
            self._by_engine.pop((fr.replica.index, fr.u_req.rid), None)
        if state == F_FINISHED:
            self.finished_count += 1
            tpot = fr.handle.tpot_s if fr.handle is not None else None
            if tpot is not None:
                self._tpot_obs.append(tpot)
            self._credit_probation(fr.replica)
        elif state == F_CANCELLED:
            self.cancelled_count += 1
        elif state == F_DEADLINE:
            self.deadline_exceeded_count += 1
        elif state == F_SHED:
            self.shed_count_total += 1
        fr.handle._wake()

    def _credit_probation(self, r: Optional[Replica]) -> None:
        """Clean service earns probation credit; graduation restores full
        routing weight. Called for a request FINISHING on the replica —
        and for a completed prefill + successful handoff (in a
        disaggregated fleet every request rebinds to a decode replica, so
        a probation PREFILL replica's service would otherwise never
        count and it could never graduate)."""
        if r is None or not r.alive or r.probation_left <= 0:
            return
        r.probation_left -= 1
        if r.probation_left == 0:
            self._graduation_count += 1
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "fleet_serving/probation_graduations",
                    help="replicas that served their probation cleanly "
                         "and regained full routing weight").inc()
            logger.warning(f"fleet replica {r.index} graduated "
                           "probation — full routing weight")

    def _drain_dead(self) -> None:
        """Resubmit every request stranded on a dead replica: recompute
        from original prompt + streamed tokens on a surviving replica —
        the same bit-exactness contract as per-engine preemption. The
        resubmission budget is spent HERE (one unit per death), not on
        QueueFull retries."""
        for r in self.replicas:
            if r.alive or r.drained:
                continue
            self._drain_replica(r)

    def _drain_replica(self, r: Replica) -> None:
        r.drained = True
        # parked requests are still bound to the replica they were
        # ORIGINALLY drained from; a later death of that (revived) replica
        # must not budget them a second time or race _retry_parked into a
        # duplicate resubmission
        victims = [fr for fr in self._requests.values()
                   if fr.replica is r and not fr.done
                   and fr.fid not in self._parked]
        for fr in victims:
            fr.resubmits += 1
            if fr.resubmits > self.config.max_resubmits:
                logger.error(
                    f"fleet request {fr.fid}: resubmission budget "
                    f"({self.config.max_resubmits}) exhausted — "
                    "cancelling")
                self._trace_finish_fr(fr, "cancelled",
                                      reason="resubmit_budget")
                self._finish_fr(fr, F_CANCELLED)
                continue
            self._try_resubmit(fr)

    def _retry_parked(self) -> None:
        """Re-attempt resubmissions that found every survivor momentarily
        full — queue pressure drains as survivors step, so later
        iterations succeed without touching the death budget."""
        if not self._parked:
            return
        parked, self._parked = self._parked, []
        now = self.clock()
        for fid in parked:
            fr = self._requests.get(fid)
            if fr is None or fr.done:
                continue
            if fr.deadline_abs is not None and now > fr.deadline_abs:
                # nobody engine-side can expire a parked request (its
                # binding is the dead replica) — the router must
                self._trace_finish_fr(fr, "deadline_exceeded",
                                      reason="parked_past_deadline")
                self._finish_fr(fr, F_DEADLINE)
                obs = get_session()
                obs.flight_event("req_terminal", event="deadline_exceeded",
                                 fid=fr.fid, reason="parked_past_deadline")
                if obs.enabled:
                    obs.registry.counter(
                        "serving/requests_deadline_exceeded",
                        help="requests terminated at an iteration "
                             "boundary after their deadline passed").inc(
                                 tenant=fr.kwargs.get("tenant", "default"))
                continue
            self._try_resubmit(fr)

    def _try_resubmit(self, fr: _FleetRequest) -> None:
        """Bind ``fr`` to a surviving replica in recompute mode; parks it
        for later iterations when every candidate is QueueFull (a full
        queue is congestion, not a death — it must not burn the
        ``max_resubmits`` budget). Cancels only when NO replica is alive."""
        obs = get_session()
        tokens = fr.handle.tokens      # everything streamed IS recoverable
        # phase-matched pool preference: a request already decoding goes
        # back to the decode pool, one still prefilling to the prefill pool
        pool = ((self.decode_pool if tokens else self.prefill_pool)
                if self.disagg else self.replicas)
        deadline_s = (max(fr.deadline_abs - self.clock(), 0.0)
                      if fr.deadline_abs is not None else None)
        cands = ([r for r in pool if self._routable(r)]
                 or [r for r in self.replicas if r.alive])
        if not cands:
            logger.error(f"fleet request {fr.fid}: no alive replica for "
                         "the resubmission — cancelling")
            self._trace_finish_fr(fr, "cancelled", reason="fleet_dead")
            self._finish_fr(fr, F_CANCELLED)
            return
        # the trace survives the dead binding: the SAME trace_id continues
        # on the survivor at attempt + 1 (the resubmission causal link)
        trace = (getattr(fr.u_req, "trace", None)
                 if fr.u_req is not None else None)
        for target in sorted(cands, key=lambda r: r.health().load_key):
            try:
                h2 = target.engine.submit_recovered(
                    fr.prompt, tokens, seed=fr.seed,
                    deadline_s=deadline_s, **fr.kwargs)
            except QueueFull:
                continue
            self._by_engine.pop((fr.replica.index, fr.u_req.rid), None)
            dead_index = fr.replica.index
            fr.bind(target, h2)
            if trace is not None:
                h2._req.trace = trace
                rt = obs.reqtrace
                if rt is not None:
                    rt.resubmitted(trace, self.clock(),
                                   replica=target.index)
            obs.flight_event("req_terminal", event="resubmit", fid=fr.fid,
                             from_replica=dead_index,
                             to_replica=target.index,
                             trace_id=(trace.trace_id
                                       if trace is not None else None))
            if fr.fid in self._parked:
                self._parked.remove(fr.fid)
            # streamed tokens live engine-side in req.generated but were
            # never pushed to the NEW handle — nothing to re-drain
            self._by_engine[(target.index, h2._req.rid)] = fr.fid
            self._resubmit_count += 1
            self._count_decision("resubmit", target)
            if obs.enabled:
                obs.registry.counter(
                    "fleet_serving/resubmits",
                    help="requests resubmitted after a replica "
                         "death").inc()
            return
        # every survivor momentarily full: park and retry next iteration
        if fr.fid not in self._parked:
            self._parked.append(fr.fid)
            logger.warning(
                f"fleet request {fr.fid}: every surviving replica is "
                "full — parking the resubmission for later iterations")

    # -- overload control: the degraded-mode ladder ------------------------
    def _update_overload(self) -> None:
        """Walk the degraded ladder: ``overload_up_iterations`` of
        sustained pressure (mean alive arena occupancy / fleet queue
        depth) per rung up, ``overload_down_iterations`` of calm per rung
        down — hysteresis keeps the fleet from oscillating. Rung 3 sheds
        one queued victim per iteration while it holds."""
        alive = [r for r in self.replicas if r.alive]
        if not alive:
            return
        # pressure counts only IRRECLAIMABLE occupancy: unpinned
        # prefix-cache blocks evict on demand, and a warm cache
        # deliberately fills the pool — counting it would park a
        # long-running idle fleet at rung 3 forever
        def _occ(r):
            alloc, cache = r.engine.alloc, r.engine.prefix
            reclaimable = cache.reclaimable_blocks if cache else 0
            return ((alloc.blocks_in_use - reclaimable)
                    / max(alloc.capacity, 1))

        occ = statistics.fmean(_occ(r) for r in alive)
        qd = sum(r.engine.sched.queue_depth() for r in alive)
        pressure = occ >= self.config.overload_occupancy or (
            self.config.overload_queue_depth > 0
            and qd >= self.config.overload_queue_depth)
        if pressure:
            self._pressure_streak += 1
            self._calm_streak = 0
            if (self._pressure_streak
                    >= self.config.overload_up_iterations
                    and self._degraded < DEGRADED_SHED):
                self._set_degraded(self._degraded + 1)
                self._pressure_streak = 0
        else:
            self._calm_streak += 1
            self._pressure_streak = 0
            if (self._calm_streak >= self.config.overload_down_iterations
                    and self._degraded > DEGRADED_NONE):
                self._set_degraded(self._degraded - 1)
                self._calm_streak = 0
        if self._degraded >= DEGRADED_SHED:
            self._shed_one()

    def _set_degraded(self, rung: int) -> None:
        direction = "up" if rung > self._degraded else "down"
        self._degraded = rung
        for r in self.replicas:
            if r.alive:
                # rung 1: speculation must never cost anyone blocks under
                # pressure — suspend it fleet-wide (bit-exact: the verify
                # path with zero proposals IS the plain decode)
                r.engine.spec_suspended = rung >= DEGRADED_NO_SPEC
        obs = get_session()
        if obs.enabled:
            obs.registry.counter(
                "fleet_serving/degraded_transitions",
                help="degraded-mode ladder transitions").inc(
                    direction=direction, rung=str(rung))
        logger.warning(f"fleet degraded-mode ladder: rung {rung} "
                       f"({direction})")

    def _shed_one(self) -> None:
        """Rung 3: shed the lowest-priority queued (unadmitted) request —
        no-deadline work first, then latest deadline — so the work least
        likely to matter soonest pays for the overload."""
        cands = [fr for fr in self._requests.values()
                 if not fr.done and fr.u_req is not None
                 and fr.u_req.state == QUEUED]
        if not cands:
            return
        victim = min(cands, key=lambda fr: (
            fr.deadline_abs is not None,
            -(fr.deadline_abs or 0.0), -fr.fid))
        self._drain_tokens(victim)
        # the shed terminal must land BEFORE the engine cancel (the first
        # terminal state wins — this one is the truthful one)
        self._trace_finish_fr(victim, "shed", reason="degraded")
        get_session().flight_event(
            "req_terminal", event="shed", reason="degraded",
            fid=victim.fid, rung=self._degraded)
        if victim.replica.alive:
            victim.replica.engine.cancel(victim.u_handle)
        tpot = self._tpot_estimate() or 0.0
        victim.retry_after_s = max(
            tpot * (statistics.fmean(self._mnt_obs)
                    if self._mnt_obs else 1.0), 0.001)
        self._count_shed("degraded")
        self._finish_fr(victim, F_SHED)
        logger.warning(f"fleet request {victim.fid} shed (degraded rung "
                       f"{self._degraded}, retry_after_s="
                       f"{victim.retry_after_s:.3f})")

    # -- disaggregation: the prefill-complete hook -------------------------
    def _handoff_from(self, src: Replica, req) -> None:
        """Called by a prefill replica (engine lock held, inside this
        router's ``step``) the moment a request's last prefill chunk
        completed: move its KV blocks to a decode replica and rebind the
        fleet request there. A transfer that FAILS mid-flight (chaos
        ``handoff_fail``, kv_import raising) retries on up to
        ``handoff_retries`` other decode replicas; failure — like a dry
        decode pool — falls back to decoding in place. Destination blocks
        of a failed transfer are freed inside the transport; source
        blocks are released exactly once, on success only."""
        # Re-enter the router lock explicitly (RLock: free on the normal
        # path, where step() already holds it). The handoff mutates router
        # state — bind(), handoff tallies, probation credit — and must not
        # rely on every engine step being driven from under step()'s lock.
        with self._lock:
            fid = self._by_engine.get((src.index, req.rid))
            fr = self._requests.get(fid) if fid is not None else None
            if fr is None or fr.done:
                return
            cands = sorted(
                (r for r in self.decode_pool
                 if self._routable(r) and r.engine is not src.engine),
                key=lambda r: r.health().load_key)
            t0 = self.clock()
            obs = get_session()
            # arm the injected transfer failure ONCE for this handoff
            # event; the finally disarms an armament the seam never
            # reached (every candidate pool dry), or it would leak into a
            # later, unplanned handoff and break the deterministic-plan
            # contract
            injected = (self._injector is not None
                        and self._injector.take_handoff_fail(
                            self._iterations))
            if injected:
                self.handoff.inject_fail_next += 1
            try:
                self._handoff_attempts(src, req, fr, cands, t0, obs)
            finally:
                if injected and self.handoff.inject_fail_next > 0:
                    self.handoff.inject_fail_next -= 1

    def _handoff_attempts(self, src: Replica, req, fr: _FleetRequest,
                          cands: List[Replica], t0: float, obs) -> None:
        failures = 0
        rt = obs.reqtrace
        for dst in cands:
            try:
                dst_ids = self.handoff.transfer(src.engine, dst.engine,
                                                req.blocks, trace=req.trace)
            except Exception:
                # mid-flight transfer loss: the transport already freed
                # the destination blocks; the source request is untouched
                # and can retry or decode in place
                failures += 1
                self._handoff_failures += 1
                if obs.enabled:
                    obs.registry.counter(
                        "fleet_serving/handoff_failures",
                        help="KV handoff transfers that failed mid-flight "
                             "(retried once, then decoded in place)").inc()
                obs.flight_event(
                    "req_terminal", event="handoff_fail", fid=fr.fid,
                    src=src.index, dst=dst.index,
                    trace_id=(req.trace.trace_id
                              if req.trace is not None else None))
                if rt is not None and req.trace is not None:
                    rt.event(req.trace, "handoff_fail", t=self.clock(),
                             src=str(src.index), dst=str(dst.index))
                logger.warning(
                    f"fleet request {fr.fid}: KV handoff to replica "
                    f"{dst.index} failed mid-transfer "
                    f"(attempt {failures})", exc_info=True)
                if failures > self.config.handoff_retries:
                    break
                continue
            if dst_ids is None:
                continue            # decode pool dry on this replica
            # the remaining deadline crosses the handoff (like _resubmit's)
            # or the adopted request would sort last in the decode pool's
            # EDF queue behind every deadline-bearing arrival
            deadline_s = (max(fr.deadline_abs - self.clock(), 0.0)
                          if fr.deadline_abs is not None else None)
            try:
                h2 = dst.engine.adopt_prefilled(
                    prompt=req.prompt[:req.n_prompt],
                    n_prompt=req.n_prompt, generated=req.generated,
                    pending_token=req.pending_token, length=req.length,
                    blocks=dst_ids, seed=req.seed, sampling=req.sampling,
                    max_new_tokens=req.max_new_tokens,
                    eos_token_id=req.eos_token_id, tenant=req.tenant,
                    deadline_s=deadline_s)
            except QueueFull:
                dst.engine.alloc.free(dst_ids)
                continue
            # tokens emitted on the source (the prefill-completion first
            # token) must reach the fleet handle BEFORE the rebinding
            self._drain_tokens(fr)
            self._by_engine.pop((src.index, req.rid), None)
            fr.bind(dst, h2)
            fr.handoffs += 1
            self._by_engine[(dst.index, h2._req.rid)] = fr.fid
            if req.trace is not None:
                # the trace context rides the handoff seam: the SAME
                # trace_id continues on the destination replica
                h2._req.trace = req.trace
                if rt is not None:
                    rt.handoff_adopted(req.trace, self.clock(),
                                       src=src.index, dst=dst.index)
            src.engine.release_for_handoff(req)
            # a completed prefill handed off cleanly IS the prefill
            # replica's unit of service — its probation credit cannot
            # come from completions (those land on the decode pool)
            self._credit_probation(src)
            ms = (self.clock() - t0) * 1e3
            self._handoff_ms.append(ms)
            if src.engine._serve_acct is not None:
                # the transfer ran inside the SOURCE replica's iteration
                # (the on_prefill_complete hook) — bucket it as handoff
                # there so its scheduling_host remainder stays honest
                src.engine._serve_acct.note_phase("handoff", ms / 1e3)
            self._count_decision("disagg_decode", dst)
            if obs.enabled:
                obs.registry.counter(
                    "fleet_serving/handoffs",
                    help="prefill→decode KV block handoffs").inc()
                obs.registry.histogram(
                    "fleet_serving/handoff_ms",
                    help="KV export+import+adopt wall ms").observe(ms)
            return
        # nobody could take it: the request decodes on the prefill replica
        self._handoff_fallbacks += 1
        if obs.enabled:
            obs.registry.counter(
                "fleet_serving/handoff_fallbacks",
                help="handoffs the decode pool refused (request decodes "
                     "on its prefill replica)").inc()

    # -- telemetry ---------------------------------------------------------
    def _publish(self) -> None:
        obs = get_session()
        if not obs.enabled:
            return
        reg = obs.registry
        alive = 0
        for r in self.replicas:
            h = r.health()
            alive += int(h.alive)
            lbl = {"replica": str(r.index), "role": r.role}
            reg.gauge("fleet_serving/queue_depth",
                      help="per-replica admission queue depth").set(
                          h.queue_depth, **lbl)
            reg.gauge("fleet_serving/in_flight",
                      help="per-replica in-flight requests").set(
                          h.in_flight, **lbl)
            reg.gauge("fleet_serving/arena_occupancy",
                      help="per-replica allocated arena fraction").set(
                          round(h.arena_occupancy, 4), **lbl)
            reg.gauge("fleet_serving/decode_batch_occupancy",
                      help="per-replica decoding rows / max_seqs").set(
                          round(h.decode_batch_occupancy, 4), **lbl)
            reg.gauge("fleet_serving/kv_blocks_in_use",
                      help="per-replica allocated arena blocks").set(
                          h.kv_blocks_in_use, **lbl)
            # 0=dead, 1=serving, 2=quarantined, 3=probation, 4=retired
            state = (4 if r.retired else 0 if not r.alive
                     else 2 if r.quarantined
                     else 3 if r.probation_left > 0 else 1)
            reg.gauge("fleet_serving/health_state",
                      help="replica lifecycle state: 0=dead 1=serving "
                           "2=quarantined 3=probation 4=retired").set(
                          state, **lbl)
            if h.step_time_median_s is not None:
                reg.gauge("fleet_serving/health_step_time_ms",
                          help="per-replica rolling median iteration wall "
                               "ms (the slow-verdict input)").set(
                              round(h.step_time_median_s * 1e3, 3), **lbl)
        reg.gauge("fleet_serving/replicas_alive",
                  help="replicas the router considers serving").set(alive)
        reg.gauge("fleet_serving/requests_in_flight",
                  help="fleet requests not yet terminal").set(
                      len(self._requests))
        reg.gauge("fleet_serving/degraded_mode",
                  help="overload ladder rung: 0=normal 1=no-speculation "
                       "2=no-affinity 3=shedding").set(self._degraded)
        # fleet-wide serving goodput: emitted tokens per device-second
        # (each replica's accounted wall is one device-second stream)
        accts = [r.engine._serve_acct for r in self.replicas
                 if r.alive and r.engine._serve_acct is not None]
        if accts:
            tots = [a.totals() for a in accts]
            wall = sum(t["wall_s"] for t in tots)
            if wall > 0:
                reg.gauge(
                    "serve_goodput/fleet_tokens_per_device_sec",
                    help="fleet emitted tokens / summed per-replica "
                         "accounted wall seconds").set(
                        sum(t["tokens"] for t in tots) / wall)

    def publish_latency_gauges(self) -> None:
        """Close-time percentile gauges over the handoff reservoir — the
        ``report`` CLI's ``== fleet serving ==`` latency inputs."""
        obs = get_session()
        if not obs.enabled or not self._handoff_ms:
            return
        from ..api import _percentile

        xs = list(self._handoff_ms)
        obs.registry.gauge("fleet_serving/handoff_p50_ms").set(
            _percentile(xs, 0.50))
        obs.registry.gauge("fleet_serving/handoff_p99_ms").set(
            _percentile(xs, 0.99))

    # -- drivers -----------------------------------------------------------
    def run(self, max_steps: Optional[int] = None) -> int:
        """Step until every fleet request is terminal (tests/benches)."""
        steps = 0
        starved = 0
        while self.in_flight():
            progress = self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            if progress:
                starved = 0
            else:
                starved += 1
                if starved > self._starvation_limit:
                    raise RuntimeError(
                        "fleet stalled: no replica can make progress "
                        f"({self.in_flight()} fleet requests in flight)")
        return steps

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._drive,
                                        name="dstpu-fleet", daemon=True)
        self._thread.start()

    def _drive(self) -> None:
        while not self._stop.is_set():
            try:
                if self.in_flight():
                    self.step()
                else:
                    self._stop.wait(0.002)
            except Exception:
                logger.exception("fleet driver step failed")
                get_session().crash_dump("fleet-step-exception")
                self._stop.wait(0.05)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.stop()
        if self._tuner is not None:
            self._tuner.finalize()     # recommendations artifact
        self.publish_latency_gauges()
        # pool the replicas' latency reservoirs BEFORE their close()
        # publishes: each ServingEngine.close() sets the same unlabeled
        # serving/ttft_p50_ms / tpot / tokens_per_sec gauges, so the last
        # replica closed would otherwise stand in for the whole fleet
        ttft, tpot, tokens_out, wall = [], [], 0, 0.0
        engines = ([r.engine for r in self.replicas]
                   + self._replaced_engines)   # revivals must not drop
        #   the dead incarnations' served-request telemetry
        for eng in engines:
            ttft.extend(eng._ttft_samples)
            tpot.extend(eng._tpot_samples)
            tokens_out += eng._tokens_out
            wall = max(wall, eng.clock() - eng._started_s)
            try:
                eng.close()
            except Exception:
                logger.warning("fleet replica engine close failed",
                               exc_info=True)
        obs = get_session()
        if obs.enabled:
            from ..api import _percentile

            reg = obs.registry
            for name, samples in (("ttft", ttft), ("tpot", tpot)):
                if samples:
                    reg.gauge(f"serving/{name}_p50_ms").set(
                        _percentile(samples, 0.50))
                    reg.gauge(f"serving/{name}_p99_ms").set(
                        _percentile(samples, 0.99))
            if tokens_out:
                reg.gauge("serving/tokens_per_sec",
                          help="generated tokens / wall seconds").set(
                              tokens_out / max(wall, 1e-9))
