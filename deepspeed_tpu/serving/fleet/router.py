"""FleetRouter — the data-plane front end over N serving replicas.

Exposes the same ``submit()/stream()/result()/cancel()`` surface as one
``ServingEngine`` and dispatches to a fleet of them:

* **Routing** — pluggable policies over the per-replica
  :class:`~.replica.ReplicaHealth` snapshot the router polls between
  scheduler iterations: ``round_robin``, ``least_queue`` (fewest in-flight
  requests), ``kv_occupancy`` (lowest arena occupancy) and ``affinity``
  (prefix-cache locality: the router remembers which replica served each
  first-prompt-block hash, so requests sharing a system prompt follow the
  warm prefix cache instead of re-prefilling it N times — the
  cross-replica prefix-cache admission hint). Every decision is counted by
  reason in ``fleet_serving/routing_decisions``.
* **Disaggregation** — replicas carry roles (``prefill`` / ``decode``):
  a request prefills on the prefill pool, then its KV blocks move to a
  decode replica through the :class:`~.disagg.KVHandoff` seam and decoding
  continues there, bit-identically (the sampling stream depends only on
  (engine seed, request seed, token index), never on which engine runs
  it). A handoff the decode pool cannot take falls back to decoding in
  place — degraded but live.
* **Resilience** — a dead replica (chaos ``replica_kill`` fault, or an
  exception out of its scheduler iteration) is drained: every in-flight
  request resubmits to a surviving replica in recompute mode
  (``ServingEngine.submit_recovered``), which re-prefills prompt +
  streamed-tokens and continues the stream bit-exactly — the per-engine
  preemption guarantee promoted to the fleet.

The router DRIVES its replicas (one scheduler iteration per replica per
``step()``); replica engines must not run their own driver threads.
``start()`` provides the fleet's background thread.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from ...config.config import FleetConfig
from ...observability import get_session
from ...utils.logging import log_dist, logger
from ..scheduler import FINISHED, QueueFull
from .disagg import ArenaHandoff, KVHandoff, register_handoff_audit_entries
from .replica import (ROLE_DECODE, ROLE_MIXED, ROLE_PREFILL, Replica,
                      ReplicaDead)

__all__ = ["FleetRouter", "FleetHandle", "FleetUnavailable"]

RUNNING = "running"
F_FINISHED = "finished"
F_CANCELLED = "cancelled"


class FleetUnavailable(RuntimeError):
    """No alive replica can take the request."""


class _FleetRequest:
    """Router-side record of one client request: the original submission
    (the resubmit source of truth) plus the CURRENT engine binding."""

    def __init__(self, fid: int, prompt: np.ndarray, seed: int,
                 kwargs: Dict[str, Any], arrival_s: float):
        self.fid = fid
        self.prompt = prompt
        self.seed = seed
        self.kwargs = kwargs          # max_new_tokens/sampling/eos/tenant
        self.deadline_abs: Optional[float] = None
        self.state = RUNNING
        self.replica: Optional[Replica] = None
        self.u_req = None             # bound engine-side Request
        self.u_handle = None          # ... and its RequestHandle
        self.consumed = 0             # tokens drained off u_handle so far
        self.resubmits = 0
        self.handoffs = 0
        self.arrival_s = arrival_s
        self.first_token_s: Optional[float] = None
        self.finish_s: Optional[float] = None
        self.handle: Optional["FleetHandle"] = None

    def bind(self, replica: Replica, u_handle) -> None:
        self.replica = replica
        self.u_handle = u_handle
        self.u_req = u_handle._req
        self.consumed = 0

    @property
    def done(self) -> bool:
        return self.state in (F_FINISHED, F_CANCELLED)


class FleetHandle:
    """Client view of one fleet request: the same incremental streaming
    surface as ``RequestHandle``, stable across KV handoffs and replica
    deaths (the router rebinds the engine side underneath it)."""

    def __init__(self, router: "FleetRouter", fr: _FleetRequest):
        self._router = router
        self._fr = fr
        self._cond = threading.Condition()
        self._tokens: List[int] = []

    # -- router-side -------------------------------------------------------
    def _push(self, token: int) -> None:
        with self._cond:
            self._tokens.append(int(token))
            self._cond.notify_all()

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    # -- client-side -------------------------------------------------------
    @property
    def request_id(self) -> int:
        return self._fr.fid

    @property
    def state(self) -> str:
        return self._fr.state

    @property
    def done(self) -> bool:
        return self._fr.done

    @property
    def tokens(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        if self._fr.first_token_s is None:
            return None
        return self._fr.first_token_s - self._fr.arrival_s

    @property
    def tpot_s(self) -> Optional[float]:
        fr = self._fr
        if (fr.finish_s is None or fr.first_token_s is None
                or len(self._tokens) < 2):
            return None
        return (fr.finish_s - fr.first_token_s) / (len(self._tokens) - 1)

    @property
    def resubmits(self) -> int:
        return self._fr.resubmits

    @property
    def handoffs(self) -> int:
        return self._fr.handoffs

    def cancel(self) -> bool:
        return self._router.cancel(self)

    def stream(self, timeout_s: Optional[float] = None) -> Iterator[int]:
        """Yield tokens as generated; in step-driven mode this drives the
        ROUTER (one fleet iteration per starved pass)."""
        from ..session import drive_stream

        rt = self._router
        yield from drive_stream(
            self._cond, self._tokens, lambda: self._fr.done, rt.clock,
            lambda: rt.threaded, rt.step, lambda: rt._starvation_limit,
            f"fleet request {self._fr.fid}",
            "fleet stalled — no replica can make progress", timeout_s)

    def result(self, timeout_s: Optional[float] = None) -> np.ndarray:
        for _ in self.stream(timeout_s=timeout_s):
            pass
        if self._fr.state == F_CANCELLED:
            from ..session import RequestCancelled

            raise RequestCancelled(
                f"fleet request {self._fr.fid} was cancelled")
        return np.asarray(self.tokens, np.int32)


class FleetRouter:
    """Data-plane router over N serving replicas (see module docstring)."""

    def __init__(self, replicas: List[Replica],
                 config: Optional[FleetConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 fault_plan: Any = None,
                 handoff: Optional[KVHandoff] = None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(replicas)
        self.config = config or FleetConfig()
        self.config.validate()
        self.clock = clock
        geoms = {(r.engine.config.block_size, r.engine.config.max_model_len)
                 for r in self.replicas}
        if len(geoms) > 1:
            raise ValueError(
                f"fleet replicas disagree on block geometry {sorted(geoms)}"
                " — affinity keys and KV handoffs need one (block_size, "
                "max_model_len)")
        self._block_size = self.replicas[0].engine.config.block_size
        roles = {r.role for r in self.replicas}
        self.disagg = roles != {ROLE_MIXED}
        self.prefill_pool = [r for r in self.replicas
                             if r.role in (ROLE_PREFILL, ROLE_MIXED)]
        self.decode_pool = [r for r in self.replicas
                            if r.role in (ROLE_DECODE, ROLE_MIXED)]
        if self.disagg and (not self.prefill_pool or not self.decode_pool):
            raise ValueError(
                "disaggregated fleet needs at least one prefill and one "
                f"decode replica (roles: {sorted(roles)})")
        self.handoff = handoff or (ArenaHandoff() if self.disagg else None)
        if self.disagg:
            for r in self.prefill_pool:
                if r.role != ROLE_PREFILL:
                    continue
                r.engine.on_prefill_complete = (
                    lambda req, _r=r: self._handoff_from(_r, req))
            register_handoff_audit_entries(self.replicas[0].engine,
                                           self.handoff)
        self._lock = threading.RLock()
        self._fid = 0
        self._iterations = 0
        # fid -> live request; terminal requests are pruned (the client
        # keeps its handle) so a long-running router stays bounded
        self._requests: Dict[int, _FleetRequest] = {}
        self._by_engine: Dict[tuple, int] = {}   # (replica_idx, rid) -> fid
        # first-prompt-block hash -> replica index (bounded LRU): the
        # cross-replica prefix-cache admission hint
        self._affinity: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._rr = 0
        # host-side (policy, reason) -> count mirror of the
        # fleet_serving/routing_decisions counter, for obs-less callers
        # (the bench A/B reads this)
        self._decisions: "collections.Counter" = collections.Counter()
        self._handoff_ms = collections.deque(maxlen=8192)
        self._resubmit_count = 0
        self._death_count = 0
        self._handoff_fallbacks = 0
        self._starvation_limit = 2 * sum(
            r.engine.config.max_queue for r in self.replicas) + 8
        self._injector = None
        if fault_plan is not None:
            from ...observability.faultinject import FaultInjector

            obs = get_session()
            self._injector = FaultInjector(
                plan=fault_plan, rank=0, restart=0,
                registry=obs.registry if obs.enabled else None)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False
        log_dist(f"fleet router ready: {len(self.replicas)} replicas "
                 f"(policy={self.config.policy}, "
                 f"disagg={'on' if self.disagg else 'off'})")

    # -- client API --------------------------------------------------------
    @property
    def threaded(self) -> bool:
        return self._thread is not None

    def in_flight(self) -> int:
        with self._lock:
            return len(self._requests)

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_token_id: Optional[int] = None, tenant: str = "default",
               deadline_s: Optional[float] = None, seed: int = 0,
               n: int = 1):
        """Route and enqueue one prompt; returns a :class:`FleetHandle`
        (a list of ``n`` for parallel sampling, non-disaggregated fleets
        only — a fork's shared blocks cannot span a handoff)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if n < 1:
            raise ValueError(f"submit(n={n}): need n >= 1")
        if n > 1 and self.disagg:
            raise NotImplementedError(
                "parallel sampling (n > 1) is per-replica COW sharing — "
                "not supported through a disaggregated fleet")
        with self._lock:
            pool = self.prefill_pool if self.disagg else self.replicas
            replica, reason = self._pick(pool, prompt)
            if replica is None:
                raise FleetUnavailable("no alive replica to route to")
            self._count_decision(reason, replica)
            handles = replica.engine.submit(
                prompt, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_token_id=eos_token_id, tenant=tenant,
                deadline_s=deadline_s, seed=seed, n=n)
            if n == 1:
                handles = [handles]
            now = self.clock()
            out = []
            for i, h in enumerate(handles):
                fr = _FleetRequest(
                    fid=self._fid, prompt=prompt.copy(), seed=seed + i,
                    kwargs=dict(
                        max_new_tokens=h._req.max_new_tokens,
                        temperature=float(temperature), top_k=int(top_k),
                        top_p=float(top_p), eos_token_id=eos_token_id,
                        tenant=tenant),
                    arrival_s=now)
                if deadline_s is not None:
                    fr.deadline_abs = now + deadline_s
                self._fid += 1
                fr.bind(replica, h)
                fr.handle = FleetHandle(self, fr)
                self._requests[fr.fid] = fr
                self._by_engine[(replica.index, h._req.rid)] = fr.fid
                out.append(fr.handle)
            return out[0] if n == 1 else out

    def cancel(self, handle: FleetHandle) -> bool:
        with self._lock:
            fr = handle._fr
            if fr.done:
                return False
            self._drain_tokens(fr)
            if fr.u_req.done:        # finished just before the cancel
                self._settle(fr)
                return False
            if fr.replica.alive:
                fr.replica.engine.cancel(fr.u_handle)
            self._finish_fr(fr, F_CANCELLED)
            return True

    # -- the fleet iteration ----------------------------------------------
    def step(self) -> bool:
        """One fleet iteration: apply scheduled faults, drain dead
        replicas (resubmitting their requests), run one scheduler
        iteration on every alive replica with work, then poll health and
        stream out newly emitted tokens."""
        with self._lock:
            if self._injector is not None:
                self._injector.before_router_step(self._iterations,
                                                  self.kill_replica)
            self._drain_dead()
            progress = False
            for r in self.replicas:
                if not r.alive or not r.engine.in_flight():
                    continue
                try:
                    progress |= r.step()
                except ReplicaDead:
                    pass
                except Exception:
                    # a replica whose iteration raises is as dead as a
                    # crashed process: drain + resubmit next pass
                    logger.exception(
                        f"fleet replica {r.index} iteration failed — "
                        "marking dead")
                    self.kill_replica(r.index, reason="step-exception")
            for fr in list(self._requests.values()):
                if fr.replica.alive:
                    self._drain_tokens(fr)
                    self._settle(fr)
            self._publish()
            self._iterations += 1
            return progress

    def reset_latency_stats(self) -> None:
        """Drop the router's handoff/decision/resubmit tallies AND every
        replica's latency reservoirs — benches call this after warmup so
        the published numbers (incl. the warmup handoff, which JIT-compiles
        kv_export/kv_import inside its timed span) describe the measured
        load, not compilation."""
        with self._lock:
            self._handoff_ms.clear()
            self._handoff_fallbacks = 0
            self._decisions.clear()
            self._resubmit_count = 0
        for r in self.replicas:
            if r.alive:
                r.engine.reset_latency_stats()
                r.engine.sched.handoffs_out = 0

    def kill_replica(self, index: int, reason: str = "fault") -> None:
        """Mark a replica dead (chaos harness / health verdicts). Its
        in-flight requests resubmit on the next ``step()``."""
        if not 0 <= index < len(self.replicas):
            raise ValueError(
                f"kill_replica({index}): fleet has "
                f"{len(self.replicas)} replicas (indices 0.."
                f"{len(self.replicas) - 1})")
        with self._lock:
            r = self.replicas[index]
            if not r.alive:
                return
            r.kill(reason)
            self._death_count += 1
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "fleet_serving/replica_deaths",
                    help="replicas the router declared dead").inc(
                        reason=reason)
            logger.warning(f"fleet replica {index} dead ({reason}); "
                           "draining its requests")

    # -- internals ---------------------------------------------------------
    def _count_decision(self, reason: str, replica: Replica) -> None:
        self._decisions[(self.config.policy, reason)] += 1
        obs = get_session()
        if obs.enabled:
            obs.registry.counter(
                "fleet_serving/routing_decisions",
                help="requests routed, by policy decision reason").inc(
                    policy=self.config.policy, reason=reason,
                    replica=str(replica.index))

    def _affinity_key(self, prompt: np.ndarray) -> Optional[bytes]:
        if int(prompt.size) < self._block_size:
            return None
        import hashlib

        return hashlib.blake2b(
            np.ascontiguousarray(prompt[:self._block_size],
                                 np.int32).tobytes(),
            digest_size=16).digest()

    def _pick(self, pool: List[Replica], prompt: np.ndarray):
        """(replica, decision reason) under the configured policy; an
        empty/dead pool degrades to any alive replica (live beats pure)."""
        alive = [r for r in pool if r.alive]
        degraded = not alive
        if degraded:
            alive = [r for r in self.replicas if r.alive]
        if not alive:
            return None, "no_replica"
        policy = self.config.policy
        health = {r.index: r.health() for r in alive}
        reason = policy
        if policy == "round_robin":
            pick = alive[self._rr % len(alive)]
            self._rr += 1
        elif policy == "least_queue":
            pick = min(alive, key=lambda r: (health[r.index].in_flight,
                                             r.index))
        elif policy == "kv_occupancy":
            pick = min(alive, key=lambda r: health[r.index].load_key)
        else:   # affinity
            key = self._affinity_key(prompt)
            pick = None
            if key is None:
                reason = "affinity_short"
            else:
                warm = self._affinity.get(key)
                if warm is None:
                    reason = "affinity_cold"
                else:
                    cand = self.replicas[warm]
                    if cand not in alive:
                        reason = "affinity_dead"
                    elif (health[cand.index].arena_occupancy
                          > self.config.affinity_overload):
                        reason = "affinity_overload"
                    else:
                        pick, reason = cand, "affinity_warm"
            if pick is None:
                pick = min(alive, key=lambda r: health[r.index].load_key)
            if key is not None:
                # the admission hint: later requests with this prefix
                # follow the replica whose cache is (about to be) warm
                self._affinity[key] = pick.index
                self._affinity.move_to_end(key)
                while len(self._affinity) > 4096:
                    self._affinity.popitem(last=False)
        if degraded:
            reason += "_degraded"
        return pick, reason

    def _drain_tokens(self, fr: _FleetRequest) -> None:
        """Move newly emitted tokens from the bound engine handle into the
        fleet handle (and stamp the fleet-level TTFT)."""
        toks = fr.u_handle.tokens
        new = toks[fr.consumed:]
        if not new:
            return
        if fr.first_token_s is None:
            fr.first_token_s = self.clock()
            obs = get_session()
            if obs.enabled:
                obs.registry.histogram(
                    "fleet_serving/ttft_ms",
                    help="fleet submit → first streamed token, "
                         "wall ms").observe(
                             (fr.first_token_s - fr.arrival_s) * 1e3)
        for t in new:
            fr.handle._push(t)
        fr.consumed = len(toks)

    def _settle(self, fr: _FleetRequest) -> None:
        """Terminal-state propagation for the CURRENT binding."""
        if fr.done or not fr.u_req.done:
            return
        self._finish_fr(fr, F_FINISHED if fr.u_req.state == FINISHED
                        else F_CANCELLED)

    def _finish_fr(self, fr: _FleetRequest, state: str) -> None:
        fr.state = state
        fr.finish_s = self.clock()
        self._requests.pop(fr.fid, None)
        if fr.replica is not None and fr.u_req is not None:
            self._by_engine.pop((fr.replica.index, fr.u_req.rid), None)
        fr.handle._wake()

    def _drain_dead(self) -> None:
        """Resubmit every request stranded on a dead replica: recompute
        from original prompt + streamed tokens on a surviving replica —
        the same bit-exactness contract as per-engine preemption."""
        for r in self.replicas:
            if r.alive or r.drained:
                continue
            r.drained = True
            victims = [fr for fr in self._requests.values()
                       if fr.replica is r and not fr.done]
            for fr in victims:
                self._resubmit(fr)

    def _resubmit(self, fr: _FleetRequest) -> None:
        fr.resubmits += 1
        obs = get_session()
        if fr.resubmits > self.config.max_resubmits:
            logger.error(f"fleet request {fr.fid}: resubmission budget "
                         f"({self.config.max_resubmits}) exhausted — "
                         "cancelling")
            self._finish_fr(fr, F_CANCELLED)
            return
        tokens = fr.handle.tokens      # everything streamed IS recoverable
        # phase-matched pool preference: a request already decoding goes
        # back to the decode pool, one still prefilling to the prefill pool
        pool = ((self.decode_pool if tokens else self.prefill_pool)
                if self.disagg else self.replicas)
        deadline_s = (max(fr.deadline_abs - self.clock(), 0.0)
                      if fr.deadline_abs is not None else None)
        cands = ([r for r in pool if r.alive]
                 or [r for r in self.replicas if r.alive])
        for target in sorted(cands, key=lambda r: r.health().load_key):
            try:
                h2 = target.engine.submit_recovered(
                    fr.prompt, tokens, seed=fr.seed,
                    deadline_s=deadline_s, **fr.kwargs)
            except QueueFull:
                continue
            self._by_engine.pop((fr.replica.index, fr.u_req.rid), None)
            fr.bind(target, h2)
            # streamed tokens live engine-side in req.generated but were
            # never pushed to the NEW handle — nothing to re-drain
            self._by_engine[(target.index, h2._req.rid)] = fr.fid
            self._resubmit_count += 1
            self._count_decision("resubmit", target)
            if obs.enabled:
                obs.registry.counter(
                    "fleet_serving/resubmits",
                    help="requests resubmitted after a replica "
                         "death").inc()
            return
        logger.error(f"fleet request {fr.fid}: no replica can take the "
                     "resubmission — cancelling")
        self._finish_fr(fr, F_CANCELLED)

    # -- disaggregation: the prefill-complete hook -------------------------
    def _handoff_from(self, src: Replica, req) -> None:
        """Called by a prefill replica (engine lock held, inside this
        router's ``step``) the moment a request's last prefill chunk
        completed: move its KV blocks to a decode replica and rebind the
        fleet request there. Failure falls back to decoding in place."""
        fid = self._by_engine.get((src.index, req.rid))
        fr = self._requests.get(fid) if fid is not None else None
        if fr is None or fr.done:
            return
        cands = sorted((r for r in self.decode_pool
                        if r.alive and r.engine is not src.engine),
                       key=lambda r: r.health().load_key)
        t0 = self.clock()
        for dst in cands:
            dst_ids = self.handoff.transfer(src.engine, dst.engine,
                                            req.blocks)
            if dst_ids is None:
                continue            # decode pool dry on this replica
            # the remaining deadline crosses the handoff (like _resubmit's)
            # or the adopted request would sort last in the decode pool's
            # EDF queue behind every deadline-bearing arrival
            deadline_s = (max(fr.deadline_abs - self.clock(), 0.0)
                          if fr.deadline_abs is not None else None)
            try:
                h2 = dst.engine.adopt_prefilled(
                    prompt=req.prompt[:req.n_prompt],
                    n_prompt=req.n_prompt, generated=req.generated,
                    pending_token=req.pending_token, length=req.length,
                    blocks=dst_ids, seed=req.seed, sampling=req.sampling,
                    max_new_tokens=req.max_new_tokens,
                    eos_token_id=req.eos_token_id, tenant=req.tenant,
                    deadline_s=deadline_s)
            except QueueFull:
                dst.engine.alloc.free(dst_ids)
                continue
            # tokens emitted on the source (the prefill-completion first
            # token) must reach the fleet handle BEFORE the rebinding
            self._drain_tokens(fr)
            self._by_engine.pop((src.index, req.rid), None)
            fr.bind(dst, h2)
            fr.handoffs += 1
            self._by_engine[(dst.index, h2._req.rid)] = fr.fid
            src.engine.release_for_handoff(req)
            ms = (self.clock() - t0) * 1e3
            self._handoff_ms.append(ms)
            self._count_decision("disagg_decode", dst)
            obs = get_session()
            if obs.enabled:
                obs.registry.counter(
                    "fleet_serving/handoffs",
                    help="prefill→decode KV block handoffs").inc()
                obs.registry.histogram(
                    "fleet_serving/handoff_ms",
                    help="KV export+import+adopt wall ms").observe(ms)
            return
        # nobody could take it: the request decodes on the prefill replica
        self._handoff_fallbacks += 1
        obs = get_session()
        if obs.enabled:
            obs.registry.counter(
                "fleet_serving/handoff_fallbacks",
                help="handoffs the decode pool refused (request decodes "
                     "on its prefill replica)").inc()

    # -- telemetry ---------------------------------------------------------
    def _publish(self) -> None:
        obs = get_session()
        if not obs.enabled:
            return
        reg = obs.registry
        alive = 0
        for r in self.replicas:
            h = r.health()
            alive += int(h.alive)
            lbl = {"replica": str(r.index), "role": r.role}
            reg.gauge("fleet_serving/queue_depth",
                      help="per-replica admission queue depth").set(
                          h.queue_depth, **lbl)
            reg.gauge("fleet_serving/in_flight",
                      help="per-replica in-flight requests").set(
                          h.in_flight, **lbl)
            reg.gauge("fleet_serving/arena_occupancy",
                      help="per-replica allocated arena fraction").set(
                          round(h.arena_occupancy, 4), **lbl)
            reg.gauge("fleet_serving/decode_batch_occupancy",
                      help="per-replica decoding rows / max_seqs").set(
                          round(h.decode_batch_occupancy, 4), **lbl)
            reg.gauge("fleet_serving/kv_blocks_in_use",
                      help="per-replica allocated arena blocks").set(
                          h.kv_blocks_in_use, **lbl)
        reg.gauge("fleet_serving/replicas_alive",
                  help="replicas the router considers serving").set(alive)
        reg.gauge("fleet_serving/requests_in_flight",
                  help="fleet requests not yet terminal").set(
                      len(self._requests))

    def publish_latency_gauges(self) -> None:
        """Close-time percentile gauges over the handoff reservoir — the
        ``report`` CLI's ``== fleet serving ==`` latency inputs."""
        obs = get_session()
        if not obs.enabled or not self._handoff_ms:
            return
        from ..api import _percentile

        xs = list(self._handoff_ms)
        obs.registry.gauge("fleet_serving/handoff_p50_ms").set(
            _percentile(xs, 0.50))
        obs.registry.gauge("fleet_serving/handoff_p99_ms").set(
            _percentile(xs, 0.99))

    # -- drivers -----------------------------------------------------------
    def run(self, max_steps: Optional[int] = None) -> int:
        """Step until every fleet request is terminal (tests/benches)."""
        steps = 0
        starved = 0
        while self.in_flight():
            progress = self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            if progress:
                starved = 0
            else:
                starved += 1
                if starved > self._starvation_limit:
                    raise RuntimeError(
                        "fleet stalled: no replica can make progress "
                        f"({self.in_flight()} fleet requests in flight)")
        return steps

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._drive,
                                        name="dstpu-fleet", daemon=True)
        self._thread.start()

    def _drive(self) -> None:
        while not self._stop.is_set():
            try:
                if self.in_flight():
                    self.step()
                else:
                    self._stop.wait(0.002)
            except Exception:
                logger.exception("fleet driver step failed")
                get_session().crash_dump("fleet-step-exception")
                self._stop.wait(0.05)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.stop()
        self.publish_latency_gauges()
        # pool the replicas' latency reservoirs BEFORE their close()
        # publishes: each ServingEngine.close() sets the same unlabeled
        # serving/ttft_p50_ms / tpot / tokens_per_sec gauges, so the last
        # replica closed would otherwise stand in for the whole fleet
        ttft, tpot, tokens_out, wall = [], [], 0, 0.0
        for r in self.replicas:
            eng = r.engine
            ttft.extend(eng._ttft_samples)
            tpot.extend(eng._tpot_samples)
            tokens_out += eng._tokens_out
            wall = max(wall, eng.clock() - eng._started_s)
            try:
                eng.close()
            except Exception:
                logger.warning(f"fleet replica {r.index} close failed",
                               exc_info=True)
        obs = get_session()
        if obs.enabled:
            from ..api import _percentile

            reg = obs.registry
            for name, samples in (("ttft", ttft), ("tpot", tpot)):
                if samples:
                    reg.gauge(f"serving/{name}_p50_ms").set(
                        _percentile(samples, 0.50))
                    reg.gauge(f"serving/{name}_p99_ms").set(
                        _percentile(samples, 0.99))
            if tokens_out:
                reg.gauge("serving/tokens_per_sec",
                          help="generated tokens / wall seconds").set(
                              tokens_out / max(wall, 1e-9))
