"""Continuous-batching serving layer — the MII/FastGen analog.

The reference DeepSpeed serves models through MII/FastGen (dynamic
batching, blocked KV, streaming); this package is the same capability
rebuilt on the repo's inference substrate under jit-cache discipline:

  paged_kv.py    paged KV arena: host block allocator + the two
                 shape-static serving programs (prefill-chunk, decode)
  scheduler.py   Orca-style iteration-level scheduler: admission, chunked
                 prefill, multi-tenant fair queueing + deadlines,
                 preemption by block eviction (device-free, injectable
                 clock)
  session.py     RequestHandle: incremental token streaming, cancellation,
                 mid-stream parallel-sampling fork
  speculative.py drafters for speculative decoding: n-gram prompt lookup
                 (host-side) + draft model (own arena, shared block pool);
                 lossless bit-stable acceptance over the R×(K+1) verify
  api.py         ServingEngine.submit()/stream()/step()/run(), metrics
                 into the observability registry, tpuaudit registration
  fleet/         the deployment layer: data-plane router over N replicas,
                 prefill/decode disaggregation with KV block handoff,
                 replica-death drain + bit-exact resubmission

See docs/serving.md for the architecture and the block-table layout.
"""

from ..config.config import (FleetConfig, ServingConfig,  # noqa: F401
                             SpeculativeConfig)
from .api import ServingEngine, init_serving  # noqa: F401
from .paged_kv import (BlockAllocator, BlockAllocatorError,  # noqa: F401
                       PrefixCache)
from .scheduler import (QueueFull, Request, SamplingParams,  # noqa: F401
                        Scheduler)
from .session import (DeadlineExceeded, RequestCancelled,  # noqa: F401
                      RequestHandle)
from .speculative import (Drafter, DraftModelDrafter,  # noqa: F401
                          NgramDrafter)
from .fleet import (ArenaHandoff, FleetHandle, FleetRouter,  # noqa: F401
                    FleetUnavailable, KVHandoff, Overloaded, Replica,
                    ReplicaHealth, build_replicas)

__all__ = [
    "ServingConfig", "SpeculativeConfig", "ServingEngine", "init_serving",
    "BlockAllocator", "BlockAllocatorError", "PrefixCache",
    "Scheduler", "Request", "SamplingParams", "QueueFull",
    "RequestHandle", "RequestCancelled", "DeadlineExceeded",
    "Drafter", "NgramDrafter", "DraftModelDrafter",
    "FleetConfig", "FleetRouter", "FleetHandle", "FleetUnavailable",
    "Overloaded", "Replica", "ReplicaHealth", "build_replicas",
    "KVHandoff", "ArenaHandoff",
]
