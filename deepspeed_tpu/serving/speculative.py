"""Speculative decoding over the paged KV arena — drafters + acceptance.

Decode is the serving layer's latency floor: every emitted token costs one
full target-model dispatch. Speculative decoding (Leviathan et al. 2023)
buys multiple tokens per dispatch: a cheap **drafter** proposes up to K
continuation tokens per request, the target model scores all of them in ONE
``R×(K+1)`` verify program (``paged_kv.build_verify_program``), and the
host keeps the longest accepted prefix. Two drafters ship:

* ``NgramDrafter`` — prompt-lookup (model-free, host-side, zero extra HBM):
  the request's trailing n-gram is matched against its own prompt+output
  history and the continuation of the most recent earlier occurrence is
  proposed. Excellent on repetitive/extractive text, free everywhere else.
* ``DraftModelDrafter`` — a smaller ``TransformerModel`` drafts
  autoregressively. Its paged KV lives in a sibling arena indexed by the
  SAME ``BlockAllocator`` as the target's (block ids are allocated from one
  pool), so draft KV spends the same HBM budget and feels the same
  eviction pressure as everything else; the drafter never preempts — when
  the pool can't extend a row's draft blocks, that row simply stops
  speculating until pressure clears.

**Acceptance rule (lossless + bit-stable).** The verify program samples
EVERY position with the key the non-speculative decode would use for that
output-token index: ``fold_in(fold_in(base_key, request_seed),
token_index)``. Let ``x_j`` be the target's sample after feeding token j
(``x_0`` after the pending token, ``x_j`` after draft ``d_j``). The host
emits ``x_0``, then accepts draft ``d_{j+1}`` — and emits ``x_{j+1}`` —
while ``x_j == d_{j+1}``. Every emitted token is therefore EXACTLY the
token the non-speculative path would have sampled at that index (same
logits — the accepted prefix pins the same context — same key), so
speculation changes latency, never output: greedy speculation is
bit-identical to vanilla greedy ``generate()``, and temperature sampling
is bit-identical to the non-speculative serving stream. This trades a
little acceptance probability against classic modified-residual rejection
sampling (acceptance ``E[p(draft)]`` instead of ``Σ min(p, q)``) to keep
the repo-wide reproducibility contract: output depends only on (engine
seed, request seed, token index), never on scheduling — or speculation.

Rollback is positional: the arena layout is left-aligned
(column == absolute position), so rejected draft KV is simply dead weight
past the accepted length — never read (causality over true positions) and
overwritten in place when real tokens reach those positions. The scheduler
frees whole blocks past the accepted length (``truncate_blocks``); the
draft arena rolls back the same way through ``Drafter.commit``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..utils.logging import logger
from . import paged_kv
from .scheduler import Request

__all__ = ["Drafter", "NgramDrafter", "DraftModelDrafter", "make_drafter",
           "request_stream"]


def request_stream(req: Request) -> np.ndarray:
    """The request's full committed token stream: original prompt plus
    every emitted token (the pending one included). Stable across
    preemption — ``req.prompt`` absorbs generated tokens in recompute mode
    but ``req.prompt[:n_prompt] + generated`` is invariant."""
    return np.concatenate(
        [req.prompt[:req.n_prompt],
         np.asarray(req.generated, np.int32)]).astype(np.int32)


class Drafter:
    """Proposal source for speculative decoding.

    The engine calls ``propose`` once per iteration with the rows that will
    verify this round and a per-row token budget; after the verify it calls
    ``commit`` per row with the post-acceptance request state, and
    ``release`` when a request leaves the arena (finish/cancel/preempt).
    ``dispatches`` counts the drafter's own device dispatches (0 for
    host-side drafters) — the bench's draft-overhead accounting."""

    name = "null"

    def __init__(self):
        self.dispatches = 0

    def propose(self, reqs: List[Request],
                caps: List[int]) -> List[np.ndarray]:
        """Up to ``caps[i]`` proposed continuation tokens for ``reqs[i]``,
        given its committed stream (the pending token is the last stream
        entry — proposals continue AFTER it). May return fewer (or none):
        proposal counts are data, not shape."""
        raise NotImplementedError

    def commit(self, req: Request) -> None:
        """Verify landed: ``req.length``/``generated`` reflect the accepted
        tokens. Drafters with device state roll their KV back here."""

    def release(self, req: Request) -> None:
        """Request left the arena (finished/cancelled/preempted)."""

    def close(self) -> None:
        """Engine shutdown: drop any device state."""


class NgramDrafter(Drafter):
    """Prompt-lookup decoding (model-free): propose the continuation of the
    most recent earlier occurrence of the request's trailing n-gram in its
    own prompt+output history. Tried longest-first from ``ngram_max`` down
    to ``ngram_min``; no match proposes nothing (that row runs as plain
    decode inside the same verify dispatch). Host-side and stateless —
    zero HBM, zero dispatches, correct by construction under preemption."""

    name = "ngram"

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1):
        super().__init__()
        if not 1 <= ngram_min <= ngram_max:
            raise ValueError(f"need 1 <= ngram_min ({ngram_min}) <= "
                             f"ngram_max ({ngram_max})")
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)

    def _lookup(self, ctx: np.ndarray, k: int) -> np.ndarray:
        L = int(ctx.size)
        for n in range(self.ngram_max, self.ngram_min - 1, -1):
            if L < n + 2:        # need the suffix plus an earlier match
                continue
            pat = ctx[L - n:]
            # candidate starts j with j+n < L: the match must end before
            # the suffix starts contributing its own continuation
            wins = np.lib.stride_tricks.sliding_window_view(ctx[:L - 1], n)
            hits = np.flatnonzero((wins == pat).all(axis=1))
            if hits.size == 0:
                continue
            j = int(hits[-1])            # most recent occurrence
            return ctx[j + n:j + n + k].astype(np.int32)
        return np.zeros((0,), np.int32)

    def propose(self, reqs: List[Request],
                caps: List[int]) -> List[np.ndarray]:
        return [self._lookup(request_stream(r), k) if k > 0
                else np.zeros((0,), np.int32)
                for r, k in zip(reqs, caps)]


class _DraftState:
    """Per-request draft-arena bookkeeping: ``length`` stream tokens whose
    KV is valid in the draft arena, backed by ``blocks``."""

    __slots__ = ("blocks", "length")

    def __init__(self):
        self.blocks: List[int] = []
        self.length = 0


class DraftModelDrafter(Drafter):
    """A smaller model drafts autoregressively in its own paged arena.

    The draft arena mirrors the target pool's geometry — same block size,
    same block count, ids allocated from the SAME ``BlockAllocator`` — so
    draft KV is a first-class tenant of the serving HBM budget: a
    speculating request holds blocks for its draft context in addition to
    its target context, and when the pool tightens the drafter backs off
    (per-row, allocation-failure-driven) rather than evicting anyone.

    Drafting is batched and greedy: one R×1 draft decode program (same
    builder as the target's) runs K times per iteration, every speculating
    row advancing together; rows that fell behind (an all-accepted round
    leaves the last draft token un-fed) re-feed known stream tokens through
    the same loop, and a freshly admitted or recomputed request catches up
    through the draft prefill program in chunks. Greedy proposals maximise
    the exact-match acceptance probability ``p_target(argmax q)`` for
    peaked target distributions and keep the drafter RNG-free."""

    name = "draft"

    def __init__(self, draft_engine, config, allocator, blocks_per_seq: int,
                 paged_impl: str = "auto"):
        super().__init__()
        import jax

        self.engine = draft_engine
        self.config = config
        self.alloc = allocator
        self.blocks_per_seq = int(blocks_per_seq)
        cfg = draft_engine.model.config
        self._cfg = cfg
        self._dtype = draft_engine.config.dtype
        spec = config.speculative
        self.draft_chunk = spec.draft_chunk or config.prefill_chunk
        from ..parallel import mesh as mesh_mod

        self._mesh_mod = mesh_mod
        with mesh_mod.ambient(draft_engine.mesh):
            self._arena = paged_kv.init_paged_cache(
                cfg, config.pool_blocks() + 1, config.block_size,
                self._dtype)
        self._decode = paged_kv.build_decode_program(cfg, paged_impl)
        self._prefill = paged_kv.build_prefill_program(cfg, paged_impl)
        self._paged_impl = paged_impl
        self._state: Dict[int, _DraftState] = {}
        self._key = jax.random.PRNGKey(0)   # greedy drafts never draw

    # -- bookkeeping -------------------------------------------------------
    def state_for(self, req: Request) -> _DraftState:
        st = self._state.get(req.rid)
        if st is None:
            st = self._state[req.rid] = _DraftState()
        return st

    def _ensure_blocks(self, st: _DraftState, upto_tokens: int) -> bool:
        """Grow the draft block list to cover ``upto_tokens`` positions —
        same optional-work discipline as the target arena's verify
        extension (shared helper: plain allocation, no eviction ladder).
        Returns False when the pool says no."""
        return paged_kv.extend_block_list(self.alloc, st.blocks,
                                          upto_tokens,
                                          self.config.block_size)

    def _truncate(self, st: _DraftState) -> None:
        paged_kv.truncate_block_list(self.alloc, st.blocks, st.length,
                                     self.config.block_size)

    # -- catch-up ----------------------------------------------------------
    def _prefill_catchup(self, req: Request, st: _DraftState,
                         target_len: int, obs) -> None:
        """Bring the draft KV from ``st.length`` to ``target_len`` stream
        tokens via the (1, C) draft prefill program — admission and
        post-preemption recompute; the steady-state ≤1-token gap rides the
        batched decode loop instead."""
        stream = request_stream(req)
        C = self.draft_chunk
        z1 = np.zeros((1,), np.float32)
        zi = np.zeros((1,), np.int32)
        o1 = np.ones((1,), np.float32)
        bt = np.zeros((1, self.blocks_per_seq), np.int32)
        bt[0, :len(st.blocks)] = st.blocks
        while st.length < target_len:
            n_valid = min(C, target_len - st.length)
            chunk = np.zeros((1, C), np.int32)
            chunk[0, :n_valid] = stream[st.length:st.length + n_valid]
            with self._mesh_mod.ambient(self.engine.mesh):
                with obs.span("serving/draft_prefill", tokens=int(n_valid)):
                    tok, _last, self._arena = self._prefill(
                        self.engine.params, self._arena, bt, chunk,
                        np.asarray(st.length, np.int32),
                        np.asarray(n_valid, np.int32),
                        z1, zi, o1, zi, self._key)
                    np.asarray(tok)     # fence
            self.dispatches += 1
            st.length += n_valid

    # -- the drafter contract ----------------------------------------------
    def propose(self, reqs: List[Request],
                caps: List[int]) -> List[np.ndarray]:
        obs = _obs()
        R = self.config.max_seqs
        jobs = []    # [list_index, req, state, queue of known tokens]
        max_iters = 0
        for i, (req, cap) in enumerate(zip(reqs, caps)):
            if cap <= 0:
                continue
            st = self.state_for(req)
            # the draft writes positions [st.length, req.length + cap):
            # catch-up + pending + cap-1 drafts — all-or-nothing budget
            if not self._ensure_blocks(st, req.length + cap):
                continue   # pool pressure: this row sits the round out
            if req.length - st.length > 1:
                self._prefill_catchup(req, st, req.length, obs)
            stream = request_stream(req)
            # residual ≤1-token gap plus the pending token (always un-fed)
            queue = [int(t) for t in stream[st.length:]]
            jobs.append((i, req, st, queue))
            max_iters = max(max_iters, cap + len(queue) - 1)
        out = [np.zeros((0,), np.int32) for _ in reqs]
        if not jobs:
            return out
        props: Dict[int, List[int]] = {j[0]: [] for j in jobs}
        last: Dict[int, int] = {}
        zR = np.zeros((R,), np.float32)
        ziR = np.zeros((R,), np.int32)
        oR = np.ones((R,), np.float32)
        for _ in range(max_iters):
            bt = np.zeros((R, self.blocks_per_seq), np.int32)
            lengths = np.zeros((R,), np.int32)
            tokens = np.zeros((R,), np.int32)
            fed: List[tuple] = []
            for i, req, st, queue in jobs:
                if len(props[i]) >= caps[i]:
                    continue            # row done: rides scratch this step
                if queue:
                    tok = queue.pop(0)
                    emits = not queue   # the queue's LAST entry is the
                    #   pending token — its output is the first proposal;
                    #   earlier entries are catch-up (outputs discarded)
                else:
                    tok = last[i]       # feed the previous proposal back
                    emits = True
                row = req.row
                bt[row, :len(st.blocks)] = st.blocks
                lengths[row] = st.length
                tokens[row] = tok
                fed.append((i, st, row, emits))
            if not fed:
                break
            with self._mesh_mod.ambient(self.engine.mesh):
                with obs.span("serving/draft_decode", batch=len(fed)):
                    nxt, self._arena = self._decode(
                        self.engine.params, self._arena, bt, lengths,
                        tokens, zR, ziR, oR, ziR, ziR, self._key)
                    nxt = np.asarray(nxt)
            self.dispatches += 1
            for i, st, row, emits in fed:
                st.length += 1
                if emits:
                    tok = int(nxt[row])
                    props[i].append(tok)
                    last[i] = tok
        for i, _req, _st, _queue in jobs:
            out[i] = np.asarray(props[i], np.int32)
        return out

    def commit(self, req: Request) -> None:
        st = self._state.get(req.rid)
        if st is None:
            return
        # the valid draft prefix is whatever it fed that the verify kept:
        # committed stream tokens only — rejected draft KV rolls back by
        # position exactly like the target arena
        st.length = min(st.length, req.length)
        self._truncate(st)

    def release(self, req: Request) -> None:
        st = self._state.pop(req.rid, None)
        if st is not None and st.blocks:
            self.alloc.free(st.blocks)

    def close(self) -> None:
        for st in self._state.values():
            if st.blocks:
                self.alloc.free(st.blocks)
        # tpusync: disable=unguarded-shared-write — shutdown-ordered:
        # close() runs after ServingEngine.close() stopped the driver
        # thread, so no release() can race it
        self._state.clear()


def _obs():
    from ..observability import get_session

    return get_session()


def make_drafter(config, target_engine, allocator, blocks_per_seq: int,
                 draft_engine=None,
                 paged_impl: str = "auto") -> Optional[Drafter]:
    """Build the drafter ``config.speculative`` asks for (None when off).
    ``draft_engine`` is an ``InferenceEngine`` over the (smaller) draft
    model — required for mode='draft', vocab-checked against the target;
    ``allocator`` is the serving pool's ``BlockAllocator`` (the draft
    arena shares it)."""
    spec = config.speculative
    if spec.mode == "off":
        return None
    if spec.mode == "ngram":
        return NgramDrafter(spec.ngram_max, spec.ngram_min)
    if draft_engine is None:
        raise ValueError(
            "speculative.mode='draft' needs a draft model: pass "
            "draft_model= to init_serving (or draft_engine= to "
            "ServingEngine)")
    tv = target_engine.model.config.vocab_size
    dv = draft_engine.model.config.vocab_size
    if tv != dv:
        raise ValueError(
            f"draft model vocab ({dv}) != target vocab ({tv}) — draft "
            "proposals would index a different token space")
    if draft_engine.config.dtype != target_engine.config.dtype:
        logger.warning(
            "draft model dtype %s != target dtype %s — allowed, but the "
            "draft arena spends pool blocks at its own width",
            draft_engine.config.dtype, target_engine.config.dtype)
    return DraftModelDrafter(
        draft_engine, config, allocator=allocator,
        blocks_per_seq=blocks_per_seq, paged_impl=paged_impl)
