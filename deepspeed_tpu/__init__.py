"""deepspeed_tpu — a TPU-native large-model training & inference framework.

Capability surface of DeepSpeed v0.9.2 (reference: /root/reference), designed
idiomatically for JAX/XLA/Pallas: named-mesh sharding instead of NCCL process
groups, SPMD ZeRO instead of hook-driven partitioning, Pallas kernels instead
of CUDA. Public entry points mirror the reference (``deepspeed/__init__.py``):

  initialize()           -> (engine, optimizer, dataloader, lr_scheduler)
  init_inference()       -> InferenceEngine
  init_serving()         -> ServingEngine (continuous batching, the MII analog)
  init_rlhf()            -> HybridEngine with the RLHF objective + serving
                            rollout side (the DeepSpeed-Chat substrate —
                            docs/rlhf.md)
  run_training_session() -> self-healing supervised training (rollback on
                            numerics trips, hang escalation, straggler
                            eviction via the elastic agent — docs/resilience.md)
  comm                   -> named-axis collective API
"""

__version__ = "0.1.0"
version = __version__

from . import comm  # noqa: F401
from .config import Config, ConfigError, load_config  # noqa: F401
from .parallel import topology  # noqa: F401
from .parallel import zero  # noqa: F401  (reference: from .runtime import zero)
from .utils.logging import log_dist, logger  # noqa: F401


def init_distributed(dist_backend: str = "xla", **kwargs):
    """Analog of ``deepspeed.init_distributed`` (reference comm bootstrap,
    deepspeed/__init__.py:129 path): env rendezvous →
    ``jax.distributed.initialize``. Idempotent."""
    from .comm.comm import init_distributed as _init

    return _init(dist_backend=dist_backend, **kwargs)


def default_inference_config():
    """Analog of ``deepspeed.default_inference_config`` (reference
    deepspeed/__init__.py:253): the default InferenceConfig as a dict."""
    import dataclasses

    from .inference.engine import InferenceConfig

    return dataclasses.asdict(InferenceConfig())


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, mesh=None, config=None,
               config_params=None, rng=None, collate_fn=None, dist_init_required=None):
    """Create a training engine — analog of ``deepspeed.initialize`` (reference
    deepspeed/__init__.py:58). Imported lazily to keep ``import deepspeed_tpu``
    cheap."""
    from .runtime.engine import initialize as _initialize

    return _initialize(args=args, model=model, optimizer=optimizer,
                       model_parameters=model_parameters, training_data=training_data,
                       lr_scheduler=lr_scheduler, mesh=mesh,
                       config=config if config is not None else config_params,
                       rng=rng, collate_fn=collate_fn)


def init_inference(model=None, config=None, **kwargs):
    """Analog of ``deepspeed.init_inference`` (reference deepspeed/__init__.py:260)."""
    from .inference.engine import init_inference as _init_inference

    return _init_inference(model=model, config=config, **kwargs)


def run_training_session(model=None, config=None, data_fn=None,
                         total_steps=0, save_dir=None, **kwargs):
    """Supervised self-healing training (runtime/session.py): the engine
    lifecycle across failures — automatic rollback to the last verified
    checkpoint on a numerics trip, hang escalation
    (dump → soft restart → hard restart for the elastic agent), and
    straggler eviction with membership shrink. See docs/resilience.md."""
    from .runtime.session import run_training_session as _run

    return _run(model=model, config=config, data_fn=data_fn,
                total_steps=total_steps, save_dir=save_dir, **kwargs)


def init_serving(model=None, serving_config=None, **kwargs):
    """Continuous-batching serving front end (the MII/FastGen analog):
    builds an inference engine and wraps it in a
    ``serving.ServingEngine`` — paged KV arena, iteration-level scheduler,
    streaming submit/stream API. See docs/serving.md."""
    from .serving import init_serving as _init_serving

    return _init_serving(model=model, serving_config=serving_config, **kwargs)


def init_rlhf(model=None, config=None, serving_config=None, **kwargs):
    """RLHF post-training entry point (the DeepSpeed-Chat hybrid-engine
    analog): a ``HybridEngine`` whose model carries the PPO-clip/GRPO
    objective and whose rollouts run through the serving stack — one
    weight set, one paged arena, bit-exactly replayable rollouts. Pair
    with ``rlhf.RLHFTrainer``. See docs/rlhf.md."""
    from .rlhf import init_rlhf as _init_rlhf

    return _init_rlhf(model=model, config=config,
                      serving_config=serving_config, **kwargs)


def add_config_arguments(parser):
    """Analog of reference deepspeed/__init__.py:237 — attach --deepspeed args."""
    import argparse

    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag for compatibility)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the framework JSON config file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse.SUPPRESS)
    return parser
