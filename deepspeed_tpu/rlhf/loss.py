"""RLHF policy losses — PPO-clip / GRPO as a drop-in ``Model.loss_fn``.

The training engine differentiates ``model.loss_fn(params, microbatch)``;
RLHF needs a different objective over an enriched microbatch, so
:func:`rlhf_model` wraps a base model with a loss that reads the extra
keys the trainer packs:

    input_ids   (B, T) int32    prompt + response, zero-padded
    targets     (B, T) int32    input_ids shifted left one (pad 0)
    loss_mask   (B, T) float32  1.0 on positions whose TARGET is a
                                response token, 0 elsewhere (prompt + pad)
    advantages  (B, T) float32  per-token advantage (group-normalized for
                                GRPO, whitened rewards for PPO), already
                                broadcast over response positions
    old_logp    (B, T) float32  behaviour-policy logprobs (the serving
                                score pass under the rollout weights)
    ref_logp    (B, T) float32  frozen-reference logprobs (second score
                                pass); all-zero when kl_coef == 0

The objective is the standard clipped surrogate plus a k3 KL penalty:

    ratio  = exp(logp - old_logp)
    pg     = -min(ratio * A, clip(ratio, 1±eps) * A)
    kl     = exp(ref - logp) - (ref - logp) - 1        # k3: >= 0, unbiased
    loss   = mean_masked(pg + kl_coef * kl)

GRPO vs PPO differ only in how the trainer computes ``advantages`` (the
host-side :func:`group_advantages` / :func:`whitened_advantages`), so ONE
compiled train step serves both.

Target logprobs are gathered with the one-hot masked-sum contraction, not
``take_along_axis`` — the vocab dim may be TP-sharded and the XLA CPU SPMD
partitioner miscompiles the gather (the PR-5 root cause in
``models/transformer.cross_entropy_loss``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence

import jax.numpy as jnp
import numpy as np

__all__ = ["rlhf_model", "group_advantages", "whitened_advantages"]


def rlhf_model(model: Any, rlhf_cfg: Any) -> Any:
    """A copy of ``model`` whose ``loss_fn`` is the PPO-clip/GRPO
    objective (``eval_loss_fn`` dropped — eval of a policy objective on
    held-out rollouts has no meaning without rollouts). The wrapped model
    drives a stock ``TrainEngine``/``HybridEngine`` unchanged — gas
    scanning, ZeRO sharding, fp16/bf16, the numerics sentinel and the
    NaN-rollback machinery all apply to the RLHF step for free."""
    clip = float(rlhf_cfg.clip_ratio)
    kl_coef = float(rlhf_cfg.kl_coef)
    base_apply = model.apply

    def loss_fn(params, mb):
        from ..models.transformer import gather_target_logprobs

        logits, _ = base_apply(params, {"input_ids": mb["input_ids"]})
        logp = gather_target_logprobs(logits, mb["targets"])
        mask = mb["loss_mask"].astype(jnp.float32)
        adv = mb["advantages"].astype(jnp.float32)
        ratio = jnp.exp(logp - mb["old_logp"])
        pg = -jnp.minimum(ratio * adv,
                          jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
        obj = pg
        if kl_coef > 0.0:
            # masked positions carry fake ref_logp (0.0 == prob 1), and a
            # padded target the model finds unlikely would drive
            # exp(ref - logp) to inf — and inf * mask(0) is NaN, poisoning
            # the whole masked sum (the same 0×nonfinite class the paged
            # read paths guard against). Zero d under the mask so pads
            # contribute exactly exp(0) - 0 - 1 = 0.
            d = jnp.where(mask > 0, mb["ref_logp"] - logp, 0.0)
            obj = obj + kl_coef * (jnp.exp(d) - d - 1.0)
        return jnp.sum(obj * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    return dataclasses.replace(model, loss_fn=loss_fn, eval_loss_fn=None,
                               name=model.name + "-rlhf")


def group_advantages(rewards: Sequence[Sequence[float]]
                     ) -> List[List[float]]:
    """GRPO: within each prompt's candidate group, advantage =
    (r - mean) / (std + eps). A zero-variance group (every candidate
    scored the same) yields zeros — no gradient signal, by design."""
    out: List[List[float]] = []
    for group in rewards:
        r = np.asarray(group, np.float64)
        centred = r - r.mean()
        std = r.std()
        out.append(list((centred / (std + 1e-6)).astype(np.float64)))
    return out


def whitened_advantages(rewards: Sequence[Sequence[float]],
                        whiten: bool = True) -> List[List[float]]:
    """PPO (critic-free): the advantage is the reward, whitened across the
    WHOLE batch when ``whiten`` — the RLOO-style baseline that keeps the
    clipped surrogate scale-stable without a value model."""
    flat = np.asarray([x for g in rewards for x in g], np.float64)
    if whiten and flat.size:
        flat = (flat - flat.mean()) / (flat.std() + 1e-6)
    out: List[List[float]] = []
    i = 0
    for group in rewards:
        out.append(list(flat[i:i + len(group)]))
        i += len(group)
    return out
