"""The RLHF iteration loop: generate → score → train → flip.

One :class:`RLHFTrainer` owns a :class:`~deepspeed_tpu.runtime
.hybrid_engine.HybridEngine` (whose model carries the
:func:`~deepspeed_tpu.rlhf.loss.rlhf_model` objective) and drives the
DeepSpeed-Chat step-3 shape over the serving stack:

1. **flip** — ``engine.flip_to_serving()``: one resharding program moves
   the current training weights into the serving layout; the arena, block
   pool, compiled programs and scheduler survive (zero realloc, zero
   recompiles); the prefix cache invalidates (stale content hashes).
2. **rollout** — :class:`~.rollout.RolloutCollector`: each prompt's
   candidate group is ONE prefill + ``fork(n)`` COW siblings; shared
   system prompts ride prefix sharing; the policy's own n-gram drafter
   speculates; seeds derive from (iteration, prompt, sample) so the whole
   phase is bit-exactly replayable from its manifest.
3. **score** — the pluggable ``reward_fn`` scores each candidate;
   behaviour-policy (π_old) and frozen-reference logprobs come from
   **two more serving passes over the same arena**
   (``ServingEngine.score_logprobs`` — one compiled program, params as an
   argument).
4. **train** — PPO-clip / GRPO step on the TrainEngine (the wrapped
   ``loss_fn``), then back to 1.

Resilience rides :class:`~deepspeed_tpu.runtime.session.TrainingSession`
(:meth:`RLHFTrainer.run`): the whole iteration is ``data_fn(step)`` — a
NaN→rollback recovery restores the checkpoint and re-calls it, and
because the restored weights and the derived seeds are bit-identical, the
re-collected rollouts reproduce the failed iteration's manifest exactly.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import get_session
from ..utils.logging import log_dist
from .loss import group_advantages, whitened_advantages
from .rollout import (RolloutBatch, RolloutCollector, RolloutManifest,
                      replay)

__all__ = ["RLHFTrainer"]


class RLHFTrainer:
    """Drives RLHF iterations over a hybrid engine.

    ``prompt_fn(iteration) -> [token arrays]`` MUST be a pure function of
    the iteration (the replay/rollback contract — the same purity rule as
    ``TrainingSession.data_fn``); return a fixed prompt count so the train
    step never respecializes. ``reward_fn(prompt, response_tokens) ->
    float`` is the pluggable scorer (a reward model, a verifier, a
    heuristic). The sample count per iteration
    (``len(prompts) * group_n``) must divide by the engine's
    ``gradient_accumulation_steps``."""

    def __init__(self, engine, prompt_fn: Callable[[int], Sequence[Any]],
                 reward_fn: Callable[[np.ndarray, List[int]], float],
                 rlhf: Optional[Any] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self.cfg = rlhf if rlhf is not None else engine.config.rlhf
        self.cfg.validate()
        self.prompt_fn = prompt_fn
        self.reward_fn = reward_fn
        self.clock = clock
        self.serving = engine.serving_engine()
        self.seq_budget = self.serving.config.max_model_len
        self.collector = RolloutCollector(
            self.serving, group_n=self.cfg.group_n,
            temperature=self.cfg.temperature, top_k=self.cfg.top_k,
            top_p=self.cfg.top_p, max_new_tokens=self.cfg.max_new_tokens,
            eos_token_id=self.cfg.eos_token_id, clock=clock)
        # frozen reference = the policy at trainer construction: flip once
        # and HOLD the resharded tree — the next flip REPLACES
        # infer.params with fresh arrays, so this reference costs zero
        # copies and stays on the serving shardings (the score program
        # accepts it without a recompile)
        engine.refresh_params()
        self._ref_params = (engine._inference_engine().params
                            if self.cfg.kl_coef > 0 else None)
        import collections

        # bounded (step, manifest) history: manifests hold every generated
        # stream, so keeping all of a long run's would leak host memory —
        # the recent window covers replay/debugging (a rollback's re-run
        # appends a second entry for the same step, deliberately); persist
        # manifests yourself (RolloutManifest.save) for full retention
        self.manifests: "collections.deque[Tuple[int, RolloutManifest]]" \
            = collections.deque(maxlen=16)
        self.losses: List[float] = []
        self._phase_s: Dict[str, float] = {
            "flip": 0.0, "rollout": 0.0, "score": 0.0, "train": 0.0}
        self._tokens_trained = 0
        self._last_prepare_end: Optional[float] = None
        self._reward_sum = 0.0
        self._reward_n = 0

    # -- one iteration's batch (the TrainingSession data_fn) ---------------
    def data_fn(self, step: int) -> Dict[str, np.ndarray]:
        """Everything before the train step: flip, rollout (+ optional
        replay verification), score, advantage, batch packing. Pure given
        the engine's weights at ``step`` — a rollback that restores them
        re-produces this batch bit-exactly."""
        eng = self.engine
        obs = get_session()
        now = self.clock()
        if self._last_prepare_end is not None:
            # the wall between data_fn calls is the train phase (the
            # session owns the train_batch call, so the trainer brackets
            # it from the outside)
            self._phase_s["train"] += now - self._last_prepare_end
        t0 = now
        serving = eng.flip_to_serving()
        self._phase_s["flip"] += self.clock() - t0

        t0 = self.clock()
        prompts = [np.asarray(p, np.int32).reshape(-1)
                   for p in self.prompt_fn(step)]
        rollouts, manifest = self.collector.collect(prompts, step)
        self.manifests.append((step, manifest))
        if self.cfg.replay_verify:
            # continuously enforce the determinism contract: replay with
            # speculation toggled OPPOSITE to the recording run
            was = serving.spec_suspended
            serving.spec_suspended = not was
            try:
                replay(manifest, serving, verify=True)
            finally:
                serving.spec_suspended = was
        self._phase_s["rollout"] += self.clock() - t0

        t0 = self.clock()
        batch = self._score_and_pack(rollouts)
        self._phase_s["score"] += self.clock() - t0
        self._publish(obs, iteration=True)
        self._last_prepare_end = self.clock()
        return batch

    # -- scoring + packing -------------------------------------------------
    def _score_and_pack(self, rollouts: RolloutBatch
                        ) -> Dict[str, np.ndarray]:
        cfg = self.cfg
        serving = self.serving
        rewards = [[self.reward_fn(s.prompt, list(s.tokens)) for s in g]
                   for g in rollouts.groups]
        self._reward_sum += float(sum(x for g in rewards for x in g))
        self._reward_n += sum(len(g) for g in rewards)
        if cfg.algo == "grpo":
            advantages = group_advantages(rewards)
        else:
            advantages = whitened_advantages(rewards,
                                             whiten=cfg.whiten_advantages)
        samples = rollouts.samples
        flat_adv = [a for g in advantages for a in g]
        B, T = len(samples), self.seq_budget
        gas = self.engine.gradient_accumulation_steps()
        if B % gas:
            raise ValueError(
                f"rlhf: samples per iteration ({B}) must divide by "
                f"gradient_accumulation_steps ({gas}) — adjust the prompt "
                "count or group_n")
        ids = np.zeros((B, T), np.int32)
        targets = np.zeros((B, T), np.int32)
        mask = np.zeros((B, T), np.float32)
        adv = np.zeros((B, T), np.float32)
        old_logp = np.zeros((B, T), np.float32)
        ref_logp = np.zeros((B, T), np.float32)
        for k, (s, a) in enumerate(zip(samples, flat_adv)):
            seq = s.sequence
            L, n_prompt = int(seq.size), int(s.prompt.size)
            ids[k, :L] = seq
            targets[k, :L - 1] = seq[1:]
            # position p's target is seq[p+1]: response targets are
            # p in [n_prompt - 1, L - 1)
            mask[k, n_prompt - 1:L - 1] = 1.0
            adv[k, n_prompt - 1:L - 1] = a
            # π_old under the freshly flipped (pre-update) weights — the
            # behaviour policy that generated the rollout
            old_logp[k, :L - 1] = serving.score_logprobs(seq)
            if self._ref_params is not None:
                ref_logp[k, :L - 1] = serving.score_logprobs(
                    seq, params=self._ref_params)
            self._tokens_trained += L
        mb = B // gas
        return {
            "input_ids": ids.reshape(gas, mb, T),
            "targets": targets.reshape(gas, mb, T),
            "loss_mask": mask.reshape(gas, mb, T),
            "advantages": adv.reshape(gas, mb, T),
            "old_logp": old_logp.reshape(gas, mb, T),
            "ref_logp": ref_logp.reshape(gas, mb, T),
        }

    # -- plain loop (tests / no-checkpoint runs) ---------------------------
    def step(self) -> float:
        """One unsupervised RLHF iteration (see :meth:`run` for the
        self-healing path): data_fn + train_batch."""
        batch = self.data_fn(self.engine.global_steps)
        loss = float(self.engine.train_batch(batch=batch))
        self.losses.append(loss)
        obs = get_session()
        if obs.enabled:
            obs.registry.gauge("rlhf/loss",
                               help="last RLHF objective value").set(loss)
        return loss

    def train(self, iterations: int) -> List[float]:
        for _ in range(int(iterations)):
            self.step()
        # close the final train-phase bracket so phase shares add up
        if self._last_prepare_end is not None:
            self._phase_s["train"] += self.clock() - self._last_prepare_end
            self._last_prepare_end = None
            self._publish(get_session())
        return list(self.losses)

    # -- the supervised path -----------------------------------------------
    def run(self, iterations: int, save_dir: str,
            engine_factory: Optional[Callable[[], Any]] = None,
            injector: Optional[Any] = None) -> Dict[str, Any]:
        """Run ``iterations`` RLHF steps under the PR-9
        :class:`TrainingSession` policy (``config.resilience``): a
        ``NumericsTrip`` rolls back to the last verified checkpoint and
        re-calls :meth:`data_fn` — the restored weights plus the derived
        seeds re-produce the failed iteration's rollouts deterministically
        before the step replays. ``engine_factory`` (for hang
        soft-restarts) defaults to reusing this trainer's engine."""
        from ..runtime.session import TrainingSession

        session = TrainingSession(
            engine_factory or (lambda: self.engine), self.data_fn,
            total_steps=int(iterations), save_dir=save_dir,
            resilience=self.engine.config.resilience, injector=injector,
            clock=self.clock,
            on_step=lambda step, loss: self.losses.append(loss))
        summary = session.run()
        if self._last_prepare_end is not None:
            self._phase_s["train"] += self.clock() - self._last_prepare_end
            self._last_prepare_end = None
            self._publish(get_session())
        summary["manifests"] = len(self.manifests)
        summary["phase_seconds"] = dict(self._phase_s)
        return summary

    # -- telemetry ---------------------------------------------------------
    def _publish(self, obs, iteration: bool = False) -> None:
        if not obs.enabled:
            return
        reg = obs.registry
        if iteration:
            reg.counter(
                "rlhf/iterations",
                help="RLHF generate→score→train→flip iterations").inc()
        for phase, secs in self._phase_s.items():
            g = reg.gauge("rlhf/phase_seconds",
                          help="cumulative wall seconds per RLHF phase")
            g.set(secs, phase=phase)
        reg.counter("rlhf/tokens_trained",
                    help="prompt+response tokens fed to the RLHF train "
                         "step").inc(self._tokens_trained
                                     - getattr(self, "_pub_trained", 0))
        self._pub_trained = self._tokens_trained
        if self._reward_n:
            reg.gauge("rlhf/reward_mean",
                      help="mean reward over all scored candidates").set(
                          self._reward_sum / self._reward_n)
        log_dist(
            "rlhf: iter done — phases "
            + " ".join(f"{k}={v:.2f}s" for k, v in self._phase_s.items()))
