"""Rollout collection through the serving stack + deterministic replay.

The RLHF rollout phase is just serving traffic with a derived seed
discipline:

* a batch of prompts sharing a system prompt rides **prefix sharing** —
  the shared head prefills once and every later prompt maps the cached
  blocks into its table (``serving/paged_kv.PrefixCache``);
* each prompt's candidate group of ``n`` samples is ONE prefill plus
  ``fork(n)`` COW siblings (``submit(n=...)``) — GRPO/best-of-n sampling
  is literally ``n-1`` block-table increfs;
* the policy's own **n-gram drafter** (``speculative.mode='ngram'``)
  speculates over its rollouts with zero extra weights;
* per-request seeds derive from ``(iteration, prompt_index,
  sample_index)`` (:func:`rollout_seed`), and the serving layer's sampling
  contract — draws depend only on (engine seed, request seed,
  output-token index) — makes every rollout **bit-exactly replayable**
  from the manifest alone: :func:`replay` reproduces identical token
  streams across preemption/recompute and with speculation toggled either
  way.

The :class:`RolloutManifest` is the replay unit: prompts, per-sample
seeds, sampling knobs and the recorded streams, JSON-serializable. It is
also the resilience contract — a NaN→rollback recovery re-runs
``data_fn(step)``, which re-collects the same iteration's rollouts from
the restored (bit-identical) weights and seeds, reproducing the manifest
exactly (tests/unit/test_rlhf.py).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import get_session

__all__ = ["rollout_seed", "RolloutSample", "RolloutBatch",
           "RolloutManifest", "RolloutCollector", "ReplayMismatch",
           "replay", "SEED_STRIDE"]

# seeds within one prompt's candidate group are consecutive (submit(n=...)
# gives sibling i seed base+i), so groups are strided apart; group_n is
# validated against this bound
SEED_STRIDE = 4096


def rollout_seed(iteration: int, prompt_index: int,
                 sample_index: int = 0) -> int:
    """The documented, replay-stable seed derivation: sample ``s`` of
    prompt ``p`` in iteration ``i`` samples with
    ``((i * 1_000_003 + p) * SEED_STRIDE + s) mod 2^30``. Consecutive
    sample indices are consecutive seeds, which is exactly the sibling
    seed rule of ``ServingEngine.submit(n=...)`` — a forked group and
    ``n`` solo submissions draw from identical streams."""
    if not 0 <= sample_index < SEED_STRIDE:
        raise ValueError(f"sample_index must be in [0, {SEED_STRIDE}), "
                         f"got {sample_index}")
    return ((iteration * 1_000_003 + prompt_index) * SEED_STRIDE
            + sample_index) & 0x3FFFFFFF


class ReplayMismatch(AssertionError):
    """A replayed rollout diverged from its manifest — the determinism
    contract is broken (weight drift, engine-seed mismatch, or a sampling
    bug)."""


@dataclasses.dataclass
class RolloutSample:
    """One generated candidate: ``tokens`` is the response stream only
    (the prompt is shared group-wide)."""

    prompt_index: int
    sample_index: int
    seed: int
    prompt: np.ndarray
    tokens: List[int]

    @property
    def sequence(self) -> np.ndarray:
        """prompt + response, the scoring/training token sequence."""
        return np.concatenate([np.asarray(self.prompt, np.int32),
                               np.asarray(self.tokens, np.int32)])


@dataclasses.dataclass
class RolloutBatch:
    """One iteration's rollouts: ``groups[p][s]`` is sample ``s`` of
    prompt ``p``, plus the collection-side stats the metrics/report layer
    surfaces."""

    iteration: int
    groups: List[List[RolloutSample]]
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def samples(self) -> List[RolloutSample]:
        return [s for g in self.groups for s in g]


@dataclasses.dataclass
class RolloutManifest:
    """Everything needed to re-produce an iteration's rollouts bit-exactly
    — and the recorded streams to verify against. ``engine_seed`` is the
    serving engine's sampling-stream seed (``ServingConfig.seed``);
    ``spec_mode`` records how the streams were produced (informational:
    the streams are identical either way — that IS the contract)."""

    iteration: int
    group_n: int
    engine_seed: int
    temperature: float
    top_k: int
    top_p: float
    max_new_tokens: int
    eos_token_id: Optional[int]
    prompts: List[List[int]]
    seeds: List[List[int]]            # [prompt][sample]
    streams: List[List[List[int]]]    # [prompt][sample][token]
    spec_mode: str = "off"
    version: int = 1
    # [prompt][sample] request-trace ids (observability/reqtrace.py), when
    # the recording run had request_tracing on — a replayed/diverged sample
    # is cross-referencable against its original causal timeline. Empty
    # (the default) when tracing was off; old manifests load unchanged.
    trace_ids: List[List[Optional[str]]] = dataclasses.field(
        default_factory=list)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, text: str) -> "RolloutManifest":
        return cls(**json.loads(text))

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "RolloutManifest":
        with open(path, "r", encoding="utf-8") as fh:
            return cls.from_json(fh.read())


class RolloutCollector:
    """Drives one iteration's generation through a ``ServingEngine``.

    ``engine`` must hold the CURRENT policy weights (the hybrid engine's
    ``flip_to_serving()`` contract). Publishes ``rlhf/*`` rollout metrics
    and returns ``(RolloutBatch, RolloutManifest)``."""

    def __init__(self, engine, group_n: int = 4, temperature: float = 0.7,
                 top_k: int = 0, top_p: float = 1.0,
                 max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        if not 1 <= group_n < SEED_STRIDE:
            raise ValueError(f"group_n must be in [1, {SEED_STRIDE}), "
                             f"got {group_n}")
        self.engine = engine
        self.group_n = int(group_n)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.clock = clock

    def collect(self, prompts: Sequence[np.ndarray], iteration: int
                ) -> Tuple[RolloutBatch, RolloutManifest]:
        eng = self.engine
        if eng.in_flight():
            raise RuntimeError(
                f"rollout collect with {eng.in_flight()} foreign requests "
                "in flight — the collector owns the engine for the phase")
        n = self.group_n
        pre_chunks = eng.prefill_chunks_run
        pre_tokens = eng.prefill_tokens_run
        pre_prop, pre_acc = eng._spec_proposed, eng._spec_accepted
        t0 = self.clock()
        handle_groups = []
        for p_idx, prompt in enumerate(prompts):
            hs = eng.submit(np.asarray(prompt, np.int32),
                            max_new_tokens=self.max_new_tokens,
                            temperature=self.temperature, top_k=self.top_k,
                            top_p=self.top_p,
                            eos_token_id=self.eos_token_id,
                            seed=rollout_seed(iteration, p_idx), n=n)
            handle_groups.append([hs] if n == 1 else hs)
        eng.run()
        wall = self.clock() - t0
        groups: List[List[RolloutSample]] = []
        for p_idx, (prompt, hs) in enumerate(zip(prompts, handle_groups)):
            groups.append([
                RolloutSample(prompt_index=p_idx, sample_index=s_idx,
                              seed=rollout_seed(iteration, p_idx, s_idx),
                              prompt=np.asarray(prompt, np.int32),
                              tokens=[int(t) for t in h.result()])
                for s_idx, h in enumerate(hs)])
        gen_tokens = sum(len(s.tokens) for g in groups for s in g)
        prefill_tokens = eng.prefill_tokens_run - pre_tokens
        # every sample's prompt would prefill in full without fork/prefix
        # reuse; the ratio is the fraction of that work the sharing paths
        # absorbed (n-1 forked siblings + prefix-cache hits)
        submitted = sum(int(np.asarray(p).size) for p in prompts) * n
        reuse = 1.0 - prefill_tokens / max(submitted, 1)
        proposed = eng._spec_proposed - pre_prop
        accepted = eng._spec_accepted - pre_acc
        stats = {
            "wall_s": wall,
            "generated_tokens": gen_tokens,
            "prefill_chunks": eng.prefill_chunks_run - pre_chunks,
            "prefill_tokens": prefill_tokens,
            "submitted_prompt_tokens": submitted,
            "fork_reuse_ratio": reuse,
            "spec_proposed": proposed,
            "spec_accepted": accepted,
            "spec_acceptance_rate": (accepted / proposed if proposed
                                     else None),
        }
        self._publish(stats, len(list(prompts)))
        manifest = RolloutManifest(
            iteration=int(iteration), group_n=n,
            engine_seed=int(eng.config.seed),
            temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p, max_new_tokens=self.max_new_tokens,
            eos_token_id=self.eos_token_id,
            prompts=[[int(t) for t in np.asarray(p).reshape(-1)]
                     for p in prompts],
            seeds=[[s.seed for s in g] for g in groups],
            streams=[[list(s.tokens) for s in g] for g in groups],
            spec_mode=("off" if eng._drafter is None or eng.spec_suspended
                       else eng.config.speculative.mode),
            trace_ids=[[(h._req.trace.trace_id
                         if getattr(h._req, "trace", None) is not None
                         else None) for h in hs]
                       for hs in handle_groups]
            if get_session().reqtrace is not None else [])
        return RolloutBatch(iteration=int(iteration), groups=groups,
                            stats=stats), manifest

    @staticmethod
    def _publish(stats: Dict[str, Any], n_prompts: int) -> None:
        obs = get_session()
        if not obs.enabled:
            return
        reg = obs.registry
        reg.counter("rlhf/rollout_tokens",
                    help="tokens generated by rollout phases").inc(
                        stats["generated_tokens"])
        reg.counter("rlhf/rollout_prompts",
                    help="prompts rolled out").inc(n_prompts)
        reg.gauge("rlhf/fork_reuse_ratio",
                  help="fraction of per-sample prompt prefill absorbed by "
                       "fork(n) + prefix sharing").set(
                      stats["fork_reuse_ratio"])
        if stats["spec_acceptance_rate"] is not None:
            reg.gauge("rlhf/spec_acceptance_rate",
                      help="rollout draft-token acceptance rate").set(
                          stats["spec_acceptance_rate"])


def replay(manifest: RolloutManifest, engine, verify: bool = True,
           ) -> List[List[List[int]]]:
    """Re-produce a manifest's token streams from the manifest alone.

    ``engine`` must hold the same weights and engine seed the recording
    run used (the iteration's policy — after a rollback, the restored
    checkpoint). Each sample resubmits INDIVIDUALLY with its recorded
    seed — deliberately not through ``submit(n=...)`` — so a successful
    verify also witnesses the fork-vs-solo bit-identity. Speculation may
    be on or off, toggled, or differently configured: the serving layer's
    sampling contract makes the streams identical, and ``verify=True``
    asserts exactly that (raising :class:`ReplayMismatch` on the first
    divergence, publishing ``rlhf/replay_verifications`` on success)."""
    if int(engine.config.seed) != manifest.engine_seed:
        raise ReplayMismatch(
            f"engine seed {engine.config.seed} != manifest engine seed "
            f"{manifest.engine_seed} — the sampling streams cannot match")
    handles = []
    for p_idx, prompt in enumerate(manifest.prompts):
        row = []
        for s_idx in range(manifest.group_n):
            row.append(engine.submit(
                np.asarray(prompt, np.int32),
                max_new_tokens=manifest.max_new_tokens,
                temperature=manifest.temperature, top_k=manifest.top_k,
                top_p=manifest.top_p, eos_token_id=manifest.eos_token_id,
                seed=manifest.seeds[p_idx][s_idx]))
        handles.append(row)
    engine.run()
    streams = [[[int(t) for t in h.result()] for h in row]
               for row in handles]
    if verify:
        for p_idx, (got_row, want_row) in enumerate(
                zip(streams, manifest.streams)):
            for s_idx, (got, want) in enumerate(zip(got_row, want_row)):
                if got != want:
                    raise ReplayMismatch(
                        f"iteration {manifest.iteration} prompt {p_idx} "
                        f"sample {s_idx}: replayed stream diverged "
                        f"(got {got[:8]}..., recorded {want[:8]}...)")
        obs = get_session()
        if obs.enabled:
            obs.registry.counter(
                "rlhf/replay_verifications",
                help="manifests replayed and verified bit-exact").inc()
    return streams
