"""RLHF post-training over the hybrid engine v2 — the DeepSpeed-Chat
substrate (PAPER.md layer 9) rebuilt on this repo's serving stack.

One weight set, one paged arena: the training engine and the serving
engine share parameters through a single resharding flip
(``runtime/hybrid_engine.py``), rollouts run as serving traffic
(continuous batching, prefix sharing over shared system prompts,
``fork(n)`` candidate groups, the policy's own n-gram drafter), scoring
is two more serving passes over the same arena, and the bit-stable
sampling contract makes every rollout replayable from its manifest —
including across a NaN→rollback recovery. See docs/rlhf.md.

    rollout.py   RolloutCollector + RolloutManifest + replay()
    loss.py      PPO-clip / GRPO objective as a drop-in Model.loss_fn
    trainer.py   the generate → score → train → flip loop, with
                 TrainingSession resilience

Entry point::

    engine = deepspeed_tpu.rlhf.init_rlhf(
        "tiny", config={"train_micro_batch_size_per_gpu": 8,
                        "rlhf": {"algo": "grpo", "group_n": 4}},
        serving_config={"max_seqs": 8, "max_model_len": 256})
    trainer = RLHFTrainer(engine, prompt_fn, reward_fn)
    trainer.run(iterations=100, save_dir="ckpt/")
"""

from .loss import group_advantages, rlhf_model, whitened_advantages
from .rollout import (ReplayMismatch, RolloutBatch, RolloutCollector,
                      RolloutManifest, RolloutSample, replay, rollout_seed)
from .trainer import RLHFTrainer

__all__ = ["init_rlhf", "RLHFTrainer", "RolloutCollector",
           "RolloutManifest", "RolloutBatch", "RolloutSample", "replay",
           "rollout_seed", "ReplayMismatch", "rlhf_model",
           "group_advantages", "whitened_advantages"]


def init_rlhf(model=None, config=None, serving_config=None, mesh=None,
              inference_mesh: str = "auto", max_out_tokens: int = 0,
              **hybrid_kwargs):
    """Build a :class:`HybridEngine` whose model carries the RLHF
    objective (:func:`rlhf_model` wraps its ``loss_fn``) and whose rollout
    side is sized by ``serving_config``. ``model`` is a preset name or a
    ``Model``; ``config`` the usual config tree (the ``rlhf`` block
    selects the algorithm). ``max_out_tokens`` defaults to the serving
    ``max_model_len`` so the offline generate() arena matches the rollout
    budget."""
    from ..config.config import ServingConfig, load_config
    from ..runtime.hybrid_engine import HybridEngine

    cfg = load_config(config)
    cfg.rlhf.validate()
    if isinstance(serving_config, dict):
        serving_config = ServingConfig.from_dict(serving_config)
    scfg = serving_config or ServingConfig()
    if isinstance(model, str):
        import jax.numpy as jnp

        from ..models import create_model

        # build the preset in the config's precision so model-internal
        # dtypes (KV writes, arena) agree with the engine's compute dtype
        dtype = {"bfloat16": jnp.bfloat16, "float16": jnp.float16,
                 "float32": jnp.float32}[cfg.precision_dtype]
        model = create_model(model, dtype=dtype)
    wrapped = rlhf_model(model, cfg.rlhf)
    return HybridEngine(
        model=wrapped, config=cfg, mesh=mesh,
        serving_config=scfg, inference_mesh=inference_mesh,
        max_out_tokens=max_out_tokens or scfg.max_model_len,
        **hybrid_kwargs)
