"""InferenceEngine — analog of ``deepspeed.init_inference`` →
``InferenceEngine`` (reference inference/engine.py:89, deepspeed/__init__.py:260).

The reference engine rewrites an HF torch module in place (injection policies
→ fused CUDA modules), builds an mp group, and manages a global KV workspace.
Here the same capabilities are jit programs over a param pytree:

  model rewrite     → family state-dict import (hf_import.py) + the platform
                      kernel registry (flash/decode Pallas kernels resolve per
                      backend — the "kernel inject" analog, zero surgery)
  mp/tp group       → mesh 'model' axis; params sharded by logical-axis rules
  KV workspace      → kv_cache.py arena pytree threaded through jit steps
  CUDA-graph        → jit cache discipline: static shapes (prompt buckets,
                      fixed arena), one compiled prefill + one decode program

``generate`` = jitted prefill (the TTFT path) + ``lax.scan`` decode loop with
greedy/temperature/top-k sampling, early-EOS masking.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.core import Model, cast_floating
from ..models.presets import create_model
from ..observability import get_session
from ..parallel import mesh as mesh_mod
from ..utils.logging import log_dist, logger
from . import kv_cache
from .hf_import import import_hf_model, import_hf_state_dict, load_flat_weights_tree


@dataclasses.dataclass
class InferenceConfig:
    """Reference DeepSpeedInferenceConfig (inference/config.py) surface,
    TPU-shaped: bf16 is the native dtype (the reference explicitly rejects
    bf16 — a CUDA-kernel limitation that does not apply here)."""

    dtype: Any = jnp.bfloat16
    tensor_parallel: int = 1           # tp_size
    expert_parallel: int = 1           # ep_size — MoE models: expert banks
    #   sharded over the mesh 'expert' axis; gate+dispatch run in the decode
    #   path and XLA lowers the (E, C, H) exchange to the all-to-all the
    #   reference's DeepSpeedMoEInference issues explicitly
    #   (moe_inference.py:160, inference/engine.py:274 _create_ep_parallel_group)
    max_out_tokens: int = 1024         # KV arena length (prompt + generated)
    replace_with_kernel_inject: bool = True   # platform Pallas kernels
    checkpoint: Optional[str] = None   # flat-npz path (save_16bit_model output)
    seed: int = 0
    quantize_bits: Optional[int] = None  # 8/4 => weight-only int8/int4
    #   storage (reference int8/int4 kernel-injection + groupwise quantizer
    #   kernels): matmul weights quantized per output channel (int8) or per
    #   (group, channel) with nibble packing (int4), dequant fused into the
    #   GEMM — halves/quarters decode-phase HBM weight traffic.
    #   dtype='int8'/'int4' sets this.
    quantize_groups: Optional[int] = None  # int4 group size along K (None =>
    #   one group per output channel; reference quantization_settings groups)
    quantize_activations: bool = False  # W8A8 decode: per-row dynamic int8
    #   activation quantization feeds the MXU's native s8xs8 path — removes
    #   the weight-convert VPU bottleneck of the weight-only kernel (the
    #   reference's int8 path also quantizes activations,
    #   pt_binding.cpp quantize_activation). dtype='w8a8' sets this.
    compile_cache: bool = True         # persistent XLA compile cache
    #   (utils/compile_cache.py); DSTPU_COMPILE_CACHE overrides dir/disables
    prompt_bucket: int = 64            # prompt-length compile bucket: prompts
    #   pad up to a multiple of this, bounding the number of distinct
    #   compiled prefill programs. The serving layer pins it to its KV
    #   block_size so a bucketed prompt never reserves arena blocks the
    #   true prompt can't use (ServingEngine does this at construction).

    def __post_init__(self):
        # dtype='int8' is storage quantization, not a compute dtype — the
        # normalisation lives here so the config-dict path can't slip a
        # string dtype into create_model/cast_floating (which would astype
        # the weights to int8 and silently destroy them)
        if self.dtype in ("int8", jnp.int8):
            self.quantize_bits = 8
            self.dtype = jnp.bfloat16
        elif self.dtype in ("w8a8",):
            self.quantize_bits = 8
            self.quantize_activations = True
            self.dtype = jnp.bfloat16
        elif self.dtype in ("w4a8",):
            self.quantize_bits = 4
            self.quantize_activations = True
            self.dtype = jnp.bfloat16
        elif self.dtype in ("int4",):
            self.quantize_bits = 4
            self.dtype = jnp.bfloat16
        elif isinstance(self.dtype, str):
            self.dtype = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                          "fp16": jnp.float16, "float16": jnp.float16,
                          "fp32": jnp.float32, "float32": jnp.float32,
                          }.get(self.dtype) or _reject_dtype(self.dtype)
        if self.quantize_bits not in (None, 4, 8):
            raise NotImplementedError(
                f"quantize_bits={self.quantize_bits}: 8 (per-channel) and "
                "4 (nibble-packed, groupwise) are supported")
        if self.quantize_groups is not None and self.quantize_bits != 4:
            raise ValueError("quantize_groups applies to int4 only")
        if self.prompt_bucket < 1:
            raise ValueError(f"prompt_bucket must be >= 1, got "
                             f"{self.prompt_bucket}")
        if self.quantize_activations and self.quantize_bits not in (4, 8):
            raise ValueError("quantize_activations (W8A8/W4A8) requires "
                             "int8 or int4 weights (dtype='w8a8'/'w4a8')")


def _reject_dtype(name: str):
    raise ValueError(f"unknown inference dtype '{name}' (use bf16/fp16/fp32 "
                     "or 'int8' for weight-only quantization)")


def _bucket(n: int, mult: int = 64) -> int:
    """Prompt-length bucket: bounds the number of distinct compiled prefill
    programs (the reference's CUDA-graph shape discipline)."""
    return max(mult, ((n + mult - 1) // mult) * mult)


def _sample(logits, rng, temperature: float, top_k: int,
            top_p: float = 1.0) -> jax.Array:
    """Greedy / temperature / top-k / top-p sampling — the ONE sampling
    rule, used for the first token and every decode step alike."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        # nucleus: keep the smallest prefix of descending-prob tokens whose
        # cumulative mass reaches top_p (the top-1 token always survives)
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs < top_p).at[..., 0].set(True)
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


class InferenceEngine:
    """Owns sharded params + the KV arena + compiled prefill/decode programs."""

    def __init__(self, model: Model, config: InferenceConfig,
                 params: Optional[Any] = None, mesh: Optional[Mesh] = None):
        if config.compile_cache:
            from ..utils.compile_cache import enable_compile_cache

            enable_compile_cache()
        self.model = model
        self.config = config
        if mesh is None:
            from ..config.config import ParallelConfig

            tp_req = max(1, config.tensor_parallel)
            ep_req = max(1, config.expert_parallel)
            mesh = mesh_mod.build_mesh(
                ParallelConfig(tensor_parallel_size=tp_req,
                               expert_parallel_size=ep_req,
                               data_parallel_size=ep_req),
                devices=jax.devices()[:tp_req * ep_req])
        self.mesh = mesh
        tp = int(self.mesh.shape[mesh_mod.MODEL_AXIS])
        ep = int(self.mesh.shape.get(mesh_mod.EXPERT_AXIS, 1))
        cfg = model.config
        if cfg is None:
            raise ValueError("model.config is required for inference (the "
                             "KV-cache arena is sized from it)")
        if cfg.num_kv_heads % max(tp, 1) != 0:
            raise ValueError(f"tensor_parallel={tp} must divide "
                             f"num_kv_heads={cfg.num_kv_heads}")
        if ep > 1:
            if cfg.moe_num_experts <= 0:
                raise ValueError(f"expert_parallel={ep} requires an MoE "
                                 "model (moe_num_experts > 0)")
            if cfg.moe_num_experts % ep != 0:
                raise ValueError(
                    f"expert_parallel={ep} must divide "
                    f"moe_num_experts={cfg.moe_num_experts}")
        if config.quantize_activations:
            # W8A8/W4A8 engage through the decode-kernel gate; a config
            # where the gate can never pass must not silently publish
            # weight-only numbers under the a8 label
            mode = "w8a8" if config.quantize_bits == 8 else "w4a8"
            wo = "int8" if config.quantize_bits == 8 else "int4"
            if tp > 1:
                raise NotImplementedError(
                    f"quantize_activations ({mode.upper()}) + "
                    "tensor_parallel > 1 is not supported — the s8xs8 "
                    f"decode kernel is single-device (weight-only {wo} "
                    "supports TP)")
            # per-site gate preview: int8 sites need K,N % 128; int4 packs
            # K/2, so its CONTRACTION dim must be % 256 (output dims stay
            # % 128)
            k_align = 128 if config.quantize_bits == 8 else 256
            H = cfg.hidden_size
            ND, F, V = (cfg.num_heads * cfg.head_dim, cfg.ffn_hidden_size,
                        cfg.vocab_size)
            sites = {"attn qkv": (H, ND), "attn out": (ND, H),
                     "mlp in": (H, F), "mlp out": (F, H)}
            if not cfg.tie_embeddings:
                sites["lm_head"] = (H, V)
            bad_sites = [name for name, (kd, nd) in sites.items()
                         if kd % k_align or nd % 128]
            if (config.quantize_bits == 4 and config.quantize_groups
                    and config.quantize_groups % 128):
                bad_sites = list(sites)
            if bad_sites:
                logger.warning(
                    f"{mode}: the s8xs8 kernel gate will not engage for "
                    f"site(s) {bad_sites} (K-alignment {k_align}, "
                    f"N-alignment 128"
                    f"{', groups ' + str(config.quantize_groups) if config.quantize_groups else ''}"
                    f") — those sites serve the weight-only {wo} path")
            cfg.a8_decode = True

        # the 'serving' policy from the rule registry: TP only, no fsdp axis
        # (reference inference shards qkv/mlp across the mp group,
        # replicating the rest); MoE expert banks additionally shard their
        # leading E dim over 'expert'
        self._param_shapes = jax.eval_shape(model.init,
                                            jax.random.PRNGKey(0))
        from ..parallel.rules import get_policy

        specs = get_policy("serving").param_specs(
            self._param_shapes, model.axes, expert_parallel=True)
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))

        if params is None:
            with mesh_mod.ambient(self.mesh):
                if config.quantize_bits:
                    # init + quantize in ONE program: XLA liveness frees each
                    # full-precision weight as its int8 replacement is
                    # produced — materialising the whole bf16 tree first
                    # OOMs at 13B on a 16GB chip
                    from ..models.transformer import quantize_model_weights

                    # shardings matter for BOTH tp>1 (sliced dense sites)
                    # and ep>1 (expert banks over the 'expert' axis) —
                    # gating on tp alone silently replicated MoE experts
                    q_sh = (self._quantized_shardings()
                            if tp > 1 or ep > 1 else None)
                    params = jax.jit(lambda key: quantize_model_weights(
                        cast_floating(model.init(key), config.dtype),
                        bits=config.quantize_bits,
                        group_size=config.quantize_groups),
                        out_shardings=q_sh)(
                            jax.random.PRNGKey(config.seed))
                else:
                    params = jax.jit(
                        lambda key: cast_floating(model.init(key), config.dtype),
                        out_shardings=self.param_shardings)(
                            jax.random.PRNGKey(config.seed))
        elif config.quantize_bits:
            # quantize BEFORE any tree-wide device_put: each host weight leaf
            # transfers, quantizes, and frees individually, so a model whose
            # full-precision weights exceed HBM (13B bf16 = 26GB on a 16GB
            # chip) still loads — only its int8 form ever resides on device
            from ..models.transformer import quantize_model_weights

            params = cast_floating(params, config.dtype)
            q_sh = (self._quantized_shardings()
                    if tp > 1 or ep > 1 else None)
            params = quantize_model_weights(params,
                                            bits=config.quantize_bits,
                                            donate=True,
                                            group_size=config.quantize_groups,
                                            shardings=q_sh)
            if q_sh is not None:
                # quantized leaves already landed sharded; this put only
                # moves the remaining dense leaves (and no-ops the rest)
                params = jax.tree.map(
                    lambda x, s: jax.device_put(jnp.asarray(x), s),
                    params, q_sh)
            else:
                params = jax.tree.map(jnp.asarray, params)  # host leaves
        else:
            params = cast_floating(params, config.dtype)
            params = jax.tree.map(
                lambda x, s: jax.device_put(np.asarray(x), s),
                params, self.param_shardings)
        self.params = params

        self._prefill_cache: Dict[Tuple, Any] = {}
        self._decode_cache: Dict[Tuple, Any] = {}
        # engine-owned KV arena, allocated once per batch size and donated
        # through prefill/decode each call (reference InferenceContext
        # allocates its workspace once, inference_context.h:49) — per-call
        # allocation is wasted HBM traffic at serving cadence
        self._arena: Dict[int, Any] = {}
        self._fwd = None
        self._generate_calls = 0   # observability step counter (watchdog)
        n = sum(int(p.size) for p in jax.tree.leaves(self.params))
        log_dist(f"inference engine ready: {n / 1e6:.1f}M params, tp={tp}, "
                 f"ep={ep}, "
                 f"dtype={jnp.dtype(config.dtype).name}, "
                 f"arena={config.max_out_tokens} tokens "
                 f"({kv_cache.cache_memory_bytes(cfg, 1, config.max_out_tokens, config.dtype) / 2**20:.0f}"
                 f" MiB/seq)")

    def _quantized_shardings(self) -> Any:
        """Sharding tree for the QUANTIZED params: each quantized site's
        packed weight inherits the dense weight's TP spec (same axis
        semantics; int4's packed K/2 keeps the K-axis placement) and its
        scales shard on the output-channel axis only — the reference's
        auto-TP slicing applied to the q8/scale pair."""
        from ..models.transformer import quantize_model_weights

        def one(spec_sh):
            spec = spec_sh.spec
            out_axis = spec[-1] if len(spec) else None
            return {
                "q8": spec_sh,            # placeholder keys; matched below
                "s": NamedSharding(self.mesh, P(*([None] * max(
                    len(spec) - 1, 1) + [out_axis]))),
            }

        # derive structure by quantizing the SHAPES already computed at init
        q_shapes = jax.eval_shape(
            lambda t: quantize_model_weights(
                t, bits=self.config.quantize_bits,
                group_size=self.config.quantize_groups), self._param_shapes)

        def walk(qnode, dense_sh):
            if isinstance(qnode, dict) and ("q8" in qnode or "q4" in qnode):
                key = "q8" if "q8" in qnode else "q4"
                built = one(dense_sh)
                return {key: built["q8"], "s": built["s"]}
            if isinstance(qnode, dict):
                return {k: walk(v, dense_sh[k]) for k, v in qnode.items()}
            return dense_sh

        return walk(q_shapes, self.param_shardings)

    # -- tpuaudit registration (tools/tpuaudit) ------------------------------
    def _audit_expected_collectives(self) -> frozenset:
        """Collectives the serving programs are allowed to contain: TP
        activation reductions/gathers, MoE dispatch all-to-alls. A
        single-device engine declares none — any collective in its program
        is a sharding bug."""
        exp: set = set()
        if int(self.mesh.shape[mesh_mod.MODEL_AXIS]) > 1:
            exp |= {"all-reduce", "all-gather"}
        if int(self.mesh.shape.get(mesh_mod.EXPERT_AXIS, 1)) > 1:
            exp |= {"all-to-all", "all-reduce", "all-gather"}
        return frozenset(exp)

    def register_audit_entries(self, batch_size: int = 1,
                               prompt_len: int = 64,
                               max_new_tokens: int = 8,
                               temperature: float = 0.0, top_k: int = 0,
                               top_p: float = 1.0,
                               eos_token_id: Optional[int] = None) -> list:
        """Register the prefill and decode programs with the tpuaudit
        auditor (``python -m tools.tpuaudit``) WITHOUT generating: the
        programs are built (jit-wrapped, untraced) and handed over with
        abstract arguments mirroring a ``generate`` call of this shape."""
        try:
            from tools.tpuaudit import registry as _audit  # noqa: F401 — probe
        except ImportError:
            return []
        names = []
        B, S_pad = batch_size, _bucket(prompt_len, self.config.prompt_bucket)
        key_p = (B, S_pad)
        if key_p not in self._prefill_cache:
            self._prefill_cache[key_p] = self._prefill_fn(S_pad)
        names.append(self._register_prefill_audit(B, S_pad))
        n_rest = max_new_tokens - 1
        if n_rest > 0:
            key_d = (B, n_rest, float(temperature), int(top_k), float(top_p),
                     eos_token_id, False)
            if key_d not in self._decode_cache:
                self._decode_cache[key_d] = self._decode_fn(
                    n_rest, temperature, top_k, top_p, eos_token_id)
            names.append(self._register_decode_audit(key_d))
        return [n for n in names if n]

    def _cache_sds(self, B: int):
        return jax.eval_shape(lambda: kv_cache.init_cache(
            self.model.config, B, self.config.max_out_tokens,
            self.config.dtype))

    def _params_sds(self):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), self.params)

    def _register_prefill_audit(self, B: int, S_pad: int) -> Optional[str]:
        try:
            from tools.tpuaudit.registry import (StaleEntryError,
                                                 register_entry_point)
        except ImportError:
            return None
        try:
            import weakref

            wself = weakref.ref(self)

            def build():
                # everything abstract is synthesized HERE, at audit time —
                # registration itself (which rides every first-shape
                # generate call) stays a dict insert, and only a weakref to
                # the engine is captured so a replaced engine's params/arena
                # are never pinned by the registry
                eng = wself()
                if eng is None:
                    raise StaleEntryError("inference/prefill: engine gone")
                T = eng.config.max_out_tokens
                args = (eng._params_sds(),
                        jax.ShapeDtypeStruct((B, S_pad), jnp.int32),
                        jax.ShapeDtypeStruct((B, T), jnp.int32),
                        eng._cache_sds(B))
                return eng._prefill_cache[(B, S_pad)], args, {}

            register_entry_point(
                "inference/prefill", build=build, donate_argnums=(3,),
                expected_collectives=self._audit_expected_collectives(),
                mesh=self.mesh,
                tags={"engine": "InferenceEngine", "batch": B,
                      "prompt_bucket": S_pad,
                      # prefill ingests the whole padded prompt per run
                      "tokens_per_step": B * S_pad,
                      "shard": self._shard_tag()})
            return "inference/prefill"
        except Exception:   # registration must never take serving down
            logger.warning("tpuaudit prefill registration failed",
                           exc_info=True)
            return None

    def _register_decode_audit(self, key_d: Tuple) -> Optional[str]:
        try:
            from tools.tpuaudit.registry import (StaleEntryError,
                                                 register_entry_point)
        except ImportError:
            return None
        try:
            import weakref

            B, n_rest = key_d[0], key_d[1]
            wself = weakref.ref(self)

            def build():
                eng = wself()
                if eng is None:
                    raise StaleEntryError("inference/decode: engine gone")
                args = (eng._params_sds(), eng._cache_sds(B),
                        jax.ShapeDtypeStruct((B, eng.config.max_out_tokens),
                                             jnp.int32),
                        jax.ShapeDtypeStruct((B,), jnp.int32),
                        jax.ShapeDtypeStruct((B,), jnp.int32),
                        jax.ShapeDtypeStruct((), jnp.float32),
                        jax.ShapeDtypeStruct((2,), jnp.uint32))
                return eng._decode_cache[key_d], args, {}

            register_entry_point(
                "inference/decode", build=build, donate_argnums=(1,),
                expected_collectives=self._audit_expected_collectives(),
                mesh=self.mesh,
                tags={"engine": "InferenceEngine", "batch": B,
                      "new_tokens": n_rest,
                      # one decode program emits n_rest tokens per row
                      "tokens_per_step": B * n_rest,
                      "shard": self._shard_tag()})
            return "inference/decode"
        except Exception:
            logger.warning("tpuaudit decode registration failed",
                           exc_info=True)
            return None

    def _shard_tag(self) -> dict:
        """tools/tpushard placement contract: the params argument follows
        the registry's 'serving' policy; every program consuming these
        weights (prefill↔decode, the ServingEngine programs over this
        engine) shares the 'serving' exchange group, so the analyzer
        cross-checks the chain's layouts."""
        from ..parallel.rules import shard_tag

        return shard_tag("serving", axes=self.model.axes, params_arg=0,
                         expert_parallel=True, group="serving")

    # -- plain forward (reference InferenceEngine.forward / module call) -----
    def forward(self, input_ids, attention_mask=None):
        """Full-sequence logits, no cache."""
        if self._fwd is None:
            self._fwd = jax.jit(lambda p, b: self.model.apply(p, b)[0])
        batch = {"input_ids": jnp.asarray(input_ids)}
        if attention_mask is not None:
            batch["attention_mask"] = jnp.asarray(attention_mask)
        with mesh_mod.ambient(self.mesh):
            return self._fwd(self.params, batch)

    __call__ = forward

    # -- generate ------------------------------------------------------------
    def _prefill_fn(self, S_pad: int):
        cfg = self.model.config
        from ..models.transformer import forward as model_forward

        def prefill(params, ids, mask, cache):
            logits, cache, _ = model_forward(params, ids, cfg,
                                             attention_mask=mask,
                                             cache=cache, start_pos=0)
            return logits, cache

        return jax.jit(prefill, donate_argnums=(3,))

    def _decode_fn(self, n_new: int, temperature: float, top_k: int,
                   top_p: float, eos_token_id: Optional[int],
                   ragged: bool = False):
        cfg = self.model.config
        T_max = self.config.max_out_tokens
        from ..models.transformer import forward as model_forward

        # RAGGED alibi batches need TRUE key positions in the bias — arena
        # columns equal positions for the right-padded prompt part, but
        # generated keys at column S+t sit at position len_b+t per row.
        # Uniform batches keep kpos=None (the column default is exact and
        # custom attention_impls without the kwarg keep working).
        use_kpos = ragged and cfg.position == "alibi"

        def decode(params, cache, valid, first_tok, lengths, s_width, rng):
            kpos = None
            if use_kpos:
                col = jnp.arange(T_max, dtype=jnp.float32)[None]
                shift = (s_width - lengths.astype(jnp.float32))[:, None]
                kpos = col - shift * (col >= s_width)

            def step(carry, rng):
                cache, valid, tok, pos, done = carry
                idx = cache["index"][0]
                # the incoming token becomes a valid key at ARENA column idx
                # (uniform across rows); its POSITION is per-row — a ragged
                # row's first decode token sits at its true prompt length,
                # not the padded array width
                valid = jax.lax.dynamic_update_slice(
                    valid, jnp.ones((valid.shape[0], 1), valid.dtype), (0, idx))
                logits, cache, _ = model_forward(
                    params, tok[:, None], cfg,
                    attention_mask=valid, cache=cache, start_pos=idx,
                    positions=pos[:, None], key_positions=kpos)
                nxt = _sample(logits[:, -1], rng, temperature, top_k, top_p)
                if eos_token_id is not None:
                    nxt = jnp.where(done, eos_token_id, nxt)
                    done = done | (nxt == eos_token_id)
                return (cache, valid, nxt, pos + 1, done), nxt

            done = jnp.zeros(first_tok.shape, bool)
            rngs = jax.random.split(rng, n_new)
            (cache, valid, _, _, _), toks = jax.lax.scan(
                step, (cache, valid, first_tok, lengths, done), rngs)
            return jnp.moveaxis(toks, 0, 1), cache  # (B, n_new)

        return jax.jit(decode, donate_argnums=(1,))

    def generate(self, input_ids, attention_mask=None, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 return_ttft: bool = False):
        """Unhandled-exception guard around :meth:`_generate`: dump the
        flight record (ring + stacks + open spans) before the exception
        unwinds — a no-op without an enabled recorder. See ``_generate``
        for the generation semantics."""
        try:
            return self._generate(
                input_ids, attention_mask=attention_mask,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
                seed=seed, return_ttft=return_ttft)
        except Exception as e:
            get_session().crash_dump("generate-exception", exc=e,
                                     call=self._generate_calls)
            raise

    def _generate(self, input_ids, attention_mask=None, max_new_tokens: int = 32,
                  temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
                  eos_token_id: Optional[int] = None, seed: int = 0,
                  return_ttft: bool = False):
        """Prompt ids (B, S) → generated ids (B, max_new_tokens).

        Ragged prompts: pass ``attention_mask`` (B, S); prompts are treated
        as right-padded. Decoded tokens take each row's TRUE next positions
        (len_b, len_b+1, ...) — and alibi models bias keys by their true
        per-row positions too — so batched ragged generation matches
        serving each prompt alone, BLOOM included.
        ``return_ttft``: also return wall seconds to first token (prefill)."""
        cfg = self.model.config
        ids = jnp.asarray(np.asarray(input_ids), jnp.int32)
        B, S = ids.shape
        S_pad = _bucket(S, self.config.prompt_bucket)
        T_max = self.config.max_out_tokens
        if S_pad + max_new_tokens > T_max:
            raise ValueError(
                f"prompt ({S_pad} padded) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_out_tokens={T_max} — raise InferenceConfig."
                f"max_out_tokens (the reference raises the same in "
                f"inference_context.h workspace sizing)")
        mask = (jnp.ones((B, S), jnp.int32) if attention_mask is None
                else jnp.asarray(np.asarray(attention_mask), jnp.int32))
        ids_pad = jnp.pad(ids, ((0, 0), (0, S_pad - S)))
        # valid-key mask over the whole arena, prompt part filled
        valid = jnp.zeros((B, T_max), jnp.int32)
        valid = valid.at[:, :S].set(mask)

        key_p = (B, S_pad)
        if key_p not in self._prefill_cache:
            self._prefill_cache[key_p] = self._prefill_fn(S_pad)
            self._register_prefill_audit(B, S_pad)
        n_rest = max_new_tokens - 1
        ragged = attention_mask is not None and bool(
            np.any(np.asarray(mask).sum(-1) != S))
        key_d = (B, n_rest, float(temperature), int(top_k), float(top_p),
                 eos_token_id, ragged)
        if n_rest > 0 and key_d not in self._decode_cache:
            self._decode_cache[key_d] = self._decode_fn(
                n_rest, temperature, top_k, top_p, eos_token_id,
                ragged=ragged)
            self._register_decode_audit(key_d)

        with mesh_mod.ambient(self.mesh):
            cache = self._arena.pop(B, None)
            # single-workspace policy (reference InferenceContext): a batch
            # size change frees the old arena instead of pinning one arena
            # per B seen over the process lifetime
            self._arena.clear()
            if cache is None:
                cache = kv_cache.init_cache(cfg, B, T_max, self.config.dtype)
            else:
                # reuse the engine-owned arena: reset the write cursor; the
                # stale keys stay masked by `valid` and are overwritten as
                # prefill/decode proceed
                cache = {**cache, "index": jnp.zeros_like(cache["index"])}
            # TTFT through the span tracer: the span brackets prefill +
            # first-token sampling, and the explicit block_until_ready is
            # the async-dispatch fence that makes the wall-clock real (the
            # tpulint wallclock-timing-without-sync contract). A disabled
            # tracer still measures, so return_ttft works without telemetry.
            obs = get_session()
            prefill_span = obs.span("inference/prefill", sync=False,
                                    batch=B, prompt_tokens=int(S))
            with prefill_span:
                logits, cache = self._prefill_cache[key_p](
                    self.params, ids_pad, valid, cache)
                # rewind the write cursor from the padded to the true prompt
                # length: decoded tokens must take positions S, S+1, ... — the
                # junk keys prefill wrote in the padding slots stay masked and
                # get overwritten as decoding proceeds
                cache = {**cache, "index": jnp.full_like(cache["index"], S)}
                lengths = mask.sum(-1)
                last = jnp.take_along_axis(
                    logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
                rng, r_first = jax.random.split(jax.random.PRNGKey(seed))
                first = _sample(last, r_first, temperature, top_k, top_p)
                first = jax.block_until_ready(first)
            ttft = prefill_span.duration_s
            if n_rest == 0:
                out = first[:, None]
            else:
                decode_span = obs.span("inference/decode",
                                       sync=True, batch=B,
                                       new_tokens=int(n_rest))
                with decode_span:
                    rest, cache = self._decode_cache[key_d](
                        self.params, cache, valid, first, lengths,
                        jnp.float32(S), rng)
                    out = jnp.concatenate([first[:, None], rest], axis=1)
                # publish only when the span actually synced (a disabled or
                # rank-gated tracer hands back a non-syncing span): an
                # unfenced duration times the enqueue, not the decode
                if decode_span.sync and decode_span.duration_s > 0:
                    obs.registry.gauge(
                        "inference/decode_tokens_per_sec").set(
                            B * n_rest / decode_span.duration_s)
            self._arena[B] = cache
            if obs.enabled:
                obs.registry.histogram(
                    "inference/ttft_ms",
                    help="prefill + first token wall ms").observe(
                        ttft * 1e3, batch=B)
                obs.registry.gauge(
                    "inference/kv_cache_occupancy",
                    help="fraction of the KV arena holding live tokens"
                ).set((S + max_new_tokens) / T_max, batch=B)
                obs.note_step(self._generate_calls)
                obs.maybe_record_memory(self._generate_calls)
                self._generate_calls += 1
        return (out, ttft) if return_ttft else out


# ---------------------------------------------------------------------------


def init_inference(model=None, config=None, tensor_parallel: Optional[int] = None,
                   dtype=None, max_out_tokens: Optional[int] = None,
                   checkpoint: Optional[str] = None, hf_model=None,
                   hf_state_dict=None, mesh: Optional[Mesh] = None,
                   replace_with_kernel_inject: bool = True,
                   expert_parallel: Optional[int] = None,
                   **model_overrides) -> InferenceEngine:
    """Analog of ``deepspeed.init_inference`` (reference __init__.py:260).

    ``model``: a ``Model`` bundle or a preset name (e.g. "bloom-7b",
    "llama-7b" — the per-architecture injection-policy registry analog).
    Weights: ``hf_model`` / ``hf_state_dict`` (HF import + TP sharding =
    auto-TP), ``checkpoint`` (flat npz from save_16bit_model), else random.
    """
    if isinstance(config, dict):
        config = InferenceConfig(**config)
    cfg = config or InferenceConfig()
    if tensor_parallel is not None:
        cfg.tensor_parallel = int(tensor_parallel)
    if expert_parallel is not None:
        cfg.expert_parallel = int(expert_parallel)
    if dtype is not None:
        # normalisation (incl. 'int8' → weight-only quantization) happens in
        # InferenceConfig.__post_init__ — rebuild so it applies
        cfg.dtype = dtype
        cfg.__post_init__()
    if max_out_tokens is not None:
        cfg.max_out_tokens = int(max_out_tokens)
    cfg.replace_with_kernel_inject = replace_with_kernel_inject
    if checkpoint is not None:
        cfg.checkpoint = checkpoint

    family = None
    if isinstance(model, str):
        from ..models.presets import _SIZES

        family = (_SIZES[model]["family"] if model in _SIZES else model)
        model = create_model(model, dtype=cfg.dtype,
                             max_seq_len=max(cfg.max_out_tokens, 128),
                             **model_overrides)
    if model is None:
        raise ValueError("model is required: a Model bundle or preset name")

    params = None
    if hf_model is not None:
        params = import_hf_model(hf_model, model.config,
                                 family or model.name)
    elif hf_state_dict is not None:
        params = import_hf_state_dict(hf_state_dict, model.config,
                                      family or model.name)
    elif cfg.checkpoint is not None:
        if cfg.checkpoint.startswith("megatron:"):
            # Megatron-LM mp_rank_XX checkpoint dir: TP shards merged into
            # the logical layout (the MegatronSDLoader analog,
            # inference/megatron_import.py); target TP resharding then
            # falls out of device_put like every other load
            from .megatron_import import load_megatron_checkpoint

            params = load_megatron_checkpoint(
                cfg.checkpoint[len("megatron:"):], model.config)
        else:
            params = load_flat_weights_tree(cfg.checkpoint)
    return InferenceEngine(model, cfg, params=params, mesh=mesh)
