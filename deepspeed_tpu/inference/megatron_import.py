"""Megatron-LM checkpoint import: TP-shard merge into the logical layout.

Reference: ``runtime/state_dict_factory.py:21`` (``MegatronSDLoader``) — the
reference loads ``mp_rank_XX`` shards and merges/splits them to the target
TP degree, with checkpoint-version-dependent query_key_value orderings
(``merge_query_key_value`` / ``split_query_key_value``, :305-404). Here the
merge produces the FULL logical-axis param pytree once; any target TP/ZeRO
sharding then falls out of ``device_put`` with the plan's NamedShardings
(reshard-on-load by construction), so the reference's explicit re-split
path dissolves.

Layout facts encoded below (Megatron-LM GPT-2 ``language_model`` trees):
  word_embeddings.weight        (V/tp, H)  vocab-split rows   → concat dim 0
  position_embeddings.weight    (S, H)     replicated
  attention.query_key_value     (3H/tp, H) column-parallel    → see versions
  attention.dense               (H, H/tp)  row-parallel       → concat dim 1
  mlp.dense_h_to_4h             (4F'/tp..) column-parallel    → concat dim 0
  mlp.dense_4h_to_h             (H, F/tp)  row-parallel       → concat dim 1
  layernorms                    replicated

query_key_value orderings (reference ``sd_loader`` ckpt_ver handling):
  version 0    : per-head interleave — rows are [q_h0 k_h0 v_h0 q_h1 ...]
  version >= 2 : per-partition blocks — rows are [q_part; k_part; v_part]
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional

import numpy as np


def _np(x) -> np.ndarray:
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    return np.asarray(x)


def _split_qkv(w: np.ndarray, num_heads_part: int, head_dim: int,
               version: float):
    """One rank's fused query_key_value rows → (q, k, v) row-blocks."""
    rows = w.shape[0]
    assert rows == 3 * num_heads_part * head_dim, (
        f"qkv shard rows {rows} != 3*{num_heads_part}*{head_dim}")
    if version >= 2.0:
        q, k, v = np.split(w, 3, axis=0)
        return q, k, v
    # version 0: (np, 3, hn) per-head interleave
    per = w.reshape(num_heads_part, 3, head_dim, *w.shape[1:])
    return (per[:, 0].reshape(-1, *w.shape[1:]),
            per[:, 1].reshape(-1, *w.shape[1:]),
            per[:, 2].reshape(-1, *w.shape[1:]))


def merge_megatron_shards(shards: List[Dict[str, Any]], cfg, *,
                          checkpoint_version: float = 2.0
                          ) -> Dict[str, Any]:
    """Per-TP-rank Megatron ``language_model`` state dicts → deepspeed_tpu
    param pytree (numpy). ``cfg`` is the TransformerConfig the checkpoint
    describes (gpt2-family: layernorm + learned positions + gelu)."""
    tp = len(shards)
    H, N, D = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    if N % tp:
        raise ValueError(f"num_heads {N} not divisible by tp degree {tp}")
    npart = N // tp
    sds = [{k: _np(v) for k, v in s.items()} for s in shards]

    def emb_key(sd):
        for k in ("embedding.word_embeddings.weight",
                  "word_embeddings.weight"):
            if k in sd:
                return k
        raise KeyError("no word_embeddings in Megatron shard "
                       f"(keys: {sorted(sd)[:5]}...)")

    tokens = np.concatenate([sd[emb_key(sd)] for sd in sds], axis=0)
    if tokens.shape[0] < cfg.vocab_size:
        raise ValueError(f"merged vocab {tokens.shape[0]} < config "
                         f"vocab_size {cfg.vocab_size}")
    tokens = tokens[:cfg.vocab_size]        # drop Megatron padded rows

    pk = ("embedding.position_embeddings.weight"
          if "embedding.position_embeddings.weight" in sds[0]
          else "position_embeddings.weight")
    pos = sds[0][pk]

    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.layers.{i}."
        qs, ks, vs, qbs, kbs, vbs = [], [], [], [], [], []
        for sd in sds:
            q, k, v = _split_qkv(sd[p + "attention.query_key_value.weight"],
                                 npart, D, checkpoint_version)
            qs.append(q)
            ks.append(k)
            vs.append(v)
            qb, kb, vb = _split_qkv(
                sd[p + "attention.query_key_value.bias"][:, None],
                npart, D, checkpoint_version)
            qbs.append(qb[:, 0])
            kbs.append(kb[:, 0])
            vbs.append(vb[:, 0])
        sd0 = sds[0]
        layers.append({
            "ln1": {"scale": sd0[p + "input_layernorm.weight"],
                    "bias": sd0[p + "input_layernorm.bias"]},
            "ln2": {"scale": sd0[p + "post_attention_layernorm.weight"],
                    "bias": sd0[p + "post_attention_layernorm.bias"]},
            "attn": {
                # Megatron Linear stores (out, in); ours is (in, out)
                "wq": np.concatenate(qs, axis=0).T.copy(),
                "wk": np.concatenate(ks, axis=0).T.copy(),
                "wv": np.concatenate(vs, axis=0).T.copy(),
                "bq": np.concatenate(qbs, axis=0),
                "bk": np.concatenate(kbs, axis=0),
                "bv": np.concatenate(vbs, axis=0),
                "wo": np.concatenate(
                    [sd[p + "attention.dense.weight"] for sd in sds],
                    axis=1).T.copy(),
                "bo": sd0[p + "attention.dense.bias"],
            },
            "mlp": {
                "w_up": np.concatenate(
                    [sd[p + "mlp.dense_h_to_4h.weight"] for sd in sds],
                    axis=0).T.copy(),
                "b_up": np.concatenate(
                    [sd[p + "mlp.dense_h_to_4h.bias"] for sd in sds],
                    axis=0),
                "w_down": np.concatenate(
                    [sd[p + "mlp.dense_4h_to_h.weight"] for sd in sds],
                    axis=1).T.copy(),
                "b_down": sd0[p + "mlp.dense_4h_to_h.bias"],
            },
        })

    import jax

    tree = {
        "embed": {"tokens": tokens},
        "pos": pos,
        "layers": jax.tree.map(lambda *xs: np.stack(xs), *layers),
        "final_norm": {
            "scale": sds[0]["transformer.final_layernorm.weight"],
            "bias": sds[0]["transformer.final_layernorm.bias"]},
    }
    return tree


def _find_rank_files(ckpt_dir: str) -> List[str]:
    """mp_rank_XX[_YYY]/model_optim_rng.pt files in TP-rank order
    (reference get_checkpoint_files glob order)."""
    out = []
    for name in sorted(os.listdir(ckpt_dir)):
        if re.fullmatch(r"mp_rank_\d+_\d+", name):
            # mp_rank_XX_YYY = pipeline-parallel layout; collecting these as
            # duplicate TP ranks would die later on an opaque qkv assertion
            raise NotImplementedError(
                f"'{name}': pipeline-parallel Megatron checkpoints "
                "(mp_rank_XX_YYY) are not supported — merge the pipeline "
                "stages with Megatron's checkpoint tools first")
        m = re.fullmatch(r"mp_rank_(\d+)", name)
        if not m:
            continue
        for fname in ("model_optim_rng.pt", "model_rng.pt"):
            path = os.path.join(ckpt_dir, name, fname)
            if os.path.exists(path):
                out.append((int(m.group(1)), path))
                break
    return [p for _, p in sorted(out)]


def load_megatron_checkpoint(ckpt_dir: str, cfg,
                             checkpoint_version: Optional[float] = None
                             ) -> Dict[str, Any]:
    """Read a Megatron-LM checkpoint directory (``mp_rank_XX`` shards via
    torch.load) and merge to the full param pytree. The checkpoint version
    comes from the shard metadata unless overridden."""
    import torch

    files = _find_rank_files(ckpt_dir)
    if not files:
        raise FileNotFoundError(
            f"no mp_rank_*/model_optim_rng.pt under {ckpt_dir}")
    raw = [torch.load(f, map_location="cpu", weights_only=False)
           for f in files]
    if checkpoint_version is None:
        checkpoint_version = float(raw[0].get("checkpoint_version", 0))
    shards = []
    for r in raw:
        sd = r.get("model", r)
        sd = sd.get("language_model", sd)
        flat = {}
        # classic nesting: {'embedding': {...}, 'transformer': {...}} with
        # already-flat dotted keys inside each section
        for sec, tree in sd.items():
            if isinstance(tree, dict):
                for k, v in tree.items():
                    flat[f"{sec}.{k}" if not k.startswith(sec) else k] = v
            else:
                flat[sec] = tree
        shards.append(flat)
    return merge_megatron_shards(shards, cfg,
                                 checkpoint_version=checkpoint_version)
