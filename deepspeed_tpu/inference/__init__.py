"""Inference stack — TPU-native analog of the reference's
``deepspeed/inference`` + ``module_inject`` + ``model_implementations``:

  engine.py     InferenceEngine / init_inference (reference inference/engine.py:89)
  kv_cache.py   preallocated KV-cache arena (reference csrc/transformer/
                inference/includes/inference_context.h:49)
  hf_import.py  HF-checkpoint import + TP sharding rules — the policy-free
                auto-TP analog (reference module_inject/auto_tp.py)
"""

from .engine import InferenceConfig, InferenceEngine, init_inference  # noqa: F401
from .kv_cache import cache_memory_bytes, init_cache  # noqa: F401
