"""KV-cache arena: preallocated per-layer key/value buffers.

TPU-native analog of the reference's ``InferenceContext`` workspace
(csrc/transformer/inference/includes/inference_context.h:49) which sizes one
global GPU arena from ``max_out_tokens`` and hands each layer a slice, and of
the per-layer ``layer_past`` tracking in
model_implementations/transformers/ds_transformer.py:86.

Here the arena is a pytree of stacked per-layer buffers, shaped to scan with
the stacked layer params (models/transformer.py forward):

    {"k": (L, B, T_max, KV_HEADS, HEAD_DIM),
     "v": (L, B, T_max, KV_HEADS, HEAD_DIM),
     "index": (L,) int32}              # write cursor per layer (all equal)

Static T_max keeps every decode step the same XLA program (the reference's
CUDA-graph discipline becomes jit-cache discipline); tokens are written with
``lax.dynamic_update_slice`` at the cursor.

**Paged arena** (the serving layer, ``deepspeed_tpu/serving``): instead of
one ``T_max`` row per sequence, the time axis is carved into fixed-size
blocks shared by every in-flight request (vLLM's PagedAttention block
tables, Kwon et al. SOSP '23):

    {"k": (L, NUM_BLOCKS, BLOCK, KV_HEADS, HEAD_DIM),
     "v": (L, NUM_BLOCKS, BLOCK, KV_HEADS, HEAD_DIM)}

Block 0 is a reserved scratch block: writes of inactive decode rows and
prompt-chunk padding land there, so the jit program needs no write-masking
branch. A host-side free list (``serving/paged_kv.BlockAllocator``) owns
blocks 1.. and hands each sequence a block table ``(MAX_BLOCKS,)`` of
physical ids; attention reads gather ``k[block_table]`` — a shape-static
lookup, so one decode program serves any occupancy.

``dtype`` is mandatory throughout: a default here let call sites silently
allocate a bf16 arena for an fp32 (or fp16) engine — the arena dtype must
come from ``InferenceConfig.dtype``.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


def init_cache(cfg, batch_size: int, max_seq_len: int, dtype
               ) -> Dict[str, jax.Array]:
    """Allocate the arena for ``cfg`` (a TransformerConfig)."""
    L = cfg.num_layers
    K = cfg.num_kv_heads
    D = cfg.head_dim
    shape = (L, batch_size, max_seq_len, K, D)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "index": jnp.zeros((L,), jnp.int32),
    }


def cache_memory_bytes(cfg, batch_size: int, max_seq_len: int,
                       dtype=jnp.bfloat16) -> int:
    """Arena footprint — the sizing arithmetic the reference does in
    InferenceContext::GenWorkSpace (inference_context.h:121)."""
    itemsize = jnp.dtype(dtype).itemsize
    return (2 * cfg.num_layers * batch_size * max_seq_len
            * cfg.num_kv_heads * cfg.head_dim * itemsize)


def cache_shape_struct(cfg, batch_size: int, max_seq_len: int,
                       dtype) -> Dict[str, Any]:
    """eval_shape-compatible structure (for AOT sharding planning)."""
    L, K, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    shape = (L, batch_size, max_seq_len, K, D)
    return {
        "k": jax.ShapeDtypeStruct(shape, dtype),
        "v": jax.ShapeDtypeStruct(shape, dtype),
        "index": jax.ShapeDtypeStruct((cfg.num_layers,), jnp.int32),
    }


# ---------------------------------------------------------------------------
# paged arena (serving layer)
# ---------------------------------------------------------------------------


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` KV entries."""
    return -(-max(int(n_tokens), 0) // int(block_size))


def assert_block_divisible(max_seq_len: int, block_size: int) -> int:
    """``max_seq_len`` must split into whole blocks — a ragged tail block
    would make the gathered view wider than the sequence budget and break
    the one-program shape discipline. Returns blocks per sequence."""
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    if max_seq_len % block_size != 0:
        raise ValueError(
            f"max_seq_len={max_seq_len} is not divisible by "
            f"block_size={block_size} — the paged arena needs whole blocks "
            "(pick a block size that divides the sequence budget)")
    return max_seq_len // block_size


def init_paged_cache(cfg, num_blocks: int, block_size: int, dtype
                     ) -> Dict[str, jax.Array]:
    """Allocate the paged arena: ``num_blocks`` INCLUDES the reserved
    scratch block 0 (allocatable blocks are 1..num_blocks-1)."""
    if num_blocks < 2:
        raise ValueError(f"num_blocks={num_blocks}: need the scratch block "
                         "plus at least one allocatable block")
    L, K, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    shape = (L, num_blocks, block_size, K, D)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def paged_cache_memory_bytes(cfg, num_blocks: int, block_size: int,
                             dtype) -> int:
    itemsize = jnp.dtype(dtype).itemsize
    return (2 * cfg.num_layers * num_blocks * block_size
            * cfg.num_kv_heads * cfg.head_dim * itemsize)


def paged_cache_shape_struct(cfg, num_blocks: int, block_size: int,
                             dtype) -> Dict[str, Any]:
    L, K, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    shape = (L, num_blocks, block_size, K, D)
    return {"k": jax.ShapeDtypeStruct(shape, dtype),
            "v": jax.ShapeDtypeStruct(shape, dtype)}
