"""KV-cache arena: preallocated per-layer key/value buffers.

TPU-native analog of the reference's ``InferenceContext`` workspace
(csrc/transformer/inference/includes/inference_context.h:49) which sizes one
global GPU arena from ``max_out_tokens`` and hands each layer a slice, and of
the per-layer ``layer_past`` tracking in
model_implementations/transformers/ds_transformer.py:86.

Here the arena is a pytree of stacked per-layer buffers, shaped to scan with
the stacked layer params (models/transformer.py forward):

    {"k": (L, B, T_max, KV_HEADS, HEAD_DIM),
     "v": (L, B, T_max, KV_HEADS, HEAD_DIM),
     "index": (L,) int32}              # write cursor per layer (all equal)

Static T_max keeps every decode step the same XLA program (the reference's
CUDA-graph discipline becomes jit-cache discipline); tokens are written with
``lax.dynamic_update_slice`` at the cursor.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


def init_cache(cfg, batch_size: int, max_seq_len: int, dtype=jnp.bfloat16
               ) -> Dict[str, jax.Array]:
    """Allocate the arena for ``cfg`` (a TransformerConfig)."""
    L = cfg.num_layers
    K = cfg.num_kv_heads
    D = cfg.head_dim
    shape = (L, batch_size, max_seq_len, K, D)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "index": jnp.zeros((L,), jnp.int32),
    }


def cache_memory_bytes(cfg, batch_size: int, max_seq_len: int,
                       dtype=jnp.bfloat16) -> int:
    """Arena footprint — the sizing arithmetic the reference does in
    InferenceContext::GenWorkSpace (inference_context.h:121)."""
    itemsize = jnp.dtype(dtype).itemsize
    return (2 * cfg.num_layers * batch_size * max_seq_len
            * cfg.num_kv_heads * cfg.head_dim * itemsize)


def cache_shape_struct(cfg, batch_size: int, max_seq_len: int,
                       dtype=jnp.bfloat16) -> Dict[str, Any]:
    """eval_shape-compatible structure (for AOT sharding planning)."""
    L, K, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    shape = (L, batch_size, max_seq_len, K, D)
    return {
        "k": jax.ShapeDtypeStruct(shape, dtype),
        "v": jax.ShapeDtypeStruct(shape, dtype),
        "index": jax.ShapeDtypeStruct((cfg.num_layers,), jnp.int32),
    }
