"""HuggingFace checkpoint import — the policy-free auto-TP analog.

The reference maps HF architectures onto its fused inference modules through
per-architecture injection policies (module_inject/containers/{gpt2,opt,
bloom,llama...}.py, TransformerPolicy extracting qkv/mlp/LN tensors) and
shards them with ReplaceWithTensorSlicing (module_inject/replace_module.py:28)
/ AutoTP (module_inject/auto_tp.py). Here the same knowledge is a pure
state-dict → param-pytree mapping per family; TP sharding then falls out of
the logical-axis tree (models/core.py) — no weight surgery, `device_put` with
a NamedSharding slices each host array straight onto the mesh.

Weight-layout facts encoded below (checked against the reference containers):
  gpt2   Conv1D stores (in, out); c_attn is fused qkv along out.
  opt    torch Linear (out, in) → transpose; positions offset by 2.
  llama  Linear (out, in) → transpose; no biases; SwiGLU gate/up/down.
  bloom  fused query_key_value rows interleaved (head, [q|k|v], head_dim)
         (containers/bloom.py qkv ordering); ALiBi + embedding LayerNorm.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def _t(w) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(w).T)


def _a(w) -> np.ndarray:
    return np.asarray(w)


def _stack(layers):
    """list of per-layer trees → tree of (L, ...) stacked arrays."""
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs), *layers)


def import_hf_state_dict(state_dict: Dict[str, Any], cfg, family: str
                         ) -> Dict[str, Any]:
    """HF ``state_dict`` (tensors or numpy) → deepspeed_tpu param pytree
    (numpy, fp32/fp16 as stored — caller casts/shards)."""
    sd = {k: np.asarray(getattr(v, "numpy", lambda: v)()
                        if hasattr(v, "numpy") else v)
          for k, v in state_dict.items()}
    fam = family.split("-")[0]
    mapper = {
        "gpt2": _import_gpt2,
        "opt": _import_opt,
        "llama": _import_llama,
        "mistral": _import_llama,
        "bloom": _import_bloom,
        "gptj": _import_gptj,
        "gptneo": _import_gptneo,
        "gptneox": _import_gptneox,
        "clip": _import_clip,
        "bert": _import_bert,
        "distilbert": _import_distilbert,
    }.get(fam)
    if mapper is None:
        raise ValueError(f"no HF import mapping for family '{family}' "
                         "(have: gpt2, opt, llama, mistral, bloom, gptj, "
                         "gptneo, gptneox, clip, bert, distilbert)")
    return mapper(sd, cfg)


def _import_gpt2(sd, cfg):
    H = cfg.hidden_size
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        qkv_w = _a(sd[p + "attn.c_attn.weight"])        # (H, 3H) Conv1D
        qkv_b = _a(sd[p + "attn.c_attn.bias"])          # (3H,)
        layers.append({
            "ln1": {"scale": _a(sd[p + "ln_1.weight"]),
                    "bias": _a(sd[p + "ln_1.bias"])},
            "ln2": {"scale": _a(sd[p + "ln_2.weight"]),
                    "bias": _a(sd[p + "ln_2.bias"])},
            "attn": {
                "wq": qkv_w[:, :H], "wk": qkv_w[:, H:2 * H], "wv": qkv_w[:, 2 * H:],
                "bq": qkv_b[:H], "bk": qkv_b[H:2 * H], "bv": qkv_b[2 * H:],
                "wo": _a(sd[p + "attn.c_proj.weight"]),
                "bo": _a(sd[p + "attn.c_proj.bias"]),
            },
            "mlp": {
                "w_up": _a(sd[p + "mlp.c_fc.weight"]),
                "b_up": _a(sd[p + "mlp.c_fc.bias"]),
                "w_down": _a(sd[p + "mlp.c_proj.weight"]),
                "b_down": _a(sd[p + "mlp.c_proj.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd["transformer.wte.weight"])},
        "pos": _a(sd["transformer.wpe.weight"]),
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd["transformer.ln_f.weight"]),
                       "bias": _a(sd["transformer.ln_f.bias"])},
    }


def _import_opt(sd, cfg):
    pre = "model.decoder."
    layers = []
    for i in range(cfg.num_layers):
        p = f"{pre}layers.{i}."
        layers.append({
            "ln1": {"scale": _a(sd[p + "self_attn_layer_norm.weight"]),
                    "bias": _a(sd[p + "self_attn_layer_norm.bias"])},
            "ln2": {"scale": _a(sd[p + "final_layer_norm.weight"]),
                    "bias": _a(sd[p + "final_layer_norm.bias"])},
            "attn": {
                "wq": _t(sd[p + "self_attn.q_proj.weight"]),
                "wk": _t(sd[p + "self_attn.k_proj.weight"]),
                "wv": _t(sd[p + "self_attn.v_proj.weight"]),
                "bq": _a(sd[p + "self_attn.q_proj.bias"]),
                "bk": _a(sd[p + "self_attn.k_proj.bias"]),
                "bv": _a(sd[p + "self_attn.v_proj.bias"]),
                "wo": _t(sd[p + "self_attn.out_proj.weight"]),
                "bo": _a(sd[p + "self_attn.out_proj.bias"]),
            },
            "mlp": {
                "w_up": _t(sd[p + "fc1.weight"]),
                "b_up": _a(sd[p + "fc1.bias"]),
                "w_down": _t(sd[p + "fc2.weight"]),
                "b_down": _a(sd[p + "fc2.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd[pre + "embed_tokens.weight"])},
        # OPT's learned positions are stored with a +2 offset
        # (reference containers/opt.py relies on HF applying it)
        "pos": _a(sd[pre + "embed_positions.weight"])[2:],
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd[pre + "final_layer_norm.weight"]),
                       "bias": _a(sd[pre + "final_layer_norm.bias"])},
    }


def _import_llama(sd, cfg):
    layers = []
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        layers.append({
            "ln1": {"scale": _a(sd[p + "input_layernorm.weight"])},
            "ln2": {"scale": _a(sd[p + "post_attention_layernorm.weight"])},
            "attn": {
                "wq": _t(sd[p + "self_attn.q_proj.weight"]),
                "wk": _t(sd[p + "self_attn.k_proj.weight"]),
                "wv": _t(sd[p + "self_attn.v_proj.weight"]),
                "wo": _t(sd[p + "self_attn.o_proj.weight"]),
            },
            "mlp": {
                "w_gate": _t(sd[p + "mlp.gate_proj.weight"]),
                "w_up": _t(sd[p + "mlp.up_proj.weight"]),
                "w_down": _t(sd[p + "mlp.down_proj.weight"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd["model.embed_tokens.weight"])},
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd["model.norm.weight"])},
        "lm_head": _t(sd["lm_head.weight"]),
    }


def _import_bloom(sd, cfg):
    H, N, D = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    pre = "transformer."
    layers = []
    for i in range(cfg.num_layers):
        p = f"{pre}h.{i}."
        # fused qkv with (head, 3, head_dim) row interleave
        # (reference containers/bloom.py / HF BloomAttention layout)
        qkv_w = _a(sd[p + "self_attention.query_key_value.weight"])  # (3H, H)
        qkv_b = _a(sd[p + "self_attention.query_key_value.bias"])    # (3H,)
        w = qkv_w.reshape(N, 3, D, H)
        b = qkv_b.reshape(N, 3, D)
        wq = np.ascontiguousarray(w[:, 0].reshape(N * D, H).T)
        wk = np.ascontiguousarray(w[:, 1].reshape(N * D, H).T)
        wv = np.ascontiguousarray(w[:, 2].reshape(N * D, H).T)
        layers.append({
            "ln1": {"scale": _a(sd[p + "input_layernorm.weight"]),
                    "bias": _a(sd[p + "input_layernorm.bias"])},
            "ln2": {"scale": _a(sd[p + "post_attention_layernorm.weight"]),
                    "bias": _a(sd[p + "post_attention_layernorm.bias"])},
            "attn": {
                "wq": wq, "wk": wk, "wv": wv,
                "bq": b[:, 0].reshape(-1), "bk": b[:, 1].reshape(-1),
                "bv": b[:, 2].reshape(-1),
                "wo": _t(sd[p + "self_attention.dense.weight"]),
                "bo": _a(sd[p + "self_attention.dense.bias"]),
            },
            "mlp": {
                "w_up": _t(sd[p + "mlp.dense_h_to_4h.weight"]),
                "b_up": _a(sd[p + "mlp.dense_h_to_4h.bias"]),
                "w_down": _t(sd[p + "mlp.dense_4h_to_h.weight"]),
                "b_down": _a(sd[p + "mlp.dense_4h_to_h.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd[pre + "word_embeddings.weight"])},
        "embed_norm": {"scale": _a(sd[pre + "word_embeddings_layernorm.weight"]),
                       "bias": _a(sd[pre + "word_embeddings_layernorm.bias"])},
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd[pre + "ln_f.weight"]),
                       "bias": _a(sd[pre + "ln_f.bias"])},
    }


def _rotary_perm(w_t: np.ndarray, N: int, D: int, rd: int) -> np.ndarray:
    """GPT-J stores rotary dims INTERLEAVED (rotate_every_two); permuting
    each head's rotary columns to evens-then-odds converts exactly to the
    rotate-half convention apply_rope implements (attention is invariant to
    a shared per-head q/k column permutation). w_t: (H, N*D) input-major."""
    H = w_t.shape[0]
    w = w_t.reshape(H, N, D)
    perm = np.concatenate([np.arange(0, rd, 2), np.arange(1, rd, 2)])
    rot = w[:, :, :rd][:, :, perm]
    return np.ascontiguousarray(
        np.concatenate([rot, w[:, :, rd:]], axis=2).reshape(H, N * D))


def _import_gptj(sd, cfg):
    """GPT-J (reference module_inject/containers/gptj.py): parallel
    attn+mlp residual off ONE LayerNorm, partial interleaved rotary, no
    attention biases, biased untied lm_head."""
    N, D, rd = cfg.num_heads, cfg.head_dim, cfg.rotary_dim or cfg.head_dim
    H = cfg.hidden_size
    zeros = lambda n: np.zeros((n,), np.float32)
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        ln = {"scale": _a(sd[p + "ln_1.weight"]),
              "bias": _a(sd[p + "ln_1.bias"])}
        layers.append({
            # one shared LN: ln2 aliases ln1 (parallel_residual reads ln2
            # for the MLP branch)
            "ln1": dict(ln), "ln2": dict(ln),
            "attn": {
                "wq": _rotary_perm(_t(sd[p + "attn.q_proj.weight"]), N, D, rd),
                "wk": _rotary_perm(_t(sd[p + "attn.k_proj.weight"]), N, D, rd),
                "wv": _t(sd[p + "attn.v_proj.weight"]),
                "bq": zeros(N * D), "bk": zeros(N * D), "bv": zeros(N * D),
                "wo": _t(sd[p + "attn.out_proj.weight"]),
                "bo": zeros(H),
            },
            "mlp": {
                "w_up": _t(sd[p + "mlp.fc_in.weight"]),
                "b_up": _a(sd[p + "mlp.fc_in.bias"]),
                "w_down": _t(sd[p + "mlp.fc_out.weight"]),
                "b_down": _a(sd[p + "mlp.fc_out.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd["transformer.wte.weight"])},
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd["transformer.ln_f.weight"]),
                       "bias": _a(sd["transformer.ln_f.bias"])},
        "lm_head": _t(sd["lm_head.weight"]),
        "lm_head_b": _a(sd["lm_head.bias"]),
    }


def _import_clip(sd, cfg):
    """CLIP text encoder (reference containers/clip.py HFCLIPLayerPolicy —
    the Stable Diffusion text tower): pre-LN causal transformer with
    quick_gelu; torch Linear (out, in) → transpose."""
    sd = _strip_prefix(sd, "text_model.")
    layers = []
    for i in range(cfg.num_layers):
        p = f"encoder.layers.{i}."
        a = p + "self_attn."
        layers.append({
            "ln1": {"scale": _a(sd[p + "layer_norm1.weight"]),
                    "bias": _a(sd[p + "layer_norm1.bias"])},
            "ln2": {"scale": _a(sd[p + "layer_norm2.weight"]),
                    "bias": _a(sd[p + "layer_norm2.bias"])},
            "attn": {
                "wq": _t(sd[a + "q_proj.weight"]),
                "wk": _t(sd[a + "k_proj.weight"]),
                "wv": _t(sd[a + "v_proj.weight"]),
                "bq": _a(sd[a + "q_proj.bias"]),
                "bk": _a(sd[a + "k_proj.bias"]),
                "bv": _a(sd[a + "v_proj.bias"]),
                "wo": _t(sd[a + "out_proj.weight"]),
                "bo": _a(sd[a + "out_proj.bias"]),
            },
            "mlp": {
                "w_up": _t(sd[p + "mlp.fc1.weight"]),
                "b_up": _a(sd[p + "mlp.fc1.bias"]),
                "w_down": _t(sd[p + "mlp.fc2.weight"]),
                "b_down": _a(sd[p + "mlp.fc2.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd["embeddings.token_embedding.weight"])},
        "pos": _a(sd["embeddings.position_embedding.weight"]),
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd["final_layer_norm.weight"]),
                       "bias": _a(sd["final_layer_norm.bias"])},
    }


def _import_gptneo(sd, cfg):
    """GPT-Neo (reference containers/gptneo.py HFGPTNEOLayerPolicy):
    separate UNBIASED q/k/v Linears, biased out_proj, Linear (out,in) MLP
    (unlike gpt2's Conv1D); alternating global/local attention and the
    unscaled-score convention live in the gptneo preset config."""
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        a = p + "attn.attention."
        layers.append({
            "ln1": {"scale": _a(sd[p + "ln_1.weight"]),
                    "bias": _a(sd[p + "ln_1.bias"])},
            "ln2": {"scale": _a(sd[p + "ln_2.weight"]),
                    "bias": _a(sd[p + "ln_2.bias"])},
            "attn": {
                "wq": _t(sd[a + "q_proj.weight"]),
                "wk": _t(sd[a + "k_proj.weight"]),
                "wv": _t(sd[a + "v_proj.weight"]),
                # HF GPT-Neo q/k/v Linears carry no bias; the model tree
                # does (layernorm-family init) — zeros are identical
                "bq": np.zeros((sd[a + "q_proj.weight"].shape[0],),
                               np.float32),
                "bk": np.zeros((sd[a + "k_proj.weight"].shape[0],),
                               np.float32),
                "bv": np.zeros((sd[a + "v_proj.weight"].shape[0],),
                               np.float32),
                "wo": _t(sd[a + "out_proj.weight"]),
                "bo": _a(sd[a + "out_proj.bias"]),
            },
            "mlp": {
                "w_up": _t(sd[p + "mlp.c_fc.weight"]),
                "b_up": _a(sd[p + "mlp.c_fc.bias"]),
                "w_down": _t(sd[p + "mlp.c_proj.weight"]),
                "b_down": _a(sd[p + "mlp.c_proj.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd["transformer.wte.weight"])},
        "pos": _a(sd["transformer.wpe.weight"]),
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd["transformer.ln_f.weight"]),
                       "bias": _a(sd["transformer.ln_f.bias"])},
    }


def _import_gptneox(sd, cfg):
    """GPT-NeoX (reference module_inject/containers/gptneox.py): fused qkv
    with per-head (q|k|v) row interleave, parallel residual with its own
    post_attention_layernorm, partial rotate-half rotary, untied embed_out."""
    H, N, D = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    pre = "gpt_neox."
    layers = []
    for i in range(cfg.num_layers):
        p = f"{pre}layers.{i}."
        qkv_w = _a(sd[p + "attention.query_key_value.weight"])  # (3H, H)
        qkv_b = _a(sd[p + "attention.query_key_value.bias"])
        w = qkv_w.reshape(N, 3, D, H)
        b = qkv_b.reshape(N, 3, D)
        layers.append({
            "ln1": {"scale": _a(sd[p + "input_layernorm.weight"]),
                    "bias": _a(sd[p + "input_layernorm.bias"])},
            "ln2": {"scale": _a(sd[p + "post_attention_layernorm.weight"]),
                    "bias": _a(sd[p + "post_attention_layernorm.bias"])},
            "attn": {
                "wq": np.ascontiguousarray(w[:, 0].reshape(N * D, H).T),
                "wk": np.ascontiguousarray(w[:, 1].reshape(N * D, H).T),
                "wv": np.ascontiguousarray(w[:, 2].reshape(N * D, H).T),
                "bq": b[:, 0].reshape(-1), "bk": b[:, 1].reshape(-1),
                "bv": b[:, 2].reshape(-1),
                "wo": _t(sd[p + "attention.dense.weight"]),
                "bo": _a(sd[p + "attention.dense.bias"]),
            },
            "mlp": {
                "w_up": _t(sd[p + "mlp.dense_h_to_4h.weight"]),
                "b_up": _a(sd[p + "mlp.dense_h_to_4h.bias"]),
                "w_down": _t(sd[p + "mlp.dense_4h_to_h.weight"]),
                "b_down": _a(sd[p + "mlp.dense_4h_to_h.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd[pre + "embed_in.weight"])},
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd[pre + "final_layer_norm.weight"]),
                       "bias": _a(sd[pre + "final_layer_norm.bias"])},
        "lm_head": _t(sd["embed_out.weight"]),
    }


def _strip_prefix(sd, prefix):
    if any(k.startswith(prefix) for k in sd):
        return {k[len(prefix):]: v for k, v in sd.items()
                if k.startswith(prefix)}
    return sd


def _import_bert(sd, cfg):
    """BERT (reference module_inject/containers/bert.py): post-LN encoder —
    LayerNorm AFTER each residual add, bidirectional attention, token-type
    embeddings, no final norm (exercises the non-causal path end to end)."""
    sd = _strip_prefix(sd, "bert.")
    layers = []
    for i in range(cfg.num_layers):
        p = f"encoder.layer.{i}."
        layers.append({
            # post-LN mapping: ln1 = attention.output.LayerNorm (applied to
            # x + attn_out), ln2 = output.LayerNorm (x + mlp_out)
            "ln1": {"scale": _a(sd[p + "attention.output.LayerNorm.weight"]),
                    "bias": _a(sd[p + "attention.output.LayerNorm.bias"])},
            "ln2": {"scale": _a(sd[p + "output.LayerNorm.weight"]),
                    "bias": _a(sd[p + "output.LayerNorm.bias"])},
            "attn": {
                "wq": _t(sd[p + "attention.self.query.weight"]),
                "wk": _t(sd[p + "attention.self.key.weight"]),
                "wv": _t(sd[p + "attention.self.value.weight"]),
                "bq": _a(sd[p + "attention.self.query.bias"]),
                "bk": _a(sd[p + "attention.self.key.bias"]),
                "bv": _a(sd[p + "attention.self.value.bias"]),
                "wo": _t(sd[p + "attention.output.dense.weight"]),
                "bo": _a(sd[p + "attention.output.dense.bias"]),
            },
            "mlp": {
                "w_up": _t(sd[p + "intermediate.dense.weight"]),
                "b_up": _a(sd[p + "intermediate.dense.bias"]),
                "w_down": _t(sd[p + "output.dense.weight"]),
                "b_down": _a(sd[p + "output.dense.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd["embeddings.word_embeddings.weight"])},
        "pos": _a(sd["embeddings.position_embeddings.weight"]),
        "type_embed": _a(sd["embeddings.token_type_embeddings.weight"]),
        "embed_norm": {"scale": _a(sd["embeddings.LayerNorm.weight"]),
                       "bias": _a(sd["embeddings.LayerNorm.bias"])},
        "layers": _stack(layers),
    }


def _import_distilbert(sd, cfg):
    """DistilBERT (reference module_inject/containers/distil_bert.py):
    BERT-style post-LN encoder without token types."""
    sd = _strip_prefix(sd, "distilbert.")
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.layer.{i}."
        layers.append({
            "ln1": {"scale": _a(sd[p + "sa_layer_norm.weight"]),
                    "bias": _a(sd[p + "sa_layer_norm.bias"])},
            "ln2": {"scale": _a(sd[p + "output_layer_norm.weight"]),
                    "bias": _a(sd[p + "output_layer_norm.bias"])},
            "attn": {
                "wq": _t(sd[p + "attention.q_lin.weight"]),
                "wk": _t(sd[p + "attention.k_lin.weight"]),
                "wv": _t(sd[p + "attention.v_lin.weight"]),
                "bq": _a(sd[p + "attention.q_lin.bias"]),
                "bk": _a(sd[p + "attention.k_lin.bias"]),
                "bv": _a(sd[p + "attention.v_lin.bias"]),
                "wo": _t(sd[p + "attention.out_lin.weight"]),
                "bo": _a(sd[p + "attention.out_lin.bias"]),
            },
            "mlp": {
                "w_up": _t(sd[p + "ffn.lin1.weight"]),
                "b_up": _a(sd[p + "ffn.lin1.bias"]),
                "w_down": _t(sd[p + "ffn.lin2.weight"]),
                "b_down": _a(sd[p + "ffn.lin2.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd["embeddings.word_embeddings.weight"])},
        "pos": _a(sd["embeddings.position_embeddings.weight"]),
        "embed_norm": {"scale": _a(sd["embeddings.LayerNorm.weight"]),
                       "bias": _a(sd["embeddings.LayerNorm.bias"])},
        "layers": _stack(layers),
    }


def import_hf_model(hf_model, cfg, family: str) -> Dict[str, Any]:
    """Import directly from a live transformers model object."""
    sd = {k: v.detach().to("cpu").float().numpy()
          for k, v in hf_model.state_dict().items()}
    return import_hf_state_dict(sd, cfg, family)


def load_flat_weights_tree(path: str) -> Dict[str, Any]:
    """Load a ``save_flat_weights``/``save_16bit_model`` npz (written by
    runtime/checkpoint.py) back into a nested param pytree."""
    from ..runtime.checkpoint import _SEP, load_flat_weights

    tree: Dict[str, Any] = {}
    for key, arr in load_flat_weights(path).items():
        parts = key.split(_SEP)
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return tree
