"""HuggingFace checkpoint import — the policy-free auto-TP analog.

The reference maps HF architectures onto its fused inference modules through
per-architecture injection policies (module_inject/containers/{gpt2,opt,
bloom,llama...}.py, TransformerPolicy extracting qkv/mlp/LN tensors) and
shards them with ReplaceWithTensorSlicing (module_inject/replace_module.py:28)
/ AutoTP (module_inject/auto_tp.py). Here the same knowledge is a pure
state-dict → param-pytree mapping per family; TP sharding then falls out of
the logical-axis tree (models/core.py) — no weight surgery, `device_put` with
a NamedSharding slices each host array straight onto the mesh.

Weight-layout facts encoded below (checked against the reference containers):
  gpt2   Conv1D stores (in, out); c_attn is fused qkv along out.
  opt    torch Linear (out, in) → transpose; positions offset by 2.
  llama  Linear (out, in) → transpose; no biases; SwiGLU gate/up/down.
  bloom  fused query_key_value rows interleaved (head, [q|k|v], head_dim)
         (containers/bloom.py qkv ordering); ALiBi + embedding LayerNorm.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def _t(w) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(w).T)


def _a(w) -> np.ndarray:
    return np.asarray(w)


def _stack(layers):
    """list of per-layer trees → tree of (L, ...) stacked arrays."""
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs), *layers)


def import_hf_state_dict(state_dict: Dict[str, Any], cfg, family: str
                         ) -> Dict[str, Any]:
    """HF ``state_dict`` (tensors or numpy) → deepspeed_tpu param pytree
    (numpy, fp32/fp16 as stored — caller casts/shards)."""
    sd = {k: np.asarray(getattr(v, "numpy", lambda: v)()
                        if hasattr(v, "numpy") else v)
          for k, v in state_dict.items()}
    fam = family.split("-")[0]
    mapper = {
        "gpt2": _import_gpt2,
        "opt": _import_opt,
        "llama": _import_llama,
        "mistral": _import_llama,
        "bloom": _import_bloom,
    }.get(fam)
    if mapper is None:
        raise ValueError(f"no HF import mapping for family '{family}' "
                         "(have: gpt2, opt, llama, mistral, bloom)")
    return mapper(sd, cfg)


def _import_gpt2(sd, cfg):
    H = cfg.hidden_size
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        qkv_w = _a(sd[p + "attn.c_attn.weight"])        # (H, 3H) Conv1D
        qkv_b = _a(sd[p + "attn.c_attn.bias"])          # (3H,)
        layers.append({
            "ln1": {"scale": _a(sd[p + "ln_1.weight"]),
                    "bias": _a(sd[p + "ln_1.bias"])},
            "ln2": {"scale": _a(sd[p + "ln_2.weight"]),
                    "bias": _a(sd[p + "ln_2.bias"])},
            "attn": {
                "wq": qkv_w[:, :H], "wk": qkv_w[:, H:2 * H], "wv": qkv_w[:, 2 * H:],
                "bq": qkv_b[:H], "bk": qkv_b[H:2 * H], "bv": qkv_b[2 * H:],
                "wo": _a(sd[p + "attn.c_proj.weight"]),
                "bo": _a(sd[p + "attn.c_proj.bias"]),
            },
            "mlp": {
                "w_up": _a(sd[p + "mlp.c_fc.weight"]),
                "b_up": _a(sd[p + "mlp.c_fc.bias"]),
                "w_down": _a(sd[p + "mlp.c_proj.weight"]),
                "b_down": _a(sd[p + "mlp.c_proj.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd["transformer.wte.weight"])},
        "pos": _a(sd["transformer.wpe.weight"]),
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd["transformer.ln_f.weight"]),
                       "bias": _a(sd["transformer.ln_f.bias"])},
    }


def _import_opt(sd, cfg):
    pre = "model.decoder."
    layers = []
    for i in range(cfg.num_layers):
        p = f"{pre}layers.{i}."
        layers.append({
            "ln1": {"scale": _a(sd[p + "self_attn_layer_norm.weight"]),
                    "bias": _a(sd[p + "self_attn_layer_norm.bias"])},
            "ln2": {"scale": _a(sd[p + "final_layer_norm.weight"]),
                    "bias": _a(sd[p + "final_layer_norm.bias"])},
            "attn": {
                "wq": _t(sd[p + "self_attn.q_proj.weight"]),
                "wk": _t(sd[p + "self_attn.k_proj.weight"]),
                "wv": _t(sd[p + "self_attn.v_proj.weight"]),
                "bq": _a(sd[p + "self_attn.q_proj.bias"]),
                "bk": _a(sd[p + "self_attn.k_proj.bias"]),
                "bv": _a(sd[p + "self_attn.v_proj.bias"]),
                "wo": _t(sd[p + "self_attn.out_proj.weight"]),
                "bo": _a(sd[p + "self_attn.out_proj.bias"]),
            },
            "mlp": {
                "w_up": _t(sd[p + "fc1.weight"]),
                "b_up": _a(sd[p + "fc1.bias"]),
                "w_down": _t(sd[p + "fc2.weight"]),
                "b_down": _a(sd[p + "fc2.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd[pre + "embed_tokens.weight"])},
        # OPT's learned positions are stored with a +2 offset
        # (reference containers/opt.py relies on HF applying it)
        "pos": _a(sd[pre + "embed_positions.weight"])[2:],
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd[pre + "final_layer_norm.weight"]),
                       "bias": _a(sd[pre + "final_layer_norm.bias"])},
    }


def _import_llama(sd, cfg):
    layers = []
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        layers.append({
            "ln1": {"scale": _a(sd[p + "input_layernorm.weight"])},
            "ln2": {"scale": _a(sd[p + "post_attention_layernorm.weight"])},
            "attn": {
                "wq": _t(sd[p + "self_attn.q_proj.weight"]),
                "wk": _t(sd[p + "self_attn.k_proj.weight"]),
                "wv": _t(sd[p + "self_attn.v_proj.weight"]),
                "wo": _t(sd[p + "self_attn.o_proj.weight"]),
            },
            "mlp": {
                "w_gate": _t(sd[p + "mlp.gate_proj.weight"]),
                "w_up": _t(sd[p + "mlp.up_proj.weight"]),
                "w_down": _t(sd[p + "mlp.down_proj.weight"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd["model.embed_tokens.weight"])},
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd["model.norm.weight"])},
        "lm_head": _t(sd["lm_head.weight"]),
    }


def _import_bloom(sd, cfg):
    H, N, D = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    pre = "transformer."
    layers = []
    for i in range(cfg.num_layers):
        p = f"{pre}h.{i}."
        # fused qkv with (head, 3, head_dim) row interleave
        # (reference containers/bloom.py / HF BloomAttention layout)
        qkv_w = _a(sd[p + "self_attention.query_key_value.weight"])  # (3H, H)
        qkv_b = _a(sd[p + "self_attention.query_key_value.bias"])    # (3H,)
        w = qkv_w.reshape(N, 3, D, H)
        b = qkv_b.reshape(N, 3, D)
        wq = np.ascontiguousarray(w[:, 0].reshape(N * D, H).T)
        wk = np.ascontiguousarray(w[:, 1].reshape(N * D, H).T)
        wv = np.ascontiguousarray(w[:, 2].reshape(N * D, H).T)
        layers.append({
            "ln1": {"scale": _a(sd[p + "input_layernorm.weight"]),
                    "bias": _a(sd[p + "input_layernorm.bias"])},
            "ln2": {"scale": _a(sd[p + "post_attention_layernorm.weight"]),
                    "bias": _a(sd[p + "post_attention_layernorm.bias"])},
            "attn": {
                "wq": wq, "wk": wk, "wv": wv,
                "bq": b[:, 0].reshape(-1), "bk": b[:, 1].reshape(-1),
                "bv": b[:, 2].reshape(-1),
                "wo": _t(sd[p + "self_attention.dense.weight"]),
                "bo": _a(sd[p + "self_attention.dense.bias"]),
            },
            "mlp": {
                "w_up": _t(sd[p + "mlp.dense_h_to_4h.weight"]),
                "b_up": _a(sd[p + "mlp.dense_h_to_4h.bias"]),
                "w_down": _t(sd[p + "mlp.dense_4h_to_h.weight"]),
                "b_down": _a(sd[p + "mlp.dense_4h_to_h.bias"]),
            },
        })
    return {
        "embed": {"tokens": _a(sd[pre + "word_embeddings.weight"])},
        "embed_norm": {"scale": _a(sd[pre + "word_embeddings_layernorm.weight"]),
                       "bias": _a(sd[pre + "word_embeddings_layernorm.bias"])},
        "layers": _stack(layers),
        "final_norm": {"scale": _a(sd[pre + "ln_f.weight"]),
                       "bias": _a(sd[pre + "ln_f.bias"])},
    }


def import_hf_model(hf_model, cfg, family: str) -> Dict[str, Any]:
    """Import directly from a live transformers model object."""
    sd = {k: v.detach().to("cpu").float().numpy()
          for k, v in hf_model.state_dict().items()}
    return import_hf_state_dict(sd, cfg, family)


def load_flat_weights_tree(path: str) -> Dict[str, Any]:
    """Load a ``save_flat_weights``/``save_16bit_model`` npz (written by
    runtime/checkpoint.py) back into a nested param pytree."""
    from ..runtime.checkpoint import _SEP, load_flat_weights

    tree: Dict[str, Any] = {}
    for key, arr in load_flat_weights(path).items():
        parts = key.split(_SEP)
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return tree
