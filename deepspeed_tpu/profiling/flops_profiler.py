"""FLOPs profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py:23`` — there, a
monkey-patched torch counts MACs per module via hooks. Under jit that
machinery dissolves: XLA already knows the program cost. Two complementary
sources are combined:

  * ``jax.stages.Compiled.cost_analysis()`` — the compiler's own whole-program
    flops / bytes-accessed estimate (exact for what actually runs, including
    fusion effects);
  * an analytic per-module breakdown from the ``TransformerConfig`` — the
    per-module tree the reference prints (attention / MLP / embedding / head
    per layer), which the compiled program cannot attribute.

``get_model_profile`` mirrors the reference's public helper of the same name
(flops_profiler/profiler.py get_model_profile): model + batch shape → total
flops/MACs/params + formatted per-module table.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple


# -- humanised formatting (reference profiler.py number_to_string etc.) ------

def number_string(n: float, units: Optional[str] = None, precision: int = 2) -> str:
    for cut, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= cut:
            return f"{n / cut:.{precision}f} {suffix}{units or ''}"
    return f"{n:.{precision}f} {units or ''}"


def flops_string(f: float, precision: int = 2) -> str:
    return number_string(f, "FLOPs", precision)


def params_string(p: float, precision: int = 2) -> str:
    return number_string(p, "", precision).strip()


def duration_string(sec: float, precision: int = 2) -> str:
    if sec >= 1:
        return f"{sec:.{precision}f} s"
    if sec >= 1e-3:
        return f"{sec * 1e3:.{precision}f} ms"
    return f"{sec * 1e6:.{precision}f} us"


# -- compiled-program cost ---------------------------------------------------


def compiled_cost(compiled) -> Dict[str, float]:
    """flops / bytes from a ``jax.stages.Compiled`` (XLA cost analysis)."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return {"flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0))}


# -- analytic transformer breakdown -----------------------------------------


@dataclasses.dataclass
class FlopsProfile:
    total_params: int
    total_flops: float            # forward flops for the given batch
    per_module: Dict[str, Dict[str, float]]
    batch_size: int
    seq_len: int

    def flops_per_token(self) -> float:
        return self.total_flops / max(self.batch_size * self.seq_len, 1)

    def table(self, step_time: Optional[float] = None,
              peak_flops: Optional[float] = None) -> str:
        lines = [f"{'module':<16}{'params':>12}{'fwd FLOPs':>16}{'share':>8}",
                 "-" * 52]
        for name, row in self.per_module.items():
            share = row["flops"] / self.total_flops if self.total_flops else 0
            lines.append(f"{name:<16}{params_string(row['params']):>12}"
                         f"{number_string(row['flops'], ''):>16}{share:>7.1%}")
        lines.append("-" * 52)
        lines.append(f"{'total':<16}{params_string(self.total_params):>12}"
                     f"{number_string(self.total_flops, ''):>16}")
        if step_time:
            # fwd+bwd ~ 3x fwd flops (reference uses the same 1:2 rule)
            achieved = 3 * self.total_flops / step_time
            lines.append(f"step time {duration_string(step_time)}  "
                         f"achieved {flops_string(achieved)}/s"
                         + (f"  MFU {achieved / peak_flops:.1%}"
                            if peak_flops else ""))
        return "\n".join(lines)


def transformer_breakdown(cfg, batch_size: int, seq_len: int) -> FlopsProfile:
    """Analytic per-module forward profile for a TransformerConfig (MACs*2)."""
    H, L, V, F = (cfg.hidden_size, cfg.num_layers, cfg.vocab_size,
                  cfg.ffn_hidden_size)
    N, K, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    T = batch_size * seq_len                      # tokens
    E = max(cfg.moe_num_experts, 1)
    topk = cfg.moe_top_k if cfg.moe_num_experts else 1

    qkv_params = H * (N * D) + 2 * H * (K * D)
    attn_params = qkv_params + (N * D) * H
    if cfg.activation == "swiglu":
        mlp_params_one = 3 * H * F
        mlp_flops_tok = 2 * 3 * H * F
    else:
        mlp_params_one = 2 * H * F
        mlp_flops_tok = 2 * 2 * H * F
    mlp_params = mlp_params_one * E
    router_params = H * cfg.moe_num_experts if cfg.moe_num_experts else 0

    per_module = {
        "embedding": {"params": V * H, "flops": 0.0},
        "attention": {"params": L * attn_params,
                      "flops": T * L * (2 * attn_params
                                        + 4 * seq_len * N * D)},
        "mlp": {"params": L * (mlp_params + router_params),
                "flops": T * L * (mlp_flops_tok * topk
                                  + 2 * router_params)},
        "norms": {"params": L * (2 * H) * (2 if cfg.norm == "layernorm" else 1)
                  + H, "flops": T * L * 8 * H},
        "lm_head": {"params": 0 if cfg.tie_embeddings else H * V,
                    "flops": T * 2 * H * V},
    }
    if cfg.position == "learned":
        per_module["embedding"]["params"] += cfg.max_seq_len * H
    total_params = sum(int(m["params"]) for m in per_module.values())
    total_flops = sum(m["flops"] for m in per_module.values())
    return FlopsProfile(total_params=total_params, total_flops=total_flops,
                        per_module=per_module, batch_size=batch_size,
                        seq_len=seq_len)


def get_model_profile(model, batch_size: int, seq_len: int,
                      print_profile: bool = False) -> Tuple[float, float, int]:
    """Reference get_model_profile parity: returns (flops, macs, params)."""
    prof = transformer_breakdown(model.config, batch_size, seq_len)
    if print_profile:
        print(prof.table())
    return prof.total_flops, prof.total_flops / 2, prof.total_params
