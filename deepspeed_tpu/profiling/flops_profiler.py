"""FLOPs profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py:23`` — there, a
monkey-patched torch counts MACs per module via hooks. Under jit that
machinery dissolves: XLA already knows the program cost. Two complementary
sources are combined:

  * ``jax.stages.Compiled.cost_analysis()`` — the compiler's own whole-program
    flops / bytes-accessed estimate (exact for what actually runs, including
    fusion effects);
  * an analytic per-module breakdown from the ``TransformerConfig`` — the
    per-module tree the reference prints (attention / MLP / embedding / head
    per layer), which the compiled program cannot attribute.

``get_model_profile`` mirrors the reference's public helper of the same name
(flops_profiler/profiler.py get_model_profile): model + batch shape → total
flops/MACs/params + formatted per-module table.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple


# -- humanised formatting (reference profiler.py number_to_string etc.) ------

def number_string(n: float, units: Optional[str] = None, precision: int = 2) -> str:
    for cut, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= cut:
            return f"{n / cut:.{precision}f} {suffix}{units or ''}"
    return f"{n:.{precision}f} {units or ''}"


def flops_string(f: float, precision: int = 2) -> str:
    return number_string(f, "FLOPs", precision)


def params_string(p: float, precision: int = 2) -> str:
    return number_string(p, "", precision).strip()


def duration_string(sec: float, precision: int = 2) -> str:
    if sec >= 1:
        return f"{sec:.{precision}f} s"
    if sec >= 1e-3:
        return f"{sec * 1e3:.{precision}f} ms"
    return f"{sec * 1e6:.{precision}f} us"


# -- compiled-program cost ---------------------------------------------------


def compiled_cost(compiled) -> Dict[str, float]:
    """flops / bytes from a ``jax.stages.Compiled`` (XLA cost analysis).

    Delegates to the tpucost extraction helpers — the single implementation
    of compiled-artifact metric parsing (``tools/tpucost/extract.py``), the
    same one the CI cost gate reads, so the profiler and the gate can never
    disagree on what a program costs. A deployment shipped without the
    ``tools/`` tree degrades to {} (the same contract as a backend without
    cost analysis)."""
    try:
        from tools.tpucost.extract import cost_analysis_dict
    except ImportError:
        return {}
    cost = cost_analysis_dict(compiled)
    if not cost:
        return {}
    return {"flops": cost["flops"], "bytes_accessed": cost["bytes_accessed"]}


# -- analytic transformer breakdown -----------------------------------------


@dataclasses.dataclass
class FlopsProfile:
    total_params: int
    total_flops: float            # forward flops for the given batch
    per_module: Dict[str, Dict[str, float]]
    batch_size: int
    seq_len: int

    def flops_per_token(self) -> float:
        return self.total_flops / max(self.batch_size * self.seq_len, 1)

    def table(self, step_time: Optional[float] = None,
              peak_flops: Optional[float] = None) -> str:
        lines = [f"{'module':<16}{'params':>12}{'fwd FLOPs':>16}{'share':>8}",
                 "-" * 52]
        for name, row in self.per_module.items():
            share = row["flops"] / self.total_flops if self.total_flops else 0
            lines.append(f"{name:<16}{params_string(row['params']):>12}"
                         f"{number_string(row['flops'], ''):>16}{share:>7.1%}")
        lines.append("-" * 52)
        lines.append(f"{'total':<16}{params_string(self.total_params):>12}"
                     f"{number_string(self.total_flops, ''):>16}")
        if step_time:
            # fwd+bwd ~ 3x fwd flops (reference uses the same 1:2 rule)
            achieved = 3 * self.total_flops / step_time
            lines.append(f"step time {duration_string(step_time)}  "
                         f"achieved {flops_string(achieved)}/s"
                         + (f"  MFU {achieved / peak_flops:.1%}"
                            if peak_flops else ""))
        return "\n".join(lines)


def transformer_breakdown(cfg, batch_size: int, seq_len: int) -> FlopsProfile:
    """Analytic per-module forward profile for a TransformerConfig (MACs*2)."""
    H, L, V, F = (cfg.hidden_size, cfg.num_layers, cfg.vocab_size,
                  cfg.ffn_hidden_size)
    N, K, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    T = batch_size * seq_len                      # tokens
    E = max(cfg.moe_num_experts, 1)
    topk = cfg.moe_top_k if cfg.moe_num_experts else 1

    qkv_params = H * (N * D) + 2 * H * (K * D)
    attn_params = qkv_params + (N * D) * H
    if cfg.activation == "swiglu":
        mlp_params_one = 3 * H * F
        mlp_flops_tok = 2 * 3 * H * F
    else:
        mlp_params_one = 2 * H * F
        mlp_flops_tok = 2 * 2 * H * F
    mlp_params = mlp_params_one * E
    router_params = H * cfg.moe_num_experts if cfg.moe_num_experts else 0

    per_module = {
        "embedding": {"params": V * H, "flops": 0.0},
        "attention": {"params": L * attn_params,
                      "flops": T * L * (2 * attn_params
                                        + 4 * seq_len * N * D)},
        "mlp": {"params": L * (mlp_params + router_params),
                "flops": T * L * (mlp_flops_tok * topk
                                  + 2 * router_params)},
        "norms": {"params": L * (2 * H) * (2 if cfg.norm == "layernorm" else 1)
                  + H, "flops": T * L * 8 * H},
        "lm_head": {"params": 0 if cfg.tie_embeddings else H * V,
                    "flops": T * 2 * H * V},
    }
    if cfg.position == "learned":
        per_module["embedding"]["params"] += cfg.max_seq_len * H
    total_params = sum(int(m["params"]) for m in per_module.values())
    total_flops = sum(m["flops"] for m in per_module.values())
    return FlopsProfile(total_params=total_params, total_flops=total_flops,
                        per_module=per_module, batch_size=batch_size,
                        seq_len=seq_len)


def get_model_profile(model, batch_size: int, seq_len: int,
                      print_profile: bool = False,
                      measured: bool = False,
                      output_file: Optional[str] = None
                      ) -> Tuple[float, float, int]:
    """Reference get_model_profile parity: returns (flops, macs, params).

    ``measured=True`` additionally RUNS the model and prints the
    ``print_model_profile`` analog (reference profiler.py:239): a depth tree
    with measured wall latency, XLA-counted GFLOPs, params, and achieved
    FLOPS per module — depth 0 model, depth 1 embedding/layers/head, depth
    2 every individual layer block."""
    prof = transformer_breakdown(model.config, batch_size, seq_len)
    if measured:
        mp = measured_model_profile(model, batch_size, seq_len)
        text = mp.table()
        if output_file:
            with open(output_file, "w") as fh:
                fh.write(text + "\n")
        elif print_profile:
            print(text)
        return mp.total_flops, mp.total_flops / 2, prof.total_params
    if print_profile:
        print(prof.table())
    return prof.total_flops, prof.total_flops / 2, prof.total_params


# -- measured per-module tree (print_model_profile analog) -------------------


@dataclasses.dataclass
class ModuleMeasurement:
    name: str
    depth: int
    latency_s: float              # measured median wall time
    flops: float                  # XLA cost analysis (analytic fallback)
    params: int

    def achieved_flops_per_s(self) -> float:
        return self.flops / self.latency_s if self.latency_s > 0 else 0.0


@dataclasses.dataclass
class MeasuredProfile:
    """The measured module tree. ``modules`` is depth-first: the depth-0
    root, then each depth-1 group with its depth-2 children."""

    modules: list
    total_flops: float
    total_latency_s: float
    batch_size: int
    seq_len: int

    def table(self) -> str:
        head = (f"{'module':<24}{'params':>10}{'latency':>12}"
                f"{'GFLOPs':>10}{'FLOPS':>15}{'% time':>8}")
        lines = ["-" * 28 + " measured model profile " + "-" * 28,
                 f"batch {self.batch_size} x seq {self.seq_len} "
                 f"(forward; segment-jitted measurement)", head, "-" * 80]
        for m in self.modules:
            pct = (m.latency_s / self.total_latency_s
                   if self.total_latency_s else 0.0)
            lines.append(
                f"{'  ' * m.depth + m.name:<24}{params_string(m.params):>10}"
                f"{duration_string(m.latency_s):>12}"
                f"{m.flops / 1e9:>10.3f}"
                f"{flops_string(m.achieved_flops_per_s(), 1):>15}"
                f"{pct:>7.1%}")
        lines.append("-" * 80)
        return "\n".join(lines)


def _median_time(fn, args, repeats: int, warmup: int) -> float:
    import time as _time

    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(repeats):
        t0 = _time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(_time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def _segment_flops(jitted, args, fallback: float) -> float:
    try:
        cost = compiled_cost(jitted.lower(*args).compile())
    except Exception:
        return fallback
    return cost.get("flops") or fallback


def measured_model_profile(model, batch_size: int, seq_len: int,
                           repeats: int = 5, warmup: int = 2
                           ) -> MeasuredProfile:
    """Measure the forward pass module-by-module (reference
    print_model_profile, profiler.py:239 — there via module hooks; under
    jit, each stage becomes its own compiled segment timed with a device
    fence). Segment boundaries follow the model's real stages — embedding,
    every layer block (`_layer_forward`, the SAME function the full forward
    scans), final norm + lm_head — so per-layer numbers are the truth of
    the layer program, modulo cross-stage fusion the monolithic jit would
    additionally do (the reference's hooks perturb timing the same way)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.transformer import (_layer_forward, _norm, eval_config,
                                      head_logits, window_table)

    cfg = eval_config(model.config)
    # per-layer sliding windows (GPT-Neo attention_layers): each timed layer
    # must see ITS window, exactly as forward()'s scan passes it — else
    # 'local' layers would be profiled as all-global attention
    win_table = window_table(cfg) if cfg.attention_layers else None
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size,
                                         (batch_size, seq_len)), jnp.int32)
    positions = jnp.arange(seq_len)

    # --- embedding segment (mirrors forward()'s embed stage) ---
    def embed_fn(p, i):
        x = p["embed"]["tokens"][i].astype(cfg.dtype)
        if cfg.position == "learned":
            x = x + p["pos"][positions].astype(cfg.dtype)
        if cfg.type_vocab_size > 0:
            x = x + p["type_embed"][
                jnp.zeros_like(i)].astype(cfg.dtype)
        if cfg.embed_norm:
            x = _norm(x, p["embed_norm"]["scale"],
                      p["embed_norm"].get("bias"), "layernorm", cfg.norm_eps)
        return x

    embed_jit = jax.jit(embed_fn)
    x = embed_jit(params, ids)

    # --- one compiled layer program, timed per layer's weights ---
    def layer_fn(layer, h, window):
        return _layer_forward(cfg, h, layer, None, positions,
                              window=window)[0]

    layer_jit = jax.jit(layer_fn)

    def win(i: int):
        return win_table[i] if win_table is not None else None
    head_jit = jax.jit(lambda p, h: head_logits(p, h, cfg))

    analytic = transformer_breakdown(cfg, batch_size, seq_len)
    L = max(cfg.num_layers, 1)
    per_layer_analytic = (analytic.per_module["attention"]["flops"]
                          + analytic.per_module["mlp"]["flops"]
                          + analytic.per_module["norms"]["flops"]) / L

    def leaf_params(tree):
        return sum(int(p.size) for p in jax.tree.leaves(tree))

    layer0 = jax.tree.map(lambda p: p[0], params["layers"])
    embed_flops = _segment_flops(embed_jit, (params, ids), 0.0)
    layer_flops = _segment_flops(layer_jit, (layer0, x, win(0)),
                                 per_layer_analytic)
    head_flops = _segment_flops(head_jit, (params, x),
                                analytic.per_module["lm_head"]["flops"])

    t_embed = _median_time(embed_jit, (params, ids), repeats, warmup)
    layer_meas = []
    h = x
    for i in range(cfg.num_layers):
        layer_i = jax.tree.map(lambda p: p[i], params["layers"])
        t_i = _median_time(layer_jit, (layer_i, h, win(i)), repeats, warmup)
        layer_meas.append(t_i)
        h = layer_jit(layer_i, h, win(i))
    t_head = _median_time(head_jit, (params, h), repeats, warmup)

    layer_params = leaf_params(params["layers"]) // max(cfg.num_layers, 1)
    embed_params = leaf_params({k: v for k, v in params.items()
                                if k in ("embed", "pos", "type_embed",
                                         "embed_norm")})
    head_params = leaf_params({k: v for k, v in params.items()
                               if k in ("final_norm", "lm_head", "lm_head_b")})

    total_lat = t_embed + sum(layer_meas) + t_head
    total_flops = embed_flops + layer_flops * cfg.num_layers + head_flops
    modules = [
        ModuleMeasurement("model", 0, total_lat, total_flops,
                          leaf_params(params)),
        ModuleMeasurement("embedding", 1, t_embed, embed_flops, embed_params),
        ModuleMeasurement(f"layers (x{cfg.num_layers})", 1, sum(layer_meas),
                          layer_flops * cfg.num_layers,
                          layer_params * cfg.num_layers),
    ]
    for i, t_i in enumerate(layer_meas):
        modules.append(ModuleMeasurement(f"layer.{i}", 2, t_i, layer_flops,
                                         layer_params))
    modules.append(ModuleMeasurement("final_norm+lm_head", 1, t_head,
                                     head_flops, head_params))
    return MeasuredProfile(modules=modules, total_flops=total_flops,
                           total_latency_s=total_lat, batch_size=batch_size,
                           seq_len=seq_len)
