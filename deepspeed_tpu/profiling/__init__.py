"""Profiling — analog of ``deepspeed/profiling`` (flops profiler) plus the
jax-profiler trace hook (the NVTX/nsys analog)."""

from .flops_profiler import (FlopsProfile, MeasuredProfile, compiled_cost,
                             duration_string, flops_string, get_model_profile,
                             measured_model_profile, number_string,
                             params_string, transformer_breakdown)  # noqa: F401
