"""Metrics monitoring — analog of ``deepspeed/monitor/`` (``MonitorMaster``
monitor.py:29 fanning (name, value, step) events out to TensorBoard / WandB /
CSV writers, rank-0 gated).

These writers are **exporters of the observability metrics registry**
(``deepspeed_tpu.observability.metrics.MetricsRegistry``), not an independent
event path: the engine publishes loss/lr/grad-norm/throughput into the
registry and hands ``registry.publish(step)``'s scalarized snapshot to its
own ``MonitorMaster`` through the ``write_events`` contract below (the
registry is a process singleton, so the engine deliberately does NOT attach
its monitor as a registry-global exporter — that would cross-feed every
engine's metrics into every other engine's monitors). ``write_events`` stays
public, but nothing in the engine calls it with a hand-built event list
anymore; ``registry.attach_exporter(master)`` remains available for user
code that wants unscoped fan-out.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, TextIO, Tuple

import jax

from ..config.config import MonitorConfig
from ..utils.logging import logger

Event = Tuple[str, float, int]


class BaseWriter:
    enabled = False

    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class CSVMonitor(BaseWriter):
    """Reference monitor/csv_monitor.py: one csv file per metric name.

    File handles are opened once per metric, kept in ``self._files``, and
    line-buffered — ``flush()``/``close()`` complete the lifecycle so short
    runs cannot lose tail rows to an unflushed buffer (and steady-state
    writes skip the per-event open/close syscall churn)."""

    def __init__(self, config) -> None:
        self.enabled = config.enabled and jax.process_index() == 0
        self.output_path = config.output_path or "./csv_monitor"
        self.job_name = config.job_name
        self._files: Dict[str, TextIO] = {}
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def _handle(self, name: str) -> TextIO:
        fh = self._files.get(name)
        if fh is None or fh.closed:
            fname = os.path.join(self.output_path, self.job_name,
                                 name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            fh = open(fname, "a", newline="", buffering=1)
            if new:
                csv.writer(fh).writerow(["step", name])
            self._files[name] = fh
        return fh

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            csv.writer(self._handle(name)).writerow([step, value])

    def flush(self) -> None:
        for fh in self._files.values():
            if not fh.closed:
                fh.flush()

    def close(self) -> None:
        for fh in self._files.values():
            if not fh.closed:
                fh.close()
        self._files.clear()
        self.enabled = False   # terminal, like the TB/WandB writers


class TensorBoardMonitor(BaseWriter):
    def __init__(self, config) -> None:
        self.enabled = False
        self.summary_writer = None
        if config.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                path = os.path.join(config.output_path or "./runs", config.job_name)
                self.summary_writer = SummaryWriter(log_dir=path)
                self.enabled = True
            except Exception as e:  # tensorboard not installed
                logger.warning(f"tensorboard unavailable ({e}); disabling writer")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self.summary_writer.add_scalar(name, value, step)

    def flush(self) -> None:
        if self.enabled:
            self.summary_writer.flush()

    def close(self) -> None:
        if self.enabled:
            self.summary_writer.close()
            self.enabled = False


class WandbMonitor(BaseWriter):
    def __init__(self, config) -> None:
        self.enabled = False
        if config.enabled and jax.process_index() == 0:
            try:
                import wandb

                wandb.init(project=config.project, group=config.group,
                           entity=config.team)
                self._wandb = wandb
                self.enabled = True
            except Exception as e:
                logger.warning(f"wandb unavailable ({e}); disabling writer")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self._wandb.log({name: value}, step=step)

    def close(self) -> None:
        if self.enabled:
            self._wandb.finish()
            self.enabled = False


class MonitorMaster(BaseWriter):
    """Fan-out to all enabled writers (reference monitor/monitor.py:29).
    Attach to a ``MetricsRegistry`` via ``registry.attach_exporter(master)``
    to receive its ``publish(step)`` snapshots."""

    def __init__(self, config: Optional[MonitorConfig] = None):
        config = config or MonitorConfig()
        self.writers: List[BaseWriter] = [
            TensorBoardMonitor(config.tensorboard),
            WandbMonitor(config.wandb),
            CSVMonitor(config.csv_monitor),
        ]
        self.enabled = any(w.enabled for w in self.writers)

    def write_events(self, events: List[Event]) -> None:
        for w in self.writers:
            w.write_events(events)
        self.flush()

    def flush(self) -> None:
        for w in self.writers:
            w.flush()

    def close(self) -> None:
        for w in self.writers:
            w.close()
