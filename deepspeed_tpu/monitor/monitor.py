"""Metrics monitoring — analog of ``deepspeed/monitor/`` (``MonitorMaster``
monitor.py:29 fanning (name, value, step) events out to TensorBoard / WandB /
CSV writers, rank-0 gated)."""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

import jax

from ..config.config import MonitorConfig
from ..utils.logging import logger

Event = Tuple[str, float, int]


class BaseWriter:
    enabled = False

    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass


class CSVMonitor(BaseWriter):
    """Reference monitor/csv_monitor.py: one csv file per metric name."""

    def __init__(self, config) -> None:
        self.enabled = config.enabled and jax.process_index() == 0
        self.output_path = config.output_path or "./csv_monitor"
        self.job_name = config.job_name
        self._files = {}
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            fname = os.path.join(self.output_path, self.job_name,
                                 name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as fh:
                w = csv.writer(fh)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


class TensorBoardMonitor(BaseWriter):
    def __init__(self, config) -> None:
        self.enabled = False
        self.summary_writer = None
        if config.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                path = os.path.join(config.output_path or "./runs", config.job_name)
                self.summary_writer = SummaryWriter(log_dir=path)
                self.enabled = True
            except Exception as e:  # tensorboard not installed
                logger.warning(f"tensorboard unavailable ({e}); disabling writer")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self.summary_writer.add_scalar(name, value, step)

    def flush(self) -> None:
        if self.enabled:
            self.summary_writer.flush()


class WandbMonitor(BaseWriter):
    def __init__(self, config) -> None:
        self.enabled = False
        if config.enabled and jax.process_index() == 0:
            try:
                import wandb

                wandb.init(project=config.project, group=config.group,
                           entity=config.team)
                self._wandb = wandb
                self.enabled = True
            except Exception as e:
                logger.warning(f"wandb unavailable ({e}); disabling writer")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self._wandb.log({name: value}, step=step)


class MonitorMaster(BaseWriter):
    """Fan-out to all enabled writers (reference monitor/monitor.py:29)."""

    def __init__(self, config: Optional[MonitorConfig] = None):
        config = config or MonitorConfig()
        self.writers: List[BaseWriter] = [
            TensorBoardMonitor(config.tensorboard),
            WandbMonitor(config.wandb),
            CSVMonitor(config.csv_monitor),
        ]
        self.enabled = any(w.enabled for w in self.writers)

    def write_events(self, events: List[Event]) -> None:
        for w in self.writers:
            w.write_events(events)

    def flush(self) -> None:
        for w in self.writers:
            w.flush()
