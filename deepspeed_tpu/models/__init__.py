from .core import Model, cast_floating, param_count, resolve_param_specs
from .presets import available_presets, create_model, transformer_config
from .simple import random_batches, random_token_batches, simple_model
from .transformer import (TransformerConfig, build_model, cross_entropy_loss,
                          forward, init_params, param_axes)

__all__ = [
    "Model", "cast_floating", "param_count", "resolve_param_specs",
    "available_presets", "create_model", "transformer_config",
    "random_batches", "random_token_batches", "simple_model",
    "TransformerConfig", "build_model", "cross_entropy_loss", "forward",
    "init_params", "param_axes",
]
