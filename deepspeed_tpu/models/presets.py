"""Named model presets — the families the reference targets with injection
policies (module_inject/containers/{gpt2,opt,bloom,gptj,gptneo,gptneox,llama}
and the BASELINE configs: GPT-2 125M, OPT-1.3B, Llama-7B, BLOOM-7B)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp

from .core import Model
from .transformer import TransformerConfig, build_model

# family defaults: (norm, position, activation, tie)
_FAMILIES: Dict[str, Dict[str, Any]] = {
    "gpt2": dict(norm="layernorm", position="learned", activation="gelu",
                 tie_embeddings=True),
    "opt": dict(norm="layernorm", position="learned", activation="relu",
                tie_embeddings=True),
    "bloom": dict(norm="layernorm", position="alibi", activation="gelu",
                  tie_embeddings=True, embed_norm=True),
    "gptj": dict(norm="layernorm", position="rope", activation="gelu",
                 tie_embeddings=False, parallel_residual=True,
                 lm_head_bias=True),
    "gptneox": dict(norm="layernorm", position="rope", activation="gelu",
                    tie_embeddings=False, parallel_residual=True),
    # GPT-Neo: alternating global/local (sliding-window 256) attention,
    # UNSCALED attention scores (HF GPTNeoSelfAttention has no 1/sqrt(d))
    "gptneo": dict(norm="layernorm", position="learned", activation="gelu",
                   tie_embeddings=True, attention_scale=1.0,
                   attention_layers=("global", "local"),
                   attention_window=256),
    # CLIP text encoder (reference containers/clip.py HFCLIPLayerPolicy —
    # the Stable Diffusion text tower): pre-LN, CAUSAL attention,
    # quick_gelu; tie_embeddings so logits = hidden @ E^T (the encoder
    # surface — parity tests invert it)
    "clip": dict(norm="layernorm", position="learned",
                 activation="quick_gelu", tie_embeddings=True, causal=True),
    "bert": dict(norm="layernorm", norm_position="post", position="learned",
                 activation="gelu-exact", tie_embeddings=True, causal=False,
                 embed_norm=True, type_vocab_size=2, final_norm=False,
                 norm_eps=1e-12),
    "distilbert": dict(norm="layernorm", norm_position="post",
                       position="learned", activation="gelu-exact",
                       tie_embeddings=True, causal=False, embed_norm=True,
                       final_norm=False, norm_eps=1e-12),
    "llama": dict(norm="rmsnorm", position="rope", activation="swiglu",
                  tie_embeddings=False, norm_eps=1e-6),
    "mistral": dict(norm="rmsnorm", position="rope", activation="swiglu",
                    tie_embeddings=False, norm_eps=1e-6),
}

# size presets: hidden, layers, heads, kv_heads, vocab, max_seq
_SIZES: Dict[str, Dict[str, Any]] = {
    "gpt2-125m": dict(family="gpt2", hidden_size=768, num_layers=12, num_heads=12,
                      vocab_size=50257, max_seq_len=1024),
    "gpt2-350m": dict(family="gpt2", hidden_size=1024, num_layers=24, num_heads=16,
                      vocab_size=50257, max_seq_len=1024),
    "gpt2-1.3b": dict(family="gpt2", hidden_size=2048, num_layers=24, num_heads=32,
                      vocab_size=50257, max_seq_len=2048),
    "opt-125m": dict(family="opt", hidden_size=768, num_layers=12, num_heads=12,
                     vocab_size=50272, max_seq_len=2048),
    "opt-1.3b": dict(family="opt", hidden_size=2048, num_layers=24, num_heads=32,
                     vocab_size=50272, max_seq_len=2048),
    "opt-6.7b": dict(family="opt", hidden_size=4096, num_layers=32, num_heads=32,
                     vocab_size=50272, max_seq_len=2048),
    "llama-7b": dict(family="llama", hidden_size=4096, num_layers=32, num_heads=32,
                     vocab_size=32000, max_seq_len=4096, ffn_hidden_size=11008),
    "llama-13b": dict(family="llama", hidden_size=5120, num_layers=40, num_heads=40,
                      vocab_size=32000, max_seq_len=4096, ffn_hidden_size=13824),
    "bloom-7b": dict(family="bloom", hidden_size=4096, num_layers=30, num_heads=32,
                     vocab_size=250880, max_seq_len=2048),
    "gptj-6b": dict(family="gptj", hidden_size=4096, num_layers=28,
                    num_heads=16, vocab_size=50400, max_seq_len=2048,
                    rotary_dim=64),
    "gptneo-1.3b": dict(family="gptneo", hidden_size=2048, num_layers=24,
                        num_heads=16, vocab_size=50257, max_seq_len=2048),
    "gptneo-2.7b": dict(family="gptneo", hidden_size=2560, num_layers=32,
                        num_heads=20, vocab_size=50257, max_seq_len=2048),
    "gptneox-20b": dict(family="gptneox", hidden_size=6144, num_layers=44,
                        num_heads=64, vocab_size=50432, max_seq_len=2048,
                        rotary_dim=24),    # rotary_pct 0.25 of head_dim 96
    "bert-base": dict(family="bert", hidden_size=768, num_layers=12,
                      num_heads=12, vocab_size=30522, max_seq_len=512),
    "bert-large": dict(family="bert", hidden_size=1024, num_layers=24,
                       num_heads=16, vocab_size=30522, max_seq_len=512),
    "distilbert-base": dict(family="distilbert", hidden_size=768,
                            num_layers=6, num_heads=12, vocab_size=30522,
                            max_seq_len=512),
    # tiny debug models (reference tests/unit/simple_model.py scale)
    "tiny": dict(family="gpt2", hidden_size=64, num_layers=2, num_heads=4,
                 vocab_size=256, max_seq_len=128),
    "tiny-llama": dict(family="llama", hidden_size=64, num_layers=2, num_heads=4,
                       num_kv_heads=2, vocab_size=256, max_seq_len=128,
                       ffn_hidden_size=128),
    "tiny-opt": dict(family="opt", hidden_size=64, num_layers=2, num_heads=4,
                     vocab_size=256, max_seq_len=128),
    "tiny-bloom": dict(family="bloom", hidden_size=64, num_layers=2, num_heads=4,
                       vocab_size=256, max_seq_len=128),
    "tiny-gptj": dict(family="gptj", hidden_size=64, num_layers=2,
                      num_heads=4, vocab_size=256, max_seq_len=128,
                      rotary_dim=8),
    "tiny-gptneox": dict(family="gptneox", hidden_size=64, num_layers=2,
                         num_heads=4, vocab_size=256, max_seq_len=128,
                         rotary_dim=4),
    "tiny-gptneo": dict(family="gptneo", hidden_size=64, num_layers=2,
                        num_heads=4, vocab_size=256, max_seq_len=128,
                        attention_window=8),
    "tiny-clip": dict(family="clip", hidden_size=64, num_layers=2,
                      num_heads=4, vocab_size=256, max_seq_len=77),
    "clip-vit-l-text": dict(family="clip", hidden_size=768, num_layers=12,
                            num_heads=12, ffn_hidden_size=3072,
                            vocab_size=49408, max_seq_len=77),
    "tiny-bert": dict(family="bert", hidden_size=64, num_layers=2,
                      num_heads=4, vocab_size=256, max_seq_len=128),
    "tiny-distilbert": dict(family="distilbert", hidden_size=64,
                            num_layers=2, num_heads=4, vocab_size=256,
                            max_seq_len=128),
    # GShard/Switch-style 8-expert GPT (BASELINE tracked config #4)
    "moe-tiny": dict(family="gpt2", hidden_size=64, num_layers=2, num_heads=4,
                     vocab_size=256, max_seq_len=128, moe_num_experts=8),
    "moe-gpt-125m-8e": dict(family="gpt2", hidden_size=768, num_layers=12,
                            num_heads=12, vocab_size=50257, max_seq_len=1024,
                            moe_num_experts=8),
    "moe-gpt-350m-8e": dict(family="gpt2", hidden_size=1024, num_layers=24,
                            num_heads=16, vocab_size=50257, max_seq_len=1024,
                            moe_num_experts=8),
}


def transformer_config(preset: str, dtype=jnp.float32, **overrides) -> TransformerConfig:
    if preset not in _SIZES:
        raise ValueError(f"unknown preset '{preset}' (known: {sorted(_SIZES)})")
    spec = dict(_SIZES[preset])
    family = spec.pop("family")
    kwargs = dict(_FAMILIES[family])
    kwargs.update(spec)
    kwargs.update(overrides)
    return TransformerConfig(dtype=dtype, **kwargs)


def create_model(preset: str, dtype=jnp.float32, **overrides) -> Model:
    cfg = transformer_config(preset, dtype=dtype, **overrides)
    return build_model(cfg, name=preset)


def available_presets():
    return sorted(_SIZES)
