"""Model/module system: params as pytrees + logical-axis metadata.

The reference is torch ``nn.Module``-based; its parallelism is imposed from
outside by hooks and weight surgery (``module_inject``, ZeRO param hooks).
The TPU-native design inverts this: a model is a pair of pure functions

    init(rng) -> params            (nested dict of jnp arrays)
    apply(params, batch) -> out

plus a **logical-axis tree**: for every param, a tuple naming each dimension
("vocab", "embed", "heads", "mlp", "layers", ...). Parallelism = a set of
*rules* mapping logical axes to mesh axes (t5x/flax-partitioning pattern):
tensor parallelism maps heads/mlp/vocab → "model"; ZeRO-3 maps the largest
still-unmapped dimension → "data". Engines consume only (params, axes), so
every parallel strategy composes with every model with no model changes —
the TPU answer to the reference's per-architecture injection policies.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

# the logical-axis vocabulary, rule sets and spec resolution live in the
# sharding rule registry (parallel/rules.py — the single source of truth
# tools/tpushard audits against); re-exported here because models declare
# their axes in these terms
from ..parallel.rules import (AxesTree, BATCH, DEFAULT_TP_RULES, EMBED,  # noqa: F401
                              EXPERT, HEAD_DIM, HEADS, KV_HEADS, LAYERS,
                              MLP, SEQ, VOCAB, logical_to_spec,
                              resolve_param_specs)


@dataclasses.dataclass
class Model:
    """A model bundle: pure init/apply + axis metadata + loss.

    ``apply(params, batch, *, rngs=None, **kw)`` returns model output;
    ``loss_fn(params, batch)`` returns scalar loss (what the engine
    differentiates). ``axes`` mirrors the params tree with logical axis tuples.
    """

    init: Callable[..., Any]
    apply: Callable[..., Any]
    loss_fn: Callable[..., Any]
    axes: AxesTree
    config: Any = None
    name: str = "model"
    pipelined: bool = False     # loss_fn consumes a whole (M, mb, ...) stack
    num_stages: int = 1
    # custom (loss, grads) producer — set by pipelinize_model to the explicit
    # 1F1B executor; engines prefer it over jax.value_and_grad(loss_fn)
    grad_fn: Optional[Callable[..., Any]] = None
    # eval-mode loss: same semantics as loss_fn but with training regularisers
    # (dropout, random-LTD) disabled via a config COPY — engines must not
    # toggle shared config state to get eval behavior
    eval_loss_fn: Optional[Callable[..., Any]] = None
    # (rng, lo, blen) -> layers subtree for layers [lo, lo+blen), identical
    # to the corresponding slice of init(rng)["layers"] — lets the ZeRO-3
    # param-offload tier initialise one block at a time without ever
    # materialising the full stack
    init_layer_block: Optional[Callable[..., Any]] = None


def param_count(params: Any) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))


def tree_bytes(params: Any) -> int:
    return sum(int(p.size) * p.dtype.itemsize for p in jax.tree.leaves(params))


def cast_floating(tree: Any, dtype) -> Any:
    """Cast floating leaves to ``dtype`` (precision plumbing)."""
    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)
