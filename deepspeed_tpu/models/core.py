"""Model/module system: params as pytrees + logical-axis metadata.

The reference is torch ``nn.Module``-based; its parallelism is imposed from
outside by hooks and weight surgery (``module_inject``, ZeRO param hooks).
The TPU-native design inverts this: a model is a pair of pure functions

    init(rng) -> params            (nested dict of jnp arrays)
    apply(params, batch) -> out

plus a **logical-axis tree**: for every param, a tuple naming each dimension
("vocab", "embed", "heads", "mlp", "layers", ...). Parallelism = a set of
*rules* mapping logical axes to mesh axes (t5x/flax-partitioning pattern):
tensor parallelism maps heads/mlp/vocab → "model"; ZeRO-3 maps the largest
still-unmapped dimension → "data". Engines consume only (params, axes), so
every parallel strategy composes with every model with no model changes —
the TPU answer to the reference's per-architecture injection policies.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

# logical axis vocabulary
BATCH = "batch"
SEQ = "seq"
LAYERS = "layers"    # scanned layer stack dim — never sharded (scan carries it)
VOCAB = "vocab"
EMBED = "embed"
HEADS = "heads"      # attention heads (TP-sharded)
KV_HEADS = "kv_heads"
HEAD_DIM = "head_dim"
MLP = "mlp"          # ffn hidden (TP-sharded)
EXPERT = "expert"    # MoE expert dim

AxesTree = Any       # pytree of tuples of logical axis names, or None leaves


@dataclasses.dataclass
class Model:
    """A model bundle: pure init/apply + axis metadata + loss.

    ``apply(params, batch, *, rngs=None, **kw)`` returns model output;
    ``loss_fn(params, batch)`` returns scalar loss (what the engine
    differentiates). ``axes`` mirrors the params tree with logical axis tuples.
    """

    init: Callable[..., Any]
    apply: Callable[..., Any]
    loss_fn: Callable[..., Any]
    axes: AxesTree
    config: Any = None
    name: str = "model"
    pipelined: bool = False     # loss_fn consumes a whole (M, mb, ...) stack
    num_stages: int = 1
    # custom (loss, grads) producer — set by pipelinize_model to the explicit
    # 1F1B executor; engines prefer it over jax.value_and_grad(loss_fn)
    grad_fn: Optional[Callable[..., Any]] = None
    # eval-mode loss: same semantics as loss_fn but with training regularisers
    # (dropout, random-LTD) disabled via a config COPY — engines must not
    # toggle shared config state to get eval behavior
    eval_loss_fn: Optional[Callable[..., Any]] = None
    # (rng, lo, blen) -> layers subtree for layers [lo, lo+blen), identical
    # to the corresponding slice of init(rng)["layers"] — lets the ZeRO-3
    # param-offload tier initialise one block at a time without ever
    # materialising the full stack
    init_layer_block: Optional[Callable[..., Any]] = None


# ---------------------------------------------------------------------------
# logical-axis → PartitionSpec resolution
# ---------------------------------------------------------------------------

# default TP rules (Megatron pattern): column-parallel on heads/mlp/vocab,
# row-parallel contractions produce partial sums that XLA psums over "model".
DEFAULT_TP_RULES: Dict[str, Optional[str]] = {
    VOCAB: MODEL_AXIS,
    HEADS: MODEL_AXIS,
    KV_HEADS: MODEL_AXIS,
    MLP: MODEL_AXIS,
    EXPERT: None,          # expert dim handled by the MoE layer itself
    "pipe_stage": "pipe",  # pipelined models: stage dim over the pipe axis
}


def logical_to_spec(axes: Optional[Tuple[str, ...]],
                    shape: Tuple[int, ...],
                    rules: Dict[str, Optional[str]],
                    fsdp_axis: Optional[str] = None,
                    fsdp_min_size: int = 2 ** 14) -> P:
    """Resolve one param's logical axes to a PartitionSpec.

    1. map each logical axis through ``rules`` (TP placement);
    2. if ``fsdp_axis`` is set (ZeRO-3), additionally shard the largest
       still-unmapped dimension over it — unless the param is tiny
       (< fsdp_min_size elements, the reference's
       stage3_param_persistence_threshold concept: small params stay
       replicated to avoid gather latency for no memory win).
    """
    if axes is None:
        return P()
    mesh_axes: list = [rules.get(a) for a in axes]
    # never shard the scan-carried layer dim
    mesh_axes = [None if a == LAYERS else m for a, m in zip(axes, mesh_axes)]
    if fsdp_axis is not None:
        # a mesh axis may appear once per PartitionSpec: drop components of
        # the (possibly composite) fsdp axis already consumed by TP/EP rules
        used = set()
        for m in mesh_axes:
            if m is None:
                continue
            used.update(m if isinstance(m, tuple) else (m,))
        want = fsdp_axis if isinstance(fsdp_axis, tuple) else (fsdp_axis,)
        free = tuple(a for a in want if a not in used)
        size = 1
        for s in shape:
            size *= s
        if free and size >= fsdp_min_size:
            candidates = [i for i, (a, m) in enumerate(zip(axes, mesh_axes))
                          if m is None and a != LAYERS]
            if candidates:
                best = max(candidates, key=lambda i: shape[i])
                mesh_axes[best] = free if len(free) > 1 else free[0]
    return P(*mesh_axes)


def resolve_param_specs(params: Any, axes: AxesTree,
                        rules: Optional[Dict[str, Optional[str]]] = None,
                        fsdp_axis: Optional[str] = None,
                        fsdp_min_size: int = 2 ** 14) -> Any:
    """Params tree + axes tree → PartitionSpec tree."""
    rules = dict(DEFAULT_TP_RULES if rules is None else rules)

    def one(p, ax):
        return logical_to_spec(ax, jnp.shape(p), rules, fsdp_axis, fsdp_min_size)

    return jax.tree.map(one, params, axes,
                        is_leaf=lambda x: x is None or (isinstance(x, tuple)
                                                        and all(isinstance(e, str) for e in x)))


def param_count(params: Any) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))


def tree_bytes(params: Any) -> int:
    return sum(int(p.size) * p.dtype.itemsize for p in jax.tree.leaves(params))


def cast_floating(tree: Any, dtype) -> Any:
    """Cast floating leaves to ``dtype`` (precision plumbing)."""
    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)
