"""Tiny fixture models for tests — analog of reference
tests/unit/simple_model.py (SimpleModel :18, SimpleMoEModel :70, ...)."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .core import EMBED, MLP, Model


def simple_model(hidden_dim: int = 10, nlayers: int = 2) -> Model:
    """Linear stack + MSE head; batch = {"x": (B,H), "y": (B,1)}."""

    def init(rng):
        keys = jax.random.split(rng, nlayers + 1)
        params = {f"linear_{i}": {
            "w": jax.random.normal(keys[i], (hidden_dim, hidden_dim)) * 0.1,
            "b": jnp.zeros((hidden_dim,))} for i in range(nlayers)}
        params["head"] = {"w": jax.random.normal(keys[-1], (hidden_dim, 1)) * 0.1,
                          "b": jnp.zeros((1,))}
        return params

    def apply(params, batch):
        h = batch["x"]
        for i in range(nlayers):
            h = jax.nn.relu(h @ params[f"linear_{i}"]["w"] + params[f"linear_{i}"]["b"])
        return h @ params["head"]["w"] + params["head"]["b"]

    def loss_fn(params, batch):
        pred = apply(params, batch)
        return jnp.mean(jnp.square(pred - batch["y"]))

    axes: Dict[str, Any] = {f"linear_{i}": {"w": (EMBED, MLP), "b": (MLP,)}
                            for i in range(nlayers)}
    axes["head"] = {"w": (EMBED, None), "b": (None,)}
    return Model(init=init, apply=apply, loss_fn=loss_fn, axes=axes, name="simple")


def random_batches(rng: jax.Array, n: int, batch_size: int, hidden_dim: int = 10):
    """Deterministic synthetic regression data (reference random_dataloader)."""
    batches = []
    for i in range(n):
        k1, k2, rng = jax.random.split(rng, 3)
        x = jax.random.normal(k1, (batch_size, hidden_dim))
        w_true = jnp.arange(hidden_dim, dtype=jnp.float32)[:, None] / hidden_dim
        y = x @ w_true + 0.01 * jax.random.normal(k2, (batch_size, 1))
        batches.append({"x": x, "y": y})
    return batches


def random_token_batches(rng: jax.Array, n: int, batch_size: int, seq_len: int,
                         vocab_size: int):
    batches = []
    for i in range(n):
        k, rng = jax.random.split(rng)
        ids = jax.random.randint(k, (batch_size, seq_len), 0, vocab_size)
        batches.append({"input_ids": ids})
    return batches
