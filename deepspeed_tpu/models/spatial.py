"""Spatial (diffusers-family) model blocks — the UNet/VAE consumer of the
spatial kernels.

Reference: ``module_inject/containers/unet.py`` + ``containers/vae.py`` and
the diffusers ``generic_policies`` path (``module_inject/replace_policy.py:26``
``UNetPolicy``/``VAEPolicy``), which swap a diffusers UNet/VAE's GroupNorm
and attention modules for the fused CUDA ops. Here the same coverage is a
small JAX module family whose hot ops route through ``ops/spatial.py``:

  * ``resnet_block``   — GroupNorm → silu → conv3x3 ×2 + skip (the
    diffusers ResnetBlock2D shape; VAE decoder workhorse)
  * ``attention_block`` — GroupNorm → qkv over flattened H·W tokens →
    non-causal attention (``diffusers_attention``) → proj + residual (the
    AttentionBlock/Transformer2D single-head spatial attention)
  * ``mid_block``      — resnet → attention → resnet (UNet/VAE mid block)

Layout is NHWC (channels-last — the TPU-native conv layout; diffusers'
NCHW weights transpose at import). ``use_kernel=None`` auto-routes to the
Pallas kernels on TPU with the jnp path as oracle/fallback, the same
platform-probe discipline as the transformer stack.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _use_kernel(name: str, use_kernel: Optional[bool],
                interpret: bool) -> bool:
    """Kernel-vs-fallback routing through the ops REGISTRY (the one place
    encoding per-op platform compatibility) — explicit use_kernel/interpret
    override it, auto (None) defers to registry.is_compatible."""
    if interpret or use_kernel is True:
        return True
    if use_kernel is False:
        return False
    from ..ops.registry import is_compatible

    return is_compatible(name)


def group_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               groups: int, eps: float = 1e-6,
               use_kernel: Optional[bool] = None,
               interpret: bool = False) -> jax.Array:
    """(B, H, W, C) GroupNorm routed through the fused Pallas kernel
    (ops/spatial.py) — flattens spatial dims to the (B, HW, C) token layout
    the kernel reduces over."""
    B, H, W, C = x.shape
    tokens = x.reshape(B, H * W, C)
    if _use_kernel("fused_group_norm", use_kernel, interpret):
        from ..ops.spatial import fused_group_norm

        out = fused_group_norm(tokens, scale, bias, groups, eps=eps,
                               interpret=interpret)
    else:
        from ..ops.spatial import reference_group_norm

        out = reference_group_norm(tokens, scale, bias, groups, eps=eps)
    return out.reshape(B, H, W, C)


def conv2d(x: jax.Array, w: jax.Array, b: Optional[jax.Array] = None,
           stride: int = 1, padding: str = "SAME") -> jax.Array:
    """NHWC conv; w: (kh, kw, Cin, Cout)."""
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if b is not None:
        out = out + b
    return out


def resnet_block(x: jax.Array, p: Dict[str, Any], groups: int = 8,
                 use_kernel: Optional[bool] = None,
                 interpret: bool = False) -> jax.Array:
    """diffusers ResnetBlock2D: GN→silu→conv, GN→silu→conv, + skip
    (1x1-conv'd when channel counts differ)."""
    h = group_norm(x, p["norm1"]["scale"], p["norm1"]["bias"], groups,
                   use_kernel=use_kernel, interpret=interpret)
    h = jax.nn.silu(h)
    h = conv2d(h, p["conv1"]["w"], p["conv1"]["b"])
    h = group_norm(h, p["norm2"]["scale"], p["norm2"]["bias"], groups,
                   use_kernel=use_kernel, interpret=interpret)
    h = jax.nn.silu(h)
    h = conv2d(h, p["conv2"]["w"], p["conv2"]["b"])
    skip = x
    if "shortcut" in p:
        skip = conv2d(x, p["shortcut"]["w"], p["shortcut"]["b"])
    return skip + h


def attention_block(x: jax.Array, p: Dict[str, Any], groups: int = 8,
                    use_kernel: Optional[bool] = None,
                    interpret: bool = False) -> jax.Array:
    """diffusers AttentionBlock: GN → single-head attention over H·W
    tokens → proj, + residual (the VAE mid-block attention; reference
    diffusers_attention.py:23)."""
    B, H, W, C = x.shape
    h = group_norm(x, p["norm"]["scale"], p["norm"]["bias"], groups,
                   use_kernel=use_kernel, interpret=interpret)
    tokens = h.reshape(B, H * W, C)
    q = tokens @ p["q"]["w"] + p["q"]["b"]
    k = tokens @ p["k"]["w"] + p["k"]["b"]
    v = tokens @ p["v"]["w"] + p["v"]["b"]
    if _use_kernel("diffusers_attention", use_kernel, interpret):
        from ..ops.spatial import diffusers_attention

        attn = diffusers_attention(q[:, :, None, :], k[:, :, None, :],
                                   v[:, :, None, :], interpret=interpret)
        attn = attn[:, :, 0, :]
    else:
        from .transformer import dot_product_attention

        attn = dot_product_attention(q[:, :, None, :], k[:, :, None, :],
                                     v[:, :, None, :], None,
                                     causal=False)[:, :, 0, :]
    out = attn @ p["proj"]["w"] + p["proj"]["b"]
    return x + out.reshape(B, H, W, C)


def mid_block(x: jax.Array, p: Dict[str, Any], groups: int = 8,
              use_kernel: Optional[bool] = None,
              interpret: bool = False) -> jax.Array:
    """UNet/VAE mid block: resnet → attention → resnet."""
    x = resnet_block(x, p["resnet1"], groups, use_kernel, interpret)
    x = attention_block(x, p["attn"], groups, use_kernel, interpret)
    return resnet_block(x, p["resnet2"], groups, use_kernel, interpret)


def init_mid_block(rng: jax.Array, channels: int, k: int = 3
                   ) -> Dict[str, Any]:
    """Random init of a mid block (parity tests / smoke); conv weights
    (kh, kw, Cin, Cout)."""
    keys = jax.random.split(rng, 12)
    C = channels
    std = 0.1

    def conv(key, kh):
        return {"w": jax.random.normal(key, (kh, kh, C, C), jnp.float32) * std,
                "b": jnp.zeros((C,), jnp.float32)}

    def lin(key):
        return {"w": jax.random.normal(key, (C, C), jnp.float32) * std,
                "b": jnp.zeros((C,), jnp.float32)}

    def norm():
        return {"scale": jnp.ones((C,), jnp.float32),
                "bias": jnp.zeros((C,), jnp.float32)}

    def resnet(k0, k1):
        return {"norm1": norm(), "conv1": conv(k0, k),
                "norm2": norm(), "conv2": conv(k1, k)}

    return {
        "resnet1": resnet(keys[0], keys[1]),
        "attn": {"norm": norm(), "q": lin(keys[2]), "k": lin(keys[3]),
                 "v": lin(keys[4]), "proj": lin(keys[5])},
        "resnet2": resnet(keys[6], keys[7]),
    }
