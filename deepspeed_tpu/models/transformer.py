"""Unified decoder-only transformer (GPT-2 / Llama families).

The reference implements transformer compute three times (fused training kernel
``csrc/transformer/``, inference kernels ``csrc/transformer/inference/``, and
per-architecture injected modules). Here ONE functional decoder covers both
families through config switches:

  GPT-2 family : LayerNorm(+bias), learned positions, GELU MLP, tied embeddings
  Llama family : RMSNorm, RoPE, SwiGLU MLP, GQA (n_kv_heads < n_heads)

Layers are **stacked and scanned** (`lax.scan` over a leading layer dim) so XLA
compiles one layer program regardless of depth — the TPU-idiomatic equivalent
of the reference's per-layer kernel launch loop — with `jax.checkpoint` for
activation rematerialisation (reference: activation_checkpointing/).

Attention is pluggable: the engine can swap in the Pallas flash-attention
kernel (ops/flash_attention.py) via ``attention_impl``.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .core import (EMBED, HEADS, KV_HEADS, LAYERS, MLP, Model, SEQ, VOCAB,
                   cast_floating)


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None      # None => MHA
    ffn_hidden_size: Optional[int] = None   # None => 4*hidden (gelu) / llama rule (swiglu)
    max_seq_len: int = 1024
    norm: str = "layernorm"                 # layernorm | rmsnorm
    norm_position: str = "pre"              # pre | post (post: BERT-family
    #   encoders — LN applied AFTER each residual add, no final norm)
    position: str = "learned"               # learned | rope | alibi
    embed_norm: bool = False                # LayerNorm after embedding (BLOOM)
    activation: str = "gelu"                # gelu | relu | swiglu
    tie_embeddings: bool = True
    causal: bool = True                     # False: bidirectional encoder
    parallel_residual: bool = False         # x + attn(ln1(x)) + mlp(ln2(x))
    # GPT-Neo family: per-layer attention pattern ('global'|'local', cycled
    # over layers) with a sliding window for local layers; non-empty routes
    # attention through the windowed jnp path (the flash kernel has no
    # window operand). attention_scale: None => 1/sqrt(head_dim); GPT-Neo
    # uses unscaled scores (1.0).
    attention_layers: tuple = ()
    attention_window: int = 256
    attention_scale: Optional[float] = None
    #   (GPT-J/GPT-NeoX; GPT-J shares one LN — its import aliases ln2=ln1)
    rotary_dim: Optional[int] = None        # partial rotary: rope on the
    #   first rotary_dim dims of each head (GPT-J/NeoX), None => full head
    type_vocab_size: int = 0                # >0: token-type embeddings (BERT)
    final_norm: bool = True                 # False: no norm after the last
    #   layer (post-LN encoders norm inside the block)
    lm_head_bias: bool = False              # untied head carries a bias (GPT-J)
    norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    dropout: float = 0.0              # embed/attn-out/mlp-out dropout rate.
    #   Applied only when dropout_enabled (the TrainEngine sets it; eval and
    #   inference run dropout-free). Attention-PROBABILITY dropout is not
    #   implemented (it would live inside the flash kernel) — these are the
    #   residual-path sites of the reference transformer kernel.
    dropout_enabled: bool = False     # draws derive from activations (no rng
    #   arg in loss_fn): deterministic per (params, batch), varies per step
    dtype: Any = jnp.float32                # compute/param dtype
    scan_unroll: int = 1                    # lax.scan unroll factor over layers
    pld_enabled: bool = False               # progressive layer drop: batch
    #   carries 'pld_theta'; layer i keeps with p = 1-(1-theta)*(i+1)/L
    # random-LTD (reference data_routing/basic_layer.py:14): listed layers run
    # on a random ltd_keep-token subset; dropped tokens skip the layer
    ltd_enabled: bool = False
    ltd_layers: Optional[Tuple[int, ...]] = None  # None => all but first/last
    ltd_keep: int = 0                       # tokens kept per LTD layer; STATIC
    #   (the schedule changes it only at quantised boundaries, so each value
    #   is one extra jit trace — same discipline as the seqlen curriculum)
    act_quant_bits: int = 0           # >0: fake-quantize layer input
    #   activations (QAT; reference QuantAct) — the engine sets it from the
    #   compression schedule; STATIC (one re-jit per boundary)
    remat: bool = False                     # activation checkpointing over layers
    remat_policy: str = "full"              # full | dots (save matmul outputs,
    #   recompute elementwise/attention — reference partition_activations analog)
    attention_impl: Optional[Callable] = None  # None => platform default
    #   (Pallas flash attention on TPU, jnp elsewhere); callable overrides
    # MoE (reference deepspeed/moe): >0 experts turns every layer's FFN into a
    # gated expert bank with top_k routing + load-balancing aux loss
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_min_capacity: int = 4
    moe_aux_loss_coef: float = 0.01
    moe_drop_tokens: bool = True      # False => infinite capacity (C = T)
    moe_use_rts: bool = False         # random token selection (top-1 only)
    moe_dispatch: str = "sparse"      # 'sparse' scatter/gather dispatch or
    #   'einsum' dense one-hot (the GShard/reference formulation; fallback)
    moe_use_residual: bool = False    # PR-MoE: dense residual MLP + learned
    #   2-way coefficient mix (reference moe/layer.py use_residual)
    a8_decode: bool = False           # W8A8: decode-shaped int8 weight sites
    #   quantize the activation row too and ride the MXU's s8xs8 path
    #   (set by InferenceEngine from InferenceConfig.quantize_activations;
    #   docs/quant_decode_analysis.md)

    def __post_init__(self):
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.ffn_hidden_size is None:
            if self.activation == "swiglu":
                self.ffn_hidden_size = int(8 * self.hidden_size / 3 / 64 + 0.999) * 64
            else:
                self.ffn_hidden_size = 4 * self.hidden_size
        assert self.hidden_size % self.num_heads == 0
        assert self.num_heads % self.num_kv_heads == 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def eval_config(cfg: TransformerConfig) -> TransformerConfig:
    """Config COPY with training regularisers off (dropout, random-LTD).
    Engines trace eval programs against this copy instead of toggling shared
    config fields (a mutate-restore window is not thread-safe and a
    concurrent train trace would silently compile regulariser-free)."""
    return dataclasses.replace(cfg, dropout_enabled=False, ltd_keep=0)


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: TransformerConfig) -> Dict[str, Any]:
    H = cfg.hidden_size
    N, K, D, V = (cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                  cfg.vocab_size)
    # fixed key slots (branch-independent): 0 embed, 1 pos, 2 layers base,
    # 3 lm_head — the layers base key feeds init_layer_params, which draws
    # per (leaf, layer) via fold_in so any layer RANGE can be initialised
    # without materialising the full stack (the param-offload tier streams
    # block inits; slicing a whole-leaf draw kept the full RNG pipeline
    # live in HBM)
    ks = jax.random.split(rng, 16)
    std = 0.02

    def normal(key, shape, s=std):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(cfg.dtype)

    params: Dict[str, Any] = {
        "embed": {"tokens": normal(ks[0], (V, H))},
    }
    if cfg.position == "learned":
        params["pos"] = normal(ks[1], (cfg.max_seq_len, H), 0.01)
    if cfg.type_vocab_size > 0:
        params["type_embed"] = normal(ks[4], (cfg.type_vocab_size, H))
    if cfg.embed_norm:
        params["embed_norm"] = {"scale": jnp.ones((H,), cfg.dtype),
                                "bias": jnp.zeros((H,), cfg.dtype)}

    params["layers"] = init_layer_params(ks[2], cfg, 0, cfg.num_layers)

    if cfg.final_norm:
        params["final_norm"] = {"scale": jnp.ones((H,), cfg.dtype)}
        if cfg.norm == "layernorm":
            params["final_norm"]["bias"] = jnp.zeros((H,), cfg.dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(ks[3], (H, V))
        if cfg.lm_head_bias:
            params["lm_head_b"] = jnp.zeros((V,), cfg.dtype)
    return params


def init_layer_params(base_key: jax.Array, cfg: TransformerConfig,
                      lo: Any, blen: int) -> Dict[str, Any]:
    """Layer-stack params for layers [lo, lo+blen): leaves shaped
    (blen, ...). Draws are per (leaf, layer) — ``fold_in(fold_in(base, tag),
    layer_idx)`` — so ANY range reproduces exactly the same values the full
    init produces (ZeRO-3 param offload inits one block at a time)."""
    H, L = cfg.hidden_size, cfg.num_layers
    N, K, D, F = (cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                  cfg.ffn_hidden_size)
    std = 0.02
    # GPT-2-style scaled init on residual-writing projections
    resid_std = std / (2 * L) ** 0.5
    E = cfg.moe_num_experts

    def one_layer(li):
        def normal(tag, shape, s=std):
            k = jax.random.fold_in(jax.random.fold_in(base_key, tag), li)
            return (jax.random.normal(k, shape, jnp.float32) * s
                    ).astype(cfg.dtype)

        layer: Dict[str, Any] = {
            "ln1": {"scale": jnp.ones((H,), cfg.dtype)},
            "ln2": {"scale": jnp.ones((H,), cfg.dtype)},
            "attn": {
                "wq": normal(0, (H, N * D)),
                "wk": normal(1, (H, K * D)),
                "wv": normal(2, (H, K * D)),
                "wo": normal(3, (N * D, H), resid_std),
            },
        }
        if E > 0:
            layer["router"] = normal(4, (H, E))
            if cfg.moe_use_residual:
                layer["res_mlp"] = {
                    "w_up": normal(5, (H, F)),
                    "b_up": jnp.zeros((F,), cfg.dtype),
                    "w_down": normal(6, (F, H), resid_std),
                    "b_down": jnp.zeros((H,), cfg.dtype),
                }
                layer["res_coef"] = {"w": normal(7, (H, 2)),
                                     "b": jnp.zeros((2,), cfg.dtype)}
            if cfg.activation == "swiglu":
                layer["mlp"] = {
                    "w_gate": normal(8, (E, H, F)),
                    "w_up": normal(9, (E, H, F)),
                    "w_down": normal(10, (E, F, H), resid_std),
                }
            else:
                layer["mlp"] = {
                    "w_up": normal(9, (E, H, F)),
                    "w_down": normal(10, (E, F, H), resid_std),
                }
        elif cfg.activation == "swiglu":
            layer["mlp"] = {
                "w_gate": normal(8, (H, F)),
                "w_up": normal(9, (H, F)),
                "w_down": normal(10, (F, H), resid_std),
            }
        else:
            layer["mlp"] = {
                "w_up": normal(9, (H, F)),
                "b_up": jnp.zeros((F,), cfg.dtype),
                "w_down": normal(10, (F, H), resid_std),
                "b_down": jnp.zeros((H,), cfg.dtype),
            }
        if cfg.norm == "layernorm":
            layer["ln1"]["bias"] = jnp.zeros((H,), cfg.dtype)
            layer["ln2"]["bias"] = jnp.zeros((H,), cfg.dtype)
            layer["attn"]["bq"] = jnp.zeros((N * D,), cfg.dtype)
            layer["attn"]["bk"] = jnp.zeros((K * D,), cfg.dtype)
            layer["attn"]["bv"] = jnp.zeros((K * D,), cfg.dtype)
            layer["attn"]["bo"] = jnp.zeros((H,), cfg.dtype)
        return layer

    return jax.vmap(one_layer)(lo + jnp.arange(blen))


def param_axes(cfg: TransformerConfig) -> Dict[str, Any]:
    """Logical-axis tree mirroring init_params — drives TP/ZeRO sharding."""
    attn = {"wq": (LAYERS, EMBED, HEADS), "wk": (LAYERS, EMBED, KV_HEADS),
            "wv": (LAYERS, EMBED, KV_HEADS), "wo": (LAYERS, HEADS, EMBED)}
    if cfg.norm == "layernorm":
        attn.update({"bq": (LAYERS, HEADS), "bk": (LAYERS, KV_HEADS),
                     "bv": (LAYERS, KV_HEADS), "bo": (LAYERS, EMBED)})
    from .core import EXPERT

    if cfg.moe_num_experts > 0:
        if cfg.activation == "swiglu":
            mlp = {"w_gate": (LAYERS, EXPERT, EMBED, MLP),
                   "w_up": (LAYERS, EXPERT, EMBED, MLP),
                   "w_down": (LAYERS, EXPERT, MLP, EMBED)}
        else:
            mlp = {"w_up": (LAYERS, EXPERT, EMBED, MLP),
                   "w_down": (LAYERS, EXPERT, MLP, EMBED)}
    elif cfg.activation == "swiglu":
        mlp = {"w_gate": (LAYERS, EMBED, MLP), "w_up": (LAYERS, EMBED, MLP),
               "w_down": (LAYERS, MLP, EMBED)}
    else:
        mlp = {"w_up": (LAYERS, EMBED, MLP), "b_up": (LAYERS, MLP),
               "w_down": (LAYERS, MLP, EMBED), "b_down": (LAYERS, EMBED)}
    ln = {"scale": (LAYERS, EMBED)}
    if cfg.norm == "layernorm":
        ln = {"scale": (LAYERS, EMBED), "bias": (LAYERS, EMBED)}
    layer_axes = {"ln1": dict(ln), "ln2": dict(ln), "attn": attn, "mlp": mlp}
    if cfg.moe_num_experts > 0:
        layer_axes["router"] = (LAYERS, EMBED, None)
        if cfg.moe_use_residual:
            layer_axes["res_mlp"] = {
                "w_up": (LAYERS, EMBED, MLP), "b_up": (LAYERS, MLP),
                "w_down": (LAYERS, MLP, EMBED), "b_down": (LAYERS, EMBED)}
            layer_axes["res_coef"] = {"w": (LAYERS, EMBED, None),
                                      "b": (LAYERS, None)}
    axes: Dict[str, Any] = {
        "embed": {"tokens": (VOCAB, EMBED)},
        "layers": layer_axes,
    }
    if cfg.final_norm:
        axes["final_norm"] = ({"scale": (EMBED,), "bias": (EMBED,)}
                              if cfg.norm == "layernorm"
                              else {"scale": (EMBED,)})
    if cfg.position == "learned":
        axes["pos"] = (SEQ, EMBED)
    if cfg.type_vocab_size > 0:
        axes["type_embed"] = (None, EMBED)
    if cfg.embed_norm:
        axes["embed_norm"] = {"scale": (EMBED,), "bias": (EMBED,)}
    if not cfg.tie_embeddings:
        axes["lm_head"] = (EMBED, VOCAB)
        if cfg.lm_head_bias:
            axes["lm_head_b"] = (VOCAB,)
    return axes


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


import functools as _functools


@_functools.lru_cache(maxsize=None)
def _kernels_active() -> bool:
    """True when the Pallas kernels are compatible with the current backend
    (ops/registry platform probe). Evaluated once per process at trace time;
    CPU/test runs keep the pure-jnp paths."""
    from ..ops.registry import is_compatible

    return is_compatible("flash_attention")


def _tp_world() -> int:
    """Model-axis size of the AMBIENT mesh context at trace time — the
    quantized-GEMM Pallas route is single-shard only (a pallas_call over
    model-sharded weights would need a manual shard_map); TP runs take the
    jnp dequant path, which XLA partitions. Reads the framework's ambient
    mesh (``parallel.mesh.ambient`` — every engine trace site enters the
    mesh through it), falling back to the public
    ``jax.sharding.get_abstract_mesh`` for ``use_mesh`` users. NOT the
    module-global mesh, which the inference engine never sets (and whose
    lazy default would be a side effect here)."""
    from ..parallel.mesh import MODEL_AXIS, ambient_mesh

    m = ambient_mesh()
    if m is not None:
        return int(dict(m.shape).get(MODEL_AXIS, 1))
    try:
        am = jax.sharding.get_abstract_mesh()
        shape = dict(getattr(am, "shape", {}) or {})
        if shape:
            return int(shape.get(MODEL_AXIS, 1))
    except Exception:
        pass
    # fail UNSAFE-proof: outside any framework mesh context we cannot rule
    # out sharded weights (e.g. a bare `with mesh:` trace) — disable the
    # single-shard kernel route rather than risk a pallas_call over them
    return 1 << 30


def _require_impl_kwarg(impl: Callable, kwarg: str, why: str) -> None:
    """A custom attention_impl must DECLARE every kwarg a model feature
    needs — failing loud beats silently dropping a bias or swapping in the
    reference implementation."""
    import inspect

    sig = inspect.signature(impl)
    if (kwarg not in sig.parameters
            and not any(p.kind is inspect.Parameter.VAR_KEYWORD
                        for p in sig.parameters.values())):
        raise TypeError(
            f"custom attention_impl must accept a {kwarg}= kwarg for {why} "
            f"(signature is {sig})")


def default_attention_impl() -> Callable:
    """Platform-resolved attention: Pallas flash attention on TPU, plain-jnp
    elsewhere. This is what ``attention_impl=None`` means (the round-1 gap:
    the kernel existed but nothing installed it — VERDICT.md weak #2)."""
    if _kernels_active():
        from ..ops.flash_attention import make_attention_impl

        return make_attention_impl()
    return dot_product_attention


def active_attention_impl(cfg: "TransformerConfig") -> str:
    """Introspection for benches/tests: which attention path will run."""
    if cfg.attention_impl is not None:
        return "custom"
    return "flash_attention" if _kernels_active() else "jnp"


def _activation_derived_key(h: jax.Array, salt: int) -> jax.Array:
    """Deterministic PRNG key from activation content — loss_fn carries no
    rng argument, so stochastic features (RTS, PLD) derive their draws from
    the data: varies across batches/steps, reproducible for a given input."""
    seed = jax.lax.bitcast_convert_type(jnp.sum(h.astype(jnp.float32)),
                                        jnp.int32)
    return jax.random.fold_in(jax.random.PRNGKey(salt), seed)


def resolve_remat_policy(cfg: "TransformerConfig"):
    """remat_policy knob → jax.checkpoint policy. Measured on v5e (gpt2-125m
    b32 s1024): "dots" 101.6k tok/s vs "full" 100.4k; saving the attention
    output as well was a wash (99.4k) — flash-fwd recompute is cheaper than
    the extra HBM traffic.

    "offload-dots" is the reference's cpu_checkpointing
    (activation_checkpointing/checkpointing.py): saved matmul outputs live
    in pinned HOST memory instead of HBM — XLA streams them out during
    forward and back in for backward (the hand-written
    copy_to_device/partition machinery dissolves into the offload policy).
    Accelerator backends only; trades PCIe traffic for HBM residency on
    long sequences."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy == "offload-dots":
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    return None


def quantize_model_weights(params: Dict[str, Any], bits: int = 8,
                           donate: bool = False,
                           group_size: Optional[int] = None,
                           shardings: Optional[Dict[str, Any]] = None
                           ) -> Dict[str, Any]:
    """Weight-only quantization for inference (reference int8/int4
    kernel-injection mode, ``inference/quantization``,
    ``csrc/includes/quantization_utils.h:468`` 4-bit packing): matmul weights
    (attention qkv/o, dense MLP, untied lm_head) become
    ``{"q8": int8, "s": fp32 per-output-channel scale}`` (8-bit) or
    ``{"q4": nibble-packed uint8 (K/2, N), "s": (G, N) group scales}``
    (4-bit). Embedding stays dense (the token gather reads rows);
    biases/norms stay dense; MoE expert banks are left dense (moe_mlp
    consumes them directly). HBM weight traffic — the decode-phase
    roofline — drops ~2x (int8) / ~4x (int4)."""
    assert bits in (4, 8)
    qmax = float(2 ** (bits - 1) - 1)

    if bits == 4:
        from ..ops.quant_matmul import quantize_int4

        def _quant_math(w):
            q4, s = quantize_int4(w, group_size)
            return {"q4": q4, "s": s}
    else:
        def _quant_math(w):
            w32 = w.astype(jnp.float32)
            absmax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
            s = jnp.where(absmax == 0.0, 1.0, absmax / qmax)
            q = jnp.clip(jnp.round(w32 / s), -qmax, qmax).astype(jnp.int8)
            return {"q8": q, "s": s}

    # donate=True quantizes leaf-by-leaf, freeing each bf16 leaf as its int8
    # replacement materialises — a whole-tree jit would transiently hold both
    # copies (OOM at 7B on a 16GB chip). The explicit delete() matters:
    # backends that ignore donation (remote/axon) would otherwise keep every
    # source buffer alive until GC, which surfaces as a lazy OOM at the
    # first fence.
    if donate:
        # out_shardings per leaf: under TP the quantized pair lands SHARDED
        # directly — routing through the default device first would need
        # the whole quantized tree resident on one chip, defeating TP's
        # memory scaling at load. One jit wrapper per distinct sharding so
        # same-shape leaves (wq/wk/wv) still share a compile.
        jits: Dict[Any, Any] = {}

        def quant(w, sh=None):
            key = (None if sh is None
                   else tuple(sorted((k, v) for k, v in sh.items())))
            if key not in jits:
                jits[key] = jax.jit(_quant_math, donate_argnums=0,
                                    out_shardings=sh)
            out = jits[key](w)
            jax.block_until_ready(out)
            try:
                w.delete()
            except Exception:
                pass                     # already consumed by donation
            return out
    else:
        def quant(w, sh=None):
            return _quant_math(w)

    def sh_of(*path):
        node = shardings
        if node is None:
            return None
        for p in path:
            node = node[p]
        return node

    params = dict(params)
    layers = dict(params["layers"])
    attn = dict(layers["attn"])
    for name in ("wq", "wk", "wv", "wo"):
        attn[name] = quant(attn[name], sh_of("layers", "attn", name))
    layers["attn"] = attn
    if "router" not in layers:           # dense MLP only (skip MoE banks)
        mlp = dict(layers["mlp"])
        for name in ("w_up", "w_gate", "w_down"):
            if name in mlp:
                mlp[name] = quant(mlp[name], sh_of("layers", "mlp", name))
        layers["mlp"] = mlp
    params["layers"] = layers
    if "lm_head" in params:
        params["lm_head"] = quant(params["lm_head"], sh_of("lm_head"))
    return params


def _dense(w: Any, dtype: Any) -> jax.Array:
    """Materialise a (possibly weight-only-quantized) weight as dense."""
    if isinstance(w, dict) and "q8" in w:
        return (w["q8"].astype(jnp.float32) * w["s"]).astype(dtype)
    if isinstance(w, dict) and "q4" in w:
        from ..ops.quant_matmul import unpack_int4

        return unpack_int4(w["q4"], w["s"], dtype)
    return w


def _qeinsum(spec: str, x: jax.Array, w: Any, dtype: Any,
             a8: bool = False) -> jax.Array:
    """Weight-site einsum with on-the-fly int8 dequant.

    Decode-shaped calls (few tokens) route through the Pallas int8 matmul
    (ops/quant_matmul.py) where each weight tile converts in VMEM under the
    int8 DMA — XLA's own lowering converts the FULL weight at VPU rate
    before the matmul, which is slower than bf16 on a memory-bound step.
    Larger (prefill/training-shaped) calls use the XLA path with the scale
    on the output; the optimization barrier stops XLA hoisting the
    loop-invariant dequantized weight stack out of the token/layer loops
    (hoisting materialises full-precision weights — OOM at 7B/16GB)."""
    if isinstance(w, dict) and "q8" in w:
        q8, s = w["q8"], w["s"]
        B, S = x.shape[0], x.shape[1]
        if (S * B <= 8 and q8.ndim == 2 and _kernels_active()
                and _tp_world() == 1
                and q8.shape[0] % 128 == 0 and q8.shape[1] % 128 == 0):
            from ..ops.quant_matmul import int8_a8_matmul, int8_matmul

            fn = int8_a8_matmul if a8 else int8_matmul
            out = fn(x.reshape(B * S, -1), q8, s, out_dtype=dtype)
            return out.reshape(x.shape[:-1] + (q8.shape[1],))
        x, q8 = lax.optimization_barrier((x, q8))
        out = jnp.einsum(spec, x, q8.astype(dtype))
        return out * s[..., 0, :].astype(dtype)
    if isinstance(w, dict) and "q4" in w:
        from ..ops.quant_matmul import unpack_int4

        q4, s = w["q4"], w["s"]
        B, S = x.shape[0], x.shape[1]
        K2, N = q4.shape[-2:]
        G = s.shape[-2]
        gs = 2 * K2 // G
        if (S * B <= 8 and q4.ndim == 2 and _kernels_active()
                and _tp_world() == 1
                and K2 % 128 == 0 and N % 128 == 0
                and (G == 1 or gs % 128 == 0)):
            from ..ops.quant_matmul import int4_a8_matmul, int4_matmul

            fn = int4_a8_matmul if a8 else int4_matmul
            out = fn(x.reshape(B * S, -1), q4, s, out_dtype=dtype)
            return out.reshape(x.shape[:-1] + (N,))
        x, q4 = lax.optimization_barrier((x, q4))
        return jnp.einsum(spec, x, unpack_int4(q4, s, dtype))
    return jnp.einsum(spec, x, w)


def _dropout(x: jax.Array, cfg: "TransformerConfig", salt: int) -> jax.Array:
    """Inverted dropout on a residual-path tensor; active only when the
    engine enabled it (training). Key derives from the tensor's content —
    varies across steps/batches/layers, reproducible for a given input."""
    if not (cfg.dropout > 0.0 and cfg.dropout_enabled):
        return x
    keep = 1.0 - cfg.dropout
    mask = jax.random.bernoulli(_activation_derived_key(x, salt), keep,
                                x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x)).astype(x.dtype)


def _norm(x: jax.Array, scale: jax.Array, bias: Optional[jax.Array],
          kind: str, eps: float) -> jax.Array:
    if _kernels_active():
        from ..ops.normalization import fused_layer_norm

        return fused_layer_norm(x, scale, bias, eps, kind == "rmsnorm")
    x32 = x.astype(jnp.float32)
    if kind == "rmsnorm":
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = x32 * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        out = (x32 - mean) * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
        if bias is not None:
            out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def rope_table(positions: jax.Array, head_dim: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for the given absolute positions, shape (..., head_dim/2)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # (..., D/2)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (B, S, n, D); cos/sin: (S, D/2) shared or (B, S, D/2) per-row
    (ragged-batch decode positions)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    if cos.ndim == 3:
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]
    else:
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1).astype(x.dtype)


def alibi_slopes(n_heads: int) -> jax.Array:
    """ALiBi per-head slopes (HF BloomModel build_alibi_tensor formula;
    reference alibi path: csrc/transformer/inference/csrc/softmax.cu)."""
    import math

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    closest = 2 ** math.floor(math.log2(n_heads))
    slopes = pow2_slopes(closest)
    if closest != n_heads:
        extra = pow2_slopes(2 * closest)
        slopes += extra[0::2][: n_heads - closest]
    return jnp.asarray(slopes, jnp.float32)


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          mask: Optional[jax.Array], causal: bool = True,
                          alibi: Optional[jax.Array] = None,
                          key_positions: Optional[jax.Array] = None,
                          window: Optional[jax.Array] = None,
                          scale: Optional[float] = None) -> jax.Array:
    """Plain-XLA reference attention. q: (B,S,N,D); k,v: (B,T,K,D) with GQA
    broadcast. Softmax in fp32 (reference softmax kernels are fp32-accum).
    ``alibi``: per-head slopes (N,) — the key-position-linear bias (the
    query-position term is softmax-shift-invariant, so slope*k_pos
    suffices). ``key_positions`` (B, T): true per-row key positions for the
    alibi bias (ragged decode — defaults to the column index). ``window``:
    sliding-window width as a (traced) scalar — queries attend only to
    keys within ``window`` positions back; <=0 means unlimited (so a
    per-layer mix of global/local layers scans with one program).
    ``scale``: score multiplier, default 1/sqrt(D)."""
    B, S, N, D = q.shape
    T, K = k.shape[1], k.shape[2]
    if K != N:
        k = jnp.repeat(k, N // K, axis=2)
        v = jnp.repeat(v, N // K, axis=2)
    scale = (D ** -0.5) if scale is None else scale
    scores = jnp.einsum("bsnd,btnd->bnst", q, k).astype(jnp.float32) * scale
    if alibi is not None:
        kpos = (jnp.arange(T, dtype=jnp.float32)[None]
                if key_positions is None
                else key_positions.astype(jnp.float32))
        scores = scores + alibi[None, :, None, None] * kpos[:, None, None, :]
    neg = jnp.finfo(jnp.float32).min
    if causal or window is not None:
        # query at absolute position (T - S + s) attends to keys <= that position
        q_pos = jnp.arange(S)[:, None] + (T - S)
        k_pos = jnp.arange(T)[None, :]
        keep = (k_pos <= q_pos) if causal else jnp.bool_(True)
        if window is not None:
            keep = keep & ((window <= 0) | (q_pos - k_pos < window))
        scores = jnp.where(keep[None, None], scores, neg)
    if mask is not None:
        # (B,T) key-padding mask or (B,S,T) full attention mask
        if mask.ndim == 2:
            scores = jnp.where(mask[:, None, None, :].astype(bool), scores, neg)
        else:
            scores = jnp.where(mask[:, None, :, :].astype(bool), scores, neg)
    from ..parallel.sequence import scores_spec, constrain as _sp_constrain

    sspec = scores_spec(N)
    if sspec is not None:
        # pin the (B,N,S,T) layout to heads-over-('seq','model') so the
        # softmax-backward reductions (B,N,S) stay in the attention region's
        # natural sharding instead of XLA resharding them S-over-'seq' via
        # involuntary full remat (zero3×TP×SP dryrun)
        scores = _sp_constrain(scores, sspec)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if sspec is not None:
        probs = _sp_constrain(probs, sspec)
    return jnp.einsum("bnst,btnd->bsnd", probs, v)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def window_table(cfg: TransformerConfig) -> jax.Array:
    """(L,) int32 per-layer sliding-window widths from the cycled
    ``attention_layers`` pattern (0 = global/unlimited). ONE builder shared
    by the resident scan and the param-offload block programs — the
    pattern expansion diverging between engines would silently change
    which layers are local."""
    pat = cfg.attention_layers
    return jnp.array(
        [cfg.attention_window if pat[i % len(pat)] == "local" else 0
         for i in range(cfg.num_layers)], jnp.int32)


def pld_gate(cfg: TransformerConfig, h: jax.Array, h_new: jax.Array,
             aux: jax.Array, idx: jax.Array, pld_theta: jax.Array):
    """Stochastic depth (reference progressive_layer_drop.py): layer i
    keeps with p = 1 - (1-theta)(i+1)/L, deeper layers drop more; kept
    outputs scaled 1/p for an unbiased expectation. The draw derives from
    the activations (loss_fn has no rng argument) so it varies across
    steps/batches but stays deterministic. ONE implementation shared by
    the resident layer scan and the param-offload block programs — the
    gate math diverging between engines would silently change the model.
    Returns (mixed h, rescaled aux)."""
    L = cfg.num_layers
    # floor keeps the 1/keep_p rescale finite even when theta has decayed
    # to ~0 for the deepest layer (0/0 NaN otherwise)
    keep_p = jnp.maximum(1.0 - (1.0 - pld_theta) * (idx + 1.0) / L, 0.01)
    key = jax.random.fold_in(_activation_derived_key(h, 17),
                             idx.astype(jnp.int32))
    gate = jax.random.bernoulli(key, keep_p).astype(jnp.float32)
    h_mixed = h + ((gate / keep_p)
                   * (h_new - h).astype(jnp.float32)).astype(h.dtype)
    # same 1/keep_p rescale as the residual — otherwise deep layers'
    # router balancing term is down-weighted in expectation
    return h_mixed, aux * gate / keep_p


def _layer_forward(cfg: TransformerConfig, x: jax.Array, layer: Dict[str, Any],
                   mask: Optional[jax.Array],
                   positions: jax.Array,
                   cache: Optional[Dict[str, jax.Array]] = None,
                   static_prefill: bool = False,
                   key_positions: Optional[jax.Array] = None,
                   window: Optional[jax.Array] = None,
                   block_table: Optional[jax.Array] = None,
                   paged_write_mask: Optional[jax.Array] = None,
                   paged_impl: str = "auto",
                   paged_chunk: bool = False
                   ) -> Tuple[jax.Array, Optional[Dict[str, jax.Array]]]:
    """One decoder block. ``layer`` holds this layer's (unstacked) params.
    ``cache`` (decode): dict with k/v of shape (B, T_max, K, D) and scalar
    ``index`` — returns the updated cache. ``window``: this layer's
    sliding-window width (traced scalar, <=0 = global) — present only for
    attention_layers models (GPT-Neo), which take the windowed jnp
    attention path throughout.

    ``block_table`` switches the cache to PAGED mode (serving layer): the
    per-layer cache is a shared pool ``{"k","v": (NUM_BLOCKS, BLOCK, K, D)}``
    and ``block_table`` (B, MAX_BLOCKS) maps each row's logical blocks to
    physical ids. ``positions`` must then be the (B, S) absolute write
    positions; ``paged_write_mask`` (B, S) routes masked-off tokens (prompt
    chunk padding) to the scratch block 0 instead of the row's blocks.
    ``paged_impl`` selects the paged READ path: 'auto' (Pallas paged
    kernels when active, GQA-native jnp paged reference otherwise) or
    'gather' (the dense ``arena[block_table]`` view — the A/B baseline,
    and always the path a custom ``attention_impl`` sees). ``paged_chunk``
    asserts the chunked-prefill contract (``positions[b] == start_b +
    arange(S)``), which is what lets S>1 take the paged flash-prefill
    kernel."""
    B, S, H = x.shape
    N, K, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    post_ln = cfg.norm_position == "post"
    if post_ln:
        h = x      # post-LN (BERT family): raw input feeds attention; the
        #            norm is applied after each residual add below
    else:
        h = _norm(x, layer["ln1"]["scale"], layer["ln1"].get("bias"),
                  cfg.norm, cfg.norm_eps)
    if cfg.act_quant_bits and cache is None:
        # activation QAT (reference QuantAct): quantize the attention input
        from ..compression.compress import fake_quant_activation

        h = fake_quant_activation(h, cfg.act_quant_bits)
    q = _qeinsum("bsh,hd->bsd", h, layer["attn"]["wq"], cfg.dtype, a8=cfg.a8_decode)
    k = _qeinsum("bsh,hd->bsd", h, layer["attn"]["wk"], cfg.dtype, a8=cfg.a8_decode)
    v = _qeinsum("bsh,hd->bsd", h, layer["attn"]["wv"], cfg.dtype, a8=cfg.a8_decode)
    if "bq" in layer["attn"]:
        q = q + layer["attn"]["bq"]
        k = k + layer["attn"]["bk"]
        v = v + layer["attn"]["bv"]
    q = q.reshape(B, S, N, D)
    k = k.reshape(B, S, K, D)
    v = v.reshape(B, S, K, D)

    # SP reshard around attention. Ulysses: sequence gathered, heads
    # scattered over ('seq','model') — XLA lowers the constraint to the
    # head-scatter all-to-all. Ring: tokens STAY seq-sharded; KV chunks
    # rotate inside ring_attention instead. Training path only (no cache).
    from ..parallel.ring import ring_attention_enabled

    use_ring = (cache is None and ring_attention_enabled()
                and cfg.attention_impl is None)
    if cache is None and not use_ring:
        from ..parallel.sequence import attn_out_spec, heads_spec, constrain

        qspec = heads_spec(N)
        kspec = heads_spec(K)
        if qspec is not None and kspec is not None:
            # two-step reshard: first pin the natural post-reshape layout
            # (tokens over 'seq', heads over 'model') so the head-scatter
            # all-to-all is a 4D→4D transition — without this, the BACKWARD
            # of the (B,S,N·D)→(B,S,N,D) reshape sees a heads-over-4-way
            # cotangent and XLA falls into involuntary full remat
            nat_q, nat_k = attn_out_spec(N), attn_out_spec(K)
            if nat_q is not None and nat_k is not None:
                q = constrain(q, nat_q)
                k = constrain(k, nat_k)
                v = constrain(v, nat_k)
            q = constrain(q, qspec)
            k = constrain(k, kspec)
            v = constrain(v, kspec)

    if cfg.position == "rope":
        rd = cfg.rotary_dim or D
        cos, sin = rope_table(positions, rd, cfg.rope_theta)
        if rd < D:
            # partial rotary (GPT-J/NeoX): rope on the first rd dims only.
            # (GPT-J's interleaved convention is handled at import time by
            # permuting the rotary columns of wq/wk into rotate-half order.)
            q = jnp.concatenate(
                [apply_rope(q[..., :rd], cos, sin), q[..., rd:]], axis=-1)
            k = jnp.concatenate(
                [apply_rope(k[..., :rd], cos, sin), k[..., rd:]], axis=-1)
        else:
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)

    attn_fn = cfg.attention_impl or default_attention_impl()
    if window is not None or cfg.attention_scale is not None:
        if cfg.attention_impl is not None:
            raise NotImplementedError(
                "custom attention_impl + sliding-window/custom-scale "
                "attention (GPT-Neo family) is not supported — silently "
                "replacing the custom impl with the windowed jnp path "
                "would change the model")
        # windowed / custom-scale attention routes through the jnp path
        # (the flash kernel has neither operand); window is applied at the
        # call sites below — the decode fallback needs TRUE positions, not
        # the end-aligned convention inside dot_product_attention
        attn_fn = _functools.partial(dot_product_attention,
                                     scale=cfg.attention_scale)
    alibi = alibi_slopes(N) if cfg.position == "alibi" else None
    if alibi is not None and cfg.attention_impl is not None:
        _require_impl_kwarg(cfg.attention_impl, "alibi",
                            "position='alibi' models (BLOOM) — silently "
                            "dropping the alibi bias would change the model")
    new_cache = None
    if cache is not None and block_table is not None:
        # PAGED serving path (deepspeed_tpu/serving/paged_kv.py): token at
        # absolute position p lands in physical block block_table[b, p//BS]
        # at offset p%BS — a scatter write. The layout is left-aligned
        # (column == true position), so causality over true positions is
        # the whole validity story and keys' alibi column bias is exact by
        # construction. Reads walk the table: the Pallas paged kernels
        # (ops/paged_decode_attention.py) DMA only each row's RESIDENT
        # pages; 'gather' materializes the dense arena[block_table] view —
        # the PR-6 path, kept as the A/B baseline
        # (serving.paged_kernel='off') and as what a custom attention_impl
        # sees (it has no block-table operand). Every path is shape-static:
        # one compiled program covers any arena occupancy (the jit-cache
        # analog of vLLM's PagedAttention block tables).
        BSz = cache["k"].shape[1]
        T_view = block_table.shape[1] * BSz
        pos = positions if positions.ndim == 2 else jnp.broadcast_to(
            positions[None], (B, S))
        wpos = jnp.minimum(pos, T_view - 1)   # clamp pad writes in-range
        blk = jnp.take_along_axis(block_table, wpos // BSz, axis=1)  # (B,S)
        off = wpos % BSz
        if paged_write_mask is not None:
            # chunk padding / inactive decode rows write to scratch block 0
            blk = jnp.where(paged_write_mask, blk, 0)
            off = jnp.where(paged_write_mask, off, 0)
        ck = cache["k"].at[blk, off].set(k.astype(cache["k"].dtype))
        cv = cache["v"].at[blk, off].set(v.astype(cache["v"].dtype))
        new_cache = {"k": ck, "v": cv}
        use_dense = (paged_impl == "gather" or cfg.attention_impl is not None
                     or window is not None or cfg.attention_scale is not None)
        if use_dense:
            kk = ck[block_table].reshape(B, T_view, K, D)
            vv = cv[block_table].reshape(B, T_view, K, D)
            col = jnp.arange(T_view, dtype=jnp.int32)
            # zero v beyond each row's max resident position — masked
            # columns carry softmax weight 0, and 0 × NaN = NaN: scratch/
            # recycled pages may hold nonfinite residue that must not
            # leak into live rows (same rule as reference_paged_attention
            # and the Pallas kernels' edge-padded v zeroing)
            resident = col[None, :] <= jnp.max(pos, axis=1)[:, None]
            vv = jnp.where(resident[:, :, None, None], vv, 0)
            full = (col[None, None, :] <= pos[:, :, None]).astype(jnp.int32)
            dense_fn = cfg.attention_impl or dot_product_attention
            if cfg.attention_scale is not None and cfg.attention_impl is None:
                dense_fn = _functools.partial(dot_product_attention,
                                              scale=cfg.attention_scale)
            if alibi is None:
                attn = dense_fn(q, kk, vv, full, causal=False)
            else:
                attn = dense_fn(q, kk, vv, full, causal=False, alibi=alibi)
        elif S == 1 and _kernels_active():
            # paged decode: walks the block table, DMAs resident pages only
            from ..ops.paged_decode_attention import paged_decode_attention

            attn = paged_decode_attention(q[:, 0], ck, cv, block_table,
                                          pos[:, 0] + 1,
                                          alibi=alibi)[:, None]
        elif S > 1 and paged_chunk and _kernels_active():
            # chunked prefill reads prior context through the table too
            from ..ops.paged_decode_attention import paged_prefill_attention

            attn = paged_prefill_attention(q, ck, cv, block_table,
                                           pos[:, 0], alibi=alibi)
        else:
            # GQA-native jnp paged reference (no head expansion, no dense
            # (B,S,T) mask materialization) — CPU fallback + parity oracle
            from ..ops.paged_decode_attention import reference_paged_attention

            attn = reference_paged_attention(q, ck, cv, block_table, pos,
                                             alibi=alibi)
    elif cache is not None:
        idx = cache["index"]
        ck = lax.dynamic_update_slice(cache["k"], k, (0, idx, 0, 0))
        cv = lax.dynamic_update_slice(cache["v"], v, (0, idx, 0, 0))
        new_cache = {"k": ck, "v": cv, "index": idx + S}
        T = ck.shape[1]
        if (S == 1 and cfg.attention_impl is None and _kernels_active()
                and window is None
                and cfg.attention_scale is None):
            # single-token decode → Pallas decode kernel (GQA-native, reads
            # the arena without head expansion; alibi in-kernel)
            from ..ops.decode_attention import decode_attention

            causal_valid = (jnp.arange(T)[None, :] <= idx).astype(jnp.int32)
            if mask is not None:
                # AND with causal so unwritten arena slots are never live,
                # matching the jnp fallback's causal_mask * mask semantics
                valid = mask * causal_valid
            else:
                valid = jnp.broadcast_to(causal_valid, (B, T))
            attn = decode_attention(q[:, 0], ck, cv, valid, alibi=alibi,
                                    key_positions=key_positions)[:, None]
        elif (static_prefill and S > 1 and cfg.attention_impl is None
              and _kernels_active() and T % 128 == 0 and window is None
              and cfg.attention_scale is None):
            # prefill from position 0: queries sit at absolute rows 0..S-1, so
            # the flash kernel's 0-based causal col<=row over the arena is
            # exact and the (B, T_max) validity mask covers padding +
            # unwritten slots — keeps the TTFT path on the flash kernel
            # instead of a (B,S,T) mask fallback. Kernel-only: the jnp path's
            # causal convention is end-aligned (q at T-S), so it must not
            # take this branch.
            valid = (mask if mask is not None else
                     jnp.broadcast_to(
                         (jnp.arange(T)[None, :] < S).astype(jnp.int32), (B, T)))
            if alibi is None:
                attn = attn_fn(q, ck, cv, valid, causal=True)
            else:
                attn = attn_fn(q, ck, cv, valid, causal=True, alibi=alibi)
        else:
            k, v = ck, cv
            # causal over absolute positions: query s sits at idx+s, keys valid <= that
            q_pos = idx + jnp.arange(S)
            k_pos = jnp.arange(T)
            causal_mask = (k_pos[None, :] <= q_pos[:, None])            # (S,T)
            if window is not None:
                # sliding window over TRUE positions (decode: q at idx+s)
                causal_mask = causal_mask & (
                    (window <= 0)
                    | (q_pos[:, None] - k_pos[None, :] < window))
            causal_mask = causal_mask.astype(jnp.int32)
            full = jnp.broadcast_to(causal_mask[None], (B, S, T))
            if mask is not None:  # (B, T_prompt) padding mask padded to T by caller
                full = full * mask[:, None, :]
            if alibi is None:
                attn = attn_fn(q, k, v, full, causal=False)
            elif key_positions is not None:
                if cfg.attention_impl is not None:
                    _require_impl_kwarg(
                        cfg.attention_impl, "key_positions",
                        "ragged alibi decode — silently swapping in the "
                        "reference attention would change the model's "
                        "performance profile")
                    attn = attn_fn(q, k, v, full, causal=False, alibi=alibi,
                                   key_positions=key_positions)
                else:
                    attn = dot_product_attention(
                        q, k, v, full, causal=False, alibi=alibi,
                        key_positions=key_positions)
            else:
                attn = attn_fn(q, k, v, full, causal=False, alibi=alibi)
    elif use_ring:
        from ..parallel.ring import ring_attention

        if alibi is not None:
            raise NotImplementedError(
                "ring attention + alibi is not supported yet — use "
                "sequence_parallel_impl='ulysses' for BLOOM-family models")
        attn = ring_attention(q, k, v, mask=mask, causal=True)
    else:
        wkw = {} if window is None else {"window": window}
        if alibi is None:
            attn = attn_fn(q, k, v, mask, causal=cfg.causal, **wkw)
        else:
            attn = attn_fn(q, k, v, mask, causal=cfg.causal, alibi=alibi,
                           **wkw)

    if cache is None and not use_ring:
        from ..parallel.sequence import attn_out_spec, constrain

        out_spec = attn_out_spec(N)
        if out_spec is not None:
            # Ulysses inverse all-to-all on the 4D tensor (see attn_out_spec)
            attn = constrain(attn, out_spec)
    attn = attn.reshape(B, S, N * D)
    attn_out = _qeinsum("bsd,dh->bsh", attn, layer["attn"]["wo"], cfg.dtype, a8=cfg.a8_decode)
    if "bo" in layer["attn"]:
        attn_out = attn_out + layer["attn"]["bo"]
    if cache is None:
        attn_out = _dropout(attn_out, cfg, salt=31)
    if cache is None:
        from ..parallel.sequence import constrain, hidden_spec, sequence_parallel_enabled

        if sequence_parallel_enabled():
            attn_out = constrain(attn_out, hidden_spec())
    if cfg.parallel_residual:
        # GPT-J/NeoX: x + attn(ln1(x)) + mlp(ln2(x)) — one residual add,
        # the MLP reads the ORIGINAL x through its own norm
        h = _norm(x, layer["ln2"]["scale"], layer["ln2"].get("bias"),
                  cfg.norm, cfg.norm_eps)
    elif post_ln:
        # BERT family: norm AFTER the residual add; the normed sum feeds MLP
        x = _norm(x + attn_out, layer["ln1"]["scale"],
                  layer["ln1"].get("bias"), cfg.norm, cfg.norm_eps)
        h = x
    else:
        x = x + attn_out
        h = _norm(x, layer["ln2"]["scale"], layer["ln2"].get("bias"),
                  cfg.norm, cfg.norm_eps)
    if cfg.act_quant_bits and cache is None:
        from ..compression.compress import fake_quant_activation

        h = fake_quant_activation(h, cfg.act_quant_bits)   # MLP input
    aux = jnp.float32(0.0)
    if cfg.moe_num_experts > 0:
        from ..parallel.moe import moe_mlp

        # cache mode == inference: exact routing, no capacity drops and no
        # RTS — dropping a decode token would silently zero its MLP output,
        # and right-padded prefill junk tokens must not steal capacity from
        # real ones (the reference's DeepSpeedMoEInference routes without
        # training-time capacity limits, moe_inference.py:160)
        infer = cache is not None
        rts_rng = (_activation_derived_key(h, 0)
                   if (cfg.moe_use_rts and not infer) else None)
        mlp_out, aux = moe_mlp(h, layer["router"], layer["mlp"], cfg.activation,
                               top_k=cfg.moe_top_k,
                               capacity_factor=cfg.moe_capacity_factor,
                               min_capacity=cfg.moe_min_capacity,
                               drop_tokens=cfg.moe_drop_tokens and not infer,
                               use_rts=cfg.moe_use_rts and not infer,
                               rng=rts_rng,
                               dispatch_impl=cfg.moe_dispatch)
        if cfg.moe_use_residual:
            # PR-MoE (reference moe/layer.py:120): dense MLP in parallel,
            # mixed by a learned softmax coefficient over (moe, dense)
            inner = jnp.einsum("bsh,hf->bsf", h, layer["res_mlp"]["w_up"]) \
                + layer["res_mlp"]["b_up"]
            inner = jax.nn.gelu(inner, approximate=True)
            res_out = jnp.einsum("bsf,fh->bsh", inner,
                                 layer["res_mlp"]["w_down"]) \
                + layer["res_mlp"]["b_down"]
            coef = jax.nn.softmax(
                (jnp.einsum("bsh,hc->bsc", h, layer["res_coef"]["w"])
                 + layer["res_coef"]["b"]).astype(jnp.float32), axis=-1
            ).astype(h.dtype)
            mlp_out = mlp_out * coef[..., 0:1] + res_out * coef[..., 1:2]
    elif cfg.activation == "swiglu":
        gate = _qeinsum("bsh,hf->bsf", h, layer["mlp"]["w_gate"], cfg.dtype, a8=cfg.a8_decode)
        up = _qeinsum("bsh,hf->bsf", h, layer["mlp"]["w_up"], cfg.dtype, a8=cfg.a8_decode)
        inner = jax.nn.silu(gate) * up
        mlp_out = _qeinsum("bsf,fh->bsh", inner, layer["mlp"]["w_down"], cfg.dtype, a8=cfg.a8_decode)
    else:
        inner = _qeinsum("bsh,hf->bsf", h, layer["mlp"]["w_up"], cfg.dtype, a8=cfg.a8_decode) + layer["mlp"]["b_up"]
        if cfg.activation == "relu":
            inner = jax.nn.relu(inner)
        elif cfg.activation == "quick_gelu":
            # CLIP's x*sigmoid(1.702x) (HF QuickGELUActivation)
            inner = inner * jax.nn.sigmoid(1.702 * inner)
        else:
            inner = jax.nn.gelu(inner,
                                approximate=cfg.activation != "gelu-exact")
        mlp_out = _qeinsum("bsf,fh->bsh", inner, layer["mlp"]["w_down"], cfg.dtype, a8=cfg.a8_decode) + layer["mlp"]["b_down"]
    if cache is None:
        mlp_out = _dropout(mlp_out, cfg, salt=37)
    if cfg.parallel_residual:
        x = x + attn_out + mlp_out
    elif post_ln:
        x = _norm(x + mlp_out, layer["ln2"]["scale"],
                  layer["ln2"].get("bias"), cfg.norm, cfg.norm_eps)
    else:
        x = x + mlp_out
    return x, new_cache, aux


def forward(params: Dict[str, Any], input_ids: jax.Array,
            cfg: TransformerConfig,
            attention_mask: Optional[jax.Array] = None,
            cache: Optional[Dict[str, Any]] = None,
            start_pos: Any = 0,
            pld_theta: Optional[jax.Array] = None,
            positions: Optional[jax.Array] = None,
            token_type_ids: Optional[jax.Array] = None,
            key_positions: Optional[jax.Array] = None,
            block_table: Optional[jax.Array] = None,
            paged_write_mask: Optional[jax.Array] = None,
            paged_impl: str = "auto",
            paged_chunk: bool = False
            ) -> Tuple[jax.Array, Optional[Dict[str, Any]], jax.Array]:
    """Token ids (B,S) → (logits (B,S,V), new_cache, moe_aux_loss). With
    ``cache``, runs in decode mode (cache is a per-layer stacked pytree; see
    inference/kv_cache.py). ``positions``: explicit absolute positions, (S,)
    shared or (B, S) per-row — ragged batches decode with each row's TRUE
    token index (the KV arena column stays uniform; only the position
    values differ).

    ``block_table`` (B, MAX_BLOCKS) switches the cache to the PAGED layout
    ``{"k","v": (L, NUM_BLOCKS, BLOCK, K, D)}`` (serving layer); ``positions``
    is then REQUIRED — per-row absolute write positions — and
    ``paged_write_mask`` (B, S) routes padding writes to the scratch block.
    ``paged_impl``/``paged_chunk`` select the paged read path (see
    ``_layer_forward``)."""
    B, S = input_ids.shape
    if paged_impl not in ("auto", "gather"):
        raise ValueError(f"paged_impl must be 'auto' or 'gather', "
                         f"got '{paged_impl}'")
    x = params["embed"]["tokens"][input_ids].astype(cfg.dtype)
    if positions is None:
        positions = jnp.arange(S) + start_pos
    if cfg.position == "learned":
        x = x + params["pos"][positions].astype(cfg.dtype)
    if cfg.type_vocab_size > 0:
        # BERT segment embeddings; absent ids mean segment 0 (HF default)
        tti = (jnp.zeros((B, S), jnp.int32) if token_type_ids is None
               else token_type_ids)
        x = x + params["type_embed"][tti].astype(cfg.dtype)
    if cfg.embed_norm:
        x = _norm(x, params["embed_norm"]["scale"],
                  params["embed_norm"].get("bias"), "layernorm", cfg.norm_eps)
    if cache is None:
        x = _dropout(x, cfg, salt=29)

    if block_table is not None and (cache is None or positions is None
                                    or positions.ndim != 2):
        raise ValueError("paged mode (block_table) requires cache= and "
                         "explicit (B, S) positions")
    static_prefill = (cache is not None and block_table is None
                      and isinstance(start_pos, int) and start_pos == 0)

    use_pld = (cfg.pld_enabled and cache is None and pld_theta is not None)
    use_ltd = (cfg.ltd_enabled and cache is None and 0 < cfg.ltd_keep < S)
    L = cfg.num_layers
    use_win = bool(cfg.attention_layers)
    if use_win:
        # per-layer sliding window (GPT-Neo): 'local' layers get the
        # window, 'global' layers 0 (= unlimited); the pattern cycles over
        # layers like HF's attention_types expansion
        win_table = window_table(cfg)
        from ..parallel.ring import ring_attention_enabled

        if cache is None and ring_attention_enabled():
            raise NotImplementedError(
                "attention_layers (sliding-window) models + ring attention "
                "are not supported — use sequence_parallel_impl='ulysses'")
    if cfg.attention_scale is not None and cache is None:
        from ..parallel.ring import ring_attention_enabled

        if ring_attention_enabled():
            # ring_attention hardcodes 1/sqrt(head_dim); a custom scale
            # (GPT-Neo uses 1.0) would be silently dropped
            raise NotImplementedError(
                "custom attention_scale models + ring attention are not "
                "supported — use sequence_parallel_impl='ulysses'")
    if use_ltd:
        # default mirrors the engine (engine.py random-LTD init): all but the
        # first and last layer; degenerate depths keep at least one layer
        ltd_layers = (cfg.ltd_layers if cfg.ltd_layers is not None
                      else tuple(range(1, L - 1)) if L > 2
                      else tuple(range(L - 1, L)))
        ltd_flags = jnp.array([1.0 if i in ltd_layers else 0.0
                               for i in range(L)], jnp.float32)

    def block(carry, layer_and_cache):
        h, aux_acc = carry
        ltd_flag = None
        if use_ltd:
            (layer, layer_cache), idx, ltd_flag = layer_and_cache
        elif use_pld or use_win:
            (layer, layer_cache), idx = layer_and_cache
        else:
            layer, layer_cache = layer_and_cache
            idx = None
        window = (win_table[idx.astype(jnp.int32)] if use_win else None)
        if use_ltd:
            # gather a random sorted token subset, run the layer on it,
            # scatter back — dropped tokens keep their input activations
            # (reference RandomLayerTokenDrop + token_sort/gather_scatter
            # kernels; sorted indices preserve the causal order so the
            # subset's causal mask is exact)
            def ltd_branch(hh):
                # trace-time import: runtime already depends on models, so the
                # reverse module-level import would be circular
                from ..runtime.data_pipeline.random_ltd import (
                    gather_tokens, sample_token_subset, scatter_tokens)

                key = jax.random.fold_in(_activation_derived_key(hh, 23),
                                         idx.astype(jnp.int32))
                kept, _ = sample_token_subset(key, S, cfg.ltd_keep)
                part = gather_tokens(hh, kept)
                msk = (None if attention_mask is None
                       else jnp.take(attention_mask, kept, axis=1))
                out, _, aux = _layer_forward(cfg, part, layer, msk,
                                             jnp.take(positions, kept), None,
                                             window=window)
                return scatter_tokens(hh, out, kept), aux

            def full_branch(hh):
                out, _, aux = _layer_forward(cfg, hh, layer, attention_mask,
                                             positions, None, window=window)
                return out, aux

            h_new, aux = lax.cond(ltd_flag > 0, ltd_branch, full_branch, h)
            new_cache = None
        else:
            h_new, new_cache, aux = _layer_forward(
                cfg, h, layer, attention_mask, positions, layer_cache,
                static_prefill=static_prefill, key_positions=key_positions,
                window=window, block_table=block_table,
                paged_write_mask=paged_write_mask)
        if use_pld:
            h_new, aux = pld_gate(cfg, h, h_new, aux, idx, pld_theta)
        return (h_new, aux_acc + aux), new_cache

    block_fn = block
    if cfg.remat and cache is None:
        block_fn = jax.checkpoint(block, prevent_cse=False,
                                  policy=resolve_remat_policy(cfg))

    if cache is None:
        # one scan; xs packing varies with the active stochastic features
        # (block unpacks in the same order; None rides the pytree untouched)
        if use_ltd:
            xs = ((params["layers"], None), jnp.arange(L, dtype=jnp.float32),
                  ltd_flags)
        elif use_pld or use_win:
            xs = ((params["layers"], None), jnp.arange(L, dtype=jnp.float32))
        else:
            xs = (params["layers"], None)
        (x, aux_total), _ = lax.scan(block_fn, (x, jnp.float32(0.0)), xs,
                                     unroll=cfg.scan_unroll)
        new_cache = None
    elif block_table is not None:
        # PAGED: the arena rides the layer scan as CARRY, not xs/ys — loop
        # carries update in place, so the shared block pool stops
        # round-tripping through per-iteration input/output buffers. On the
        # selftest decode program this cut XLA-counted bytes_accessed 33%
        # and peak HBM 22% vs the xs/ys form (the pool dominates both).
        # window/PLD/LTD are training- or dense-cache-only features; the
        # serving engine rejects sliding-window models, and the dense-view
        # fallback inside _layer_forward ignores `window` exactly like the
        # PR-6 paged branch did.
        def paged_block(carry, layer_and_idx):
            h, aux_acc, ark, arv = carry
            layer, idx = layer_and_idx
            layer_cache = {
                "k": lax.dynamic_index_in_dim(ark, idx, keepdims=False),
                "v": lax.dynamic_index_in_dim(arv, idx, keepdims=False)}
            h_new, new_c, aux = _layer_forward(
                cfg, h, layer, attention_mask, positions, layer_cache,
                static_prefill=static_prefill, key_positions=key_positions,
                window=None, block_table=block_table,
                paged_write_mask=paged_write_mask, paged_impl=paged_impl,
                paged_chunk=paged_chunk)
            ark = lax.dynamic_update_index_in_dim(ark, new_c["k"], idx, 0)
            arv = lax.dynamic_update_index_in_dim(arv, new_c["v"], idx, 0)
            return (h_new, aux_acc + aux, ark, arv), None

        (x, aux_total, ck_all, cv_all), _ = lax.scan(
            paged_block, (x, jnp.float32(0.0), cache["k"], cache["v"]),
            (params["layers"], jnp.arange(L, dtype=jnp.int32)))
        new_cache = {"k": ck_all, "v": cv_all}
    else:
        xs = ((params["layers"], cache) if not use_win else
              ((params["layers"], cache), jnp.arange(L, dtype=jnp.float32)))
        (x, aux_total), new_cache = lax.scan(block_fn, (x, jnp.float32(0.0)),
                                             xs)

    logits = head_logits(params, x, cfg)
    return logits, new_cache, aux_total


def head_logits(params: Dict[str, Any], x: jax.Array,
                cfg: TransformerConfig) -> jax.Array:
    """Final norm + output projection — THE one head implementation (the
    pipeline and param-offload executors call it too; a config knob added
    here must not be re-implemented there)."""
    if cfg.final_norm:
        x = _norm(x, params["final_norm"]["scale"],
                  params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsh,vh->bsv", x, params["embed"]["tokens"])
    else:
        logits = _qeinsum("bsh,hv->bsv", x, params["lm_head"], cfg.dtype, a8=cfg.a8_decode)
        if "lm_head_b" in params:
            logits = logits + params["lm_head_b"]
    return logits


def gather_target_logprobs(logits: jax.Array,
                           targets: jax.Array) -> jax.Array:
    """Per-position log softmax mass on ``targets`` (``logits[..., V]`` →
    ``(...)`` fp32), via the TP-safe one-hot masked-sum contraction — the
    shared implementation behind the RLHF score program and policy loss.
    ``take_along_axis`` over a vocab dim TP shards over 'model'
    miscompiles in the XLA CPU SPMD partitioner (see the rationale in
    :func:`cross_entropy_loss`, which interleaves the same contraction
    with its -100 label masking)."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    one_hot = targets[..., None] == jnp.arange(logits.shape[-1],
                                               dtype=targets.dtype)
    picked = jnp.sum(jnp.where(one_hot, logits, 0.0), axis=-1)
    return picked - lse


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       mask: Optional[jax.Array] = None) -> jax.Array:
    """Next-token cross entropy with fp32 accumulation; labels == -100 are
    ignored (HF convention used throughout the reference tests). Computed as
    logsumexp - picked_logit so no fp32 (B,S,V) log-softmax buffer is ever
    materialised (the (B,S,V) upcast fuses into the reduction)."""
    valid = labels != -100
    if mask is not None:
        valid = valid & mask.astype(bool)
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)          # (B,S)
    # picked logit via a one-hot masked sum, NOT take_along_axis: gathering
    # along a vocab dim that TP shards over 'model' miscompiles in the XLA
    # CPU SPMD partitioner (NaN in the gathered values under tp×sp meshes —
    # the numerics-sentinel triage of the zero3×TP×SP dryrun; the
    # de-optimized program is clean). The compare+select fuses into the
    # reduction, and each vocab shard contributes its local partial sum —
    # the standard TP-safe cross-entropy contraction.
    one_hot = safe_labels[..., None] == jnp.arange(
        logits.shape[-1], dtype=safe_labels.dtype)
    picked = jnp.sum(jnp.where(one_hot, logits.astype(jnp.float32), 0.0),
                     axis=-1)
    token_loss = jnp.where(valid, lse - picked, 0.0)
    return token_loss.sum() / jnp.maximum(valid.sum(), 1)


def build_model(cfg: TransformerConfig, name: str = "transformer") -> Model:
    """Bundle init/apply/loss/axes for the engine."""

    def init(rng):
        return init_params(rng, cfg)

    def apply(params, batch, cache=None, start_pos=0):
        logits, new_cache, _ = forward(params, batch["input_ids"], cfg,
                                       attention_mask=batch.get("attention_mask"),
                                       cache=cache, start_pos=start_pos)
        return logits, new_cache

    def make_loss(c: TransformerConfig):
        def loss_fn(params, batch):
            logits, _, aux = forward(params, batch["input_ids"], c,
                                     attention_mask=batch.get("attention_mask"),
                                     pld_theta=batch.get("pld_theta"))
            labels = batch.get("labels")
            if labels is None:
                labels = jnp.concatenate(
                    [batch["input_ids"][:, 1:],
                     jnp.full((batch["input_ids"].shape[0], 1), -100, batch["input_ids"].dtype)],
                    axis=1)
            loss = cross_entropy_loss(logits, labels, batch.get("attention_mask"))
            if c.moe_num_experts > 0:
                loss = loss + c.moe_aux_loss_coef * aux / max(c.num_layers, 1)
            return loss

        return loss_fn

    def init_layer_block(rng, lo, blen):
        return init_layer_params(jax.random.split(rng, 16)[2], cfg, lo, blen)

    def eval_loss_fn(params, batch):
        # derive the eval copy at TRACE time so live-config mutations the
        # engine makes at compression boundaries (act_quant_bits) reach
        # eval on the next retrace — a build-time copy would freeze them
        return make_loss(eval_config(cfg))(params, batch)

    return Model(init=init, apply=apply, loss_fn=make_loss(cfg),
                 eval_loss_fn=eval_loss_fn,
                 init_layer_block=init_layer_block,
                 axes=param_axes(cfg), config=cfg, name=name)
