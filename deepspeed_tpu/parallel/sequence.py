"""Sequence/context parallelism (Ulysses-style) — first-class on TPU.

Absent in reference v0.9.2 (SURVEY §2.4: no deepspeed/sequence/) but mandated
as first-class here. The DeepSpeed-Ulysses scheme: tokens are sharded over the
'seq' axis; around attention, an all-to-all re-shards from token-sharded to
head-sharded (each device gets the FULL sequence for N/sp heads), attention
runs locally, and the inverse all-to-all restores token sharding.

In SPMD-jit we express this purely with sharding constraints — XLA lowers the
reshard to exactly the head-scatter all-to-all Ulysses hand-codes:

  hidden  (B, S, H):    P(data, seq, None)      tokens sharded
  q/k/v   (B, S, N, D): P(data, None, ('seq','model'), None)
                        sequence gathered, heads scattered
  attn out -> back to   P(data, seq, None)

Ring attention (blockwise P2P over 'seq' with ppermute) is the long-term
long-context path; Ulysses covers seq lengths where one device holds S×H/sp.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_SHARD, MODEL_AXIS, SEQ_AXIS, get_mesh


def _active_mesh():
    try:
        from .mesh import ambient_mesh

        mesh = ambient_mesh() or get_mesh()
        if not mesh.shape:
            return None
        return mesh
    except Exception:
        return None


def _in_manual_pipe() -> bool:
    """True when tracing inside the pipeline's manual shard_map — sharding
    constraints over auto axes there trip an XLA SPMD partitioner check
    (spmd_partitioner_util.cc subgroup mismatch), so constraints are skipped
    and layout is left to propagation."""
    from jax import lax

    try:
        # psum of a python int constant-folds to the axis size — unlike
        # axis_index it emits NO op into the traced program (axis_index
        # lowers to partition-id, which the partial-auto partitioner
        # rejects even when the value is unused before DCE)
        lax.psum(1, "pipe")
        return True
    except Exception:
        return False


def constrain(x: jax.Array, spec: P) -> jax.Array:
    """Apply a sharding constraint if a mesh is active; no-op otherwise."""
    mesh = _active_mesh()
    if mesh is None or _in_manual_pipe():
        return x
    try:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    except Exception:
        return x


def hidden_spec() -> P:
    """(B, S, H) activations: batch over data, tokens over seq."""
    return P(DATA_SHARD, SEQ_AXIS, None)


def heads_spec(num_heads: int) -> Optional[P]:
    """(B, S, N, D) around attention: full sequence, heads over seq×model.
    None when the head count doesn't divide the axis product (constraint
    would be invalid) — callers then skip the reshard."""
    mesh = _active_mesh()
    if mesh is None:
        return None
    sp = int(mesh.shape.get(SEQ_AXIS, 1))
    tp = int(mesh.shape.get(MODEL_AXIS, 1))
    if sp == 1 and tp == 1:
        return None
    if num_heads % max(sp * tp, 1) != 0:
        return None
    return P(DATA_SHARD, None, (SEQ_AXIS, MODEL_AXIS), None)


def attn_out_spec(num_heads: int) -> Optional[P]:
    """(B, S, N, D) attention OUTPUT, before the head-merge reshape: tokens
    re-scattered over 'seq' (the Ulysses inverse all-to-all), heads kept on
    'model' for the row-parallel wo contraction. Constraining here — on the
    4D tensor — matters: merging N into H first leaves H sharded over
    ('seq','model'), and the (B,S,N·D) reshape into the P(data,seq,None)
    consumer is a sharding transition XLA can only do by full
    rematerialisation (observed: '[SPMD] Involuntary full rematerialization'
    in the zero3×TP×SP dryrun)."""
    mesh = _active_mesh()
    if mesh is None:
        return None
    sp = int(mesh.shape.get(SEQ_AXIS, 1))
    tp = int(mesh.shape.get(MODEL_AXIS, 1))
    if sp == 1 and tp == 1:
        return None
    if tp > 1 and num_heads % tp != 0:
        return None
    return P(DATA_SHARD, SEQ_AXIS, MODEL_AXIS if tp > 1 else None, None)


def scores_spec(num_heads: int) -> Optional[P]:
    """(B, N, S, T) attention scores/probs inside the Ulysses region: heads
    over ('seq','model'), sequence gathered. None when SP is off or the head
    count doesn't divide the axis product."""
    mesh = _active_mesh()
    if mesh is None:
        return None
    sp = int(mesh.shape.get(SEQ_AXIS, 1))
    tp = int(mesh.shape.get(MODEL_AXIS, 1))
    if sp == 1:
        return None
    if num_heads % max(sp * tp, 1) != 0:
        return None
    return P(DATA_SHARD, (SEQ_AXIS, MODEL_AXIS), None, None)


def sequence_parallel_enabled() -> bool:
    mesh = _active_mesh()
    return mesh is not None and int(mesh.shape.get(SEQ_AXIS, 1)) > 1
