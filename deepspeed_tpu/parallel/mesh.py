"""Device-mesh construction — the TPU analog of ``deepspeed/utils/groups.py``.

The reference creates and caches NCCL process groups per parallel dimension
(``_create_model_parallel`` groups.py:59, expert groups :108-258, accessors
:319-392). On TPU all of that collapses into ONE ``jax.sharding.Mesh`` whose
named axes are the parallel dimensions; collectives are addressed by axis name
and XLA routes them over ICI/DCN. This module owns:

  * axis-name constants (data/fsdp, model, pipe, seq, expert),
  * mesh construction from a ``ParallelConfig`` + device list,
  * the groups-accessor API surface of the reference (sizes/ranks), and
  * a process-global default mesh (mirror of the reference's module globals).

Axis layout convention (outermost → innermost):
("pipe", "expert", "data", "seq", "model"). Innermost axes change fastest
across physically adjacent devices, so "model" (highest-bandwidth collectives:
TP allreduce every layer) rides the shortest ICI hops, matching the
scaling-book recipe.

The total data-parallel degree is expert x data: batch/grads/fsdp shard over
the composite ``DATA_SHARD = ("expert", "data")`` tuple; MoE layers shard the
expert dim over "expert" only, so each expert is replicated across its
``data``-axis ranks — exactly the reference's expert-parallel +
expert-DATA-parallel group structure (groups.py:108/156) with ep <= dp.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config.config import ParallelConfig
from ..utils.logging import logger

# canonical axis names
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"  # expert parallelism (ep <= total dp)
DATA_AXIS = "data"      # dp WITHIN an expert group (total dp = expert x data)
SEQ_AXIS = "seq"        # sequence/context parallelism (Ulysses / ring)
MODEL_AXIS = "model"    # tensor parallelism

MESH_AXES = (PIPE_AXIS, EXPERT_AXIS, DATA_AXIS, SEQ_AXIS, MODEL_AXIS)
# composite spec entry for everything data-parallel (batch, grads, fsdp)
DATA_SHARD = (EXPERT_AXIS, DATA_AXIS)

_GLOBAL_MESH: Optional[Mesh] = None


def build_mesh(parallel: Optional[ParallelConfig] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Create the framework mesh.

    ``data`` size is inferred as world/(pp*sp*tp) when left 0. Device order uses
    ``jax.experimental.mesh_utils`` when available so the innermost axes land on
    physically adjacent chips (ICI-contiguous), falling back to a plain reshape
    for CPU test meshes.
    """
    parallel = parallel or ParallelConfig()
    devices = list(devices if devices is not None else jax.devices())
    world = len(devices)
    pp, tp, sp = (parallel.pipeline_parallel_size, parallel.tensor_parallel_size,
                  parallel.sequence_parallel_size)
    ep = parallel.expert_parallel_size
    denom = pp * tp * sp
    if world % denom != 0:
        raise ValueError(f"world size {world} not divisible by pipe*seq*model = {denom}")
    dp_total = parallel.data_parallel_size or world // denom
    if pp * dp_total * sp * tp != world:
        raise ValueError(
            f"mesh {pp}x{dp_total}x{sp}x{tp} (pipe,data,seq,model) != world size {world}")
    if dp_total % ep != 0:
        raise ValueError(
            f"expert_parallel_size {ep} must divide the data-parallel degree "
            f"{dp_total} (reference: groups.py:108 ep<=dp constraint)")

    shape = (pp, ep, dp_total // ep, sp, tp)
    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        device_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(device_array, MESH_AXES)
    logger.info(f"Built mesh pipe={pp} expert={ep} data={dp_total // ep} "
                f"seq={sp} model={tp} over {world} devices")
    return mesh


def set_mesh(mesh: Mesh) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh() -> Mesh:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = build_mesh()
    return _GLOBAL_MESH


def reset_mesh() -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = None


_TLS = threading.local()


@contextmanager
def ambient(mesh: Mesh):
    """Enter ``mesh`` as the jit mesh context AND register it on a
    framework-owned thread-local stack readable via :func:`ambient_mesh`.

    This replaces reading ``jax.interpreters.pxla.thread_resources`` (a JAX
    internal, deprecated since 0.8.2) as the way trace-time code discovers
    the mesh it is being traced under — e.g. the quantized-GEMM kernel gate
    in ``models/transformer.py`` needs the model-axis world size at trace
    time. Every engine trace site enters the mesh through here."""
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        stack.pop()


def ambient_mesh() -> Optional[Mesh]:
    """The mesh of the innermost active :func:`ambient` context on this
    thread, or None outside any framework mesh context."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


@contextmanager
def mesh_context(mesh: Mesh):
    global _GLOBAL_MESH
    prev = _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    try:
        with ambient(mesh):
            yield mesh
    finally:
        _GLOBAL_MESH = prev


# ---------------------------------------------------------------------------
# groups-style accessors (reference utils/groups.py:319-392 API surface)
# ---------------------------------------------------------------------------

def _axis_size(axis: str, mesh: Optional[Mesh] = None) -> int:
    # ambient first: an engine tracing under its own mesh (inference EP/TP)
    # must see THAT mesh's degrees, not a stale global default — identical
    # in training, where every trace site enters ambient(global mesh)
    mesh = mesh or ambient_mesh() or get_mesh()
    return int(mesh.shape.get(axis, 1))


def get_data_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    """TOTAL data-parallel degree (expert x data axes) — the reference's
    dp_world, of which expert groups are a sub-division."""
    return _axis_size(DATA_AXIS, mesh) * _axis_size(EXPERT_AXIS, mesh)


def get_model_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(MODEL_AXIS, mesh)


def get_pipe_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(PIPE_AXIS, mesh)


def get_sequence_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(SEQ_AXIS, mesh)


def get_expert_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(EXPERT_AXIS, mesh)


def get_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return int(np.prod(list(mesh.shape.values())))


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), P())


def sharding(spec: P, mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), spec)


def batch_spec() -> P:
    """Input-batch sharding: batch dim split over (expert, data); tokens over seq."""
    return P(DATA_SHARD, SEQ_AXIS)


def local_device_count() -> int:
    return jax.local_device_count()


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()
