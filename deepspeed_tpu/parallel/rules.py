"""Logical-axis rule registry — the single source of truth for sharding.

Models declare *logical* axes once (``('embed', 'vocab')``,
``('layers', 'kv_heads', 'head_dim')``, ...) in ``Model.axes``; this module
owns the **rule sets** that map logical axes to mesh axes, bundled into named
:class:`Policy` objects (the t5x/flax ``logical_axis_rules`` pattern, one
registry instead of per-engine dicts):

* ``tp``      — Megatron tensor parallelism only: heads/mlp/vocab over the
  ``model`` axis, everything else replicated. The placement of ZeRO 0-2
  params, ZeRO 0-1 grads and ZeRO-0 optimizer state.
* ``fsdp``    — ``tp`` plus the largest still-unmapped dimension of each
  (large-enough) param sharded over the composite data axes
  (``DATA_SHARD = (expert, data)``). The placement of ZeRO-3 params, ZeRO-2+
  grads and ZeRO-1+ optimizer state.
* ``serving`` — ``tp`` resolved on the serving mesh with MoE expert banks
  over the ``expert`` axis and NO fsdp axis (the reference's inference
  engine shards qkv/mlp across the mp group only). Also the TARGET of the
  RLHF train→serve weight flip, which makes the flip "two policies over one
  rule set": its source is the train policy, its ``out_shardings`` derive
  from this one.

Everything that used to hand-build PartitionSpec trees (``models/core.py``
annotations, ``parallel/zero.py`` spec trees, engine ``out_shardings``, the
RLHF flip's target specs) derives from this registry; ``tools/tpushard``
statically audits every registered program against it, and the tpulint rule
``hardcoded-partition-spec`` flags new hand-built specs outside this module.

Entry points advertise their placement contract to the analyzer via
:func:`shard_tag` stored under ``tags["shard"]`` at tpuaudit registration.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import DATA_SHARD, EXPERT_AXIS, MODEL_AXIS, PIPE_AXIS

# ---------------------------------------------------------------------------
# logical axis vocabulary (models declare these once, in Model.axes)
# ---------------------------------------------------------------------------

BATCH = "batch"
SEQ = "seq"
LAYERS = "layers"    # scanned layer stack dim — never sharded (scan carries it)
VOCAB = "vocab"
EMBED = "embed"
HEADS = "heads"      # attention heads (TP-sharded)
KV_HEADS = "kv_heads"
HEAD_DIM = "head_dim"
MLP = "mlp"          # ffn hidden (TP-sharded)
EXPERT = "expert"    # MoE expert dim
PIPE_STAGE = "pipe_stage"   # pipelined models: stacked per-stage params

AxesTree = Any       # pytree of tuples of logical axis names, or None leaves

# default TP rules (Megatron pattern): column-parallel on heads/mlp/vocab,
# row-parallel contractions produce partial sums that XLA psums over "model".
DEFAULT_TP_RULES: Dict[str, Optional[str]] = {
    VOCAB: MODEL_AXIS,
    HEADS: MODEL_AXIS,
    KV_HEADS: MODEL_AXIS,
    MLP: MODEL_AXIS,
    EXPERT: None,           # expert dim handled by the MoE layer itself
    PIPE_STAGE: PIPE_AXIS,  # pipelined models: stage dim over the pipe axis
}


# ---------------------------------------------------------------------------
# logical-axis → PartitionSpec resolution
# ---------------------------------------------------------------------------

def logical_to_spec(axes: Optional[Tuple[str, ...]],
                    shape: Tuple[int, ...],
                    rules: Dict[str, Optional[str]],
                    fsdp_axis: Optional[str] = None,
                    fsdp_min_size: int = 2 ** 14) -> P:
    """Resolve one param's logical axes to a PartitionSpec.

    1. map each logical axis through ``rules`` (TP placement);
    2. if ``fsdp_axis`` is set (ZeRO-3), additionally shard the largest
       still-unmapped dimension over it — unless the param is tiny
       (< fsdp_min_size elements, the reference's
       stage3_param_persistence_threshold concept: small params stay
       replicated to avoid gather latency for no memory win).
    """
    if axes is None:
        return P()
    mesh_axes: list = [rules.get(a) for a in axes]
    # never shard the scan-carried layer dim
    mesh_axes = [None if a == LAYERS else m for a, m in zip(axes, mesh_axes)]
    if fsdp_axis is not None:
        # a mesh axis may appear once per PartitionSpec: drop components of
        # the (possibly composite) fsdp axis already consumed by TP/EP rules
        used = set()
        for m in mesh_axes:
            if m is None:
                continue
            used.update(m if isinstance(m, tuple) else (m,))
        want = fsdp_axis if isinstance(fsdp_axis, tuple) else (fsdp_axis,)
        free = tuple(a for a in want if a not in used)
        size = 1
        for s in shape:
            size *= s
        if free and size >= fsdp_min_size:
            candidates = [i for i, (a, m) in enumerate(zip(axes, mesh_axes))
                          if m is None and a != LAYERS]
            if candidates:
                best = max(candidates, key=lambda i: shape[i])
                mesh_axes[best] = free if len(free) > 1 else free[0]
    return P(*mesh_axes)


def resolve_param_specs(params: Any, axes: AxesTree,
                        rules: Optional[Dict[str, Optional[str]]] = None,
                        fsdp_axis: Optional[str] = None,
                        fsdp_min_size: int = 2 ** 14) -> Any:
    """Params tree + axes tree → PartitionSpec tree."""
    rules = dict(DEFAULT_TP_RULES if rules is None else rules)

    def one(p, ax):
        return logical_to_spec(ax, jnp.shape(p), rules, fsdp_axis, fsdp_min_size)

    return jax.tree.map(one, params, axes,
                        is_leaf=lambda x: x is None or (isinstance(x, tuple)
                                                        and all(isinstance(e, str) for e in x)))


# ---------------------------------------------------------------------------
# named policies
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Policy:
    """One named rule set: logical-axis → mesh-axis mapping plus the fsdp
    derivation parameters. ``rules`` is stored as a sorted item tuple so the
    policy is hashable/frozen; read it through :meth:`rules_dict`."""

    name: str
    description: str
    rules: Tuple[Tuple[str, Any], ...]
    fsdp_axis: Optional[Any] = None         # mesh axis name or tuple, or None
    fsdp_min_size: int = 2 ** 11

    def rules_dict(self, *, expert_parallel: bool = False,
                   overrides: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Optional[str]]:
        """The mapping this policy applies. ``expert_parallel`` adds the MoE
        expert-bank rule (expert dim over the 'expert' mesh axis — the
        reference's ep<=dp group structure). ``overrides`` is tpushard's
        fault-injection seam: remap axes on the EXPECTATION side only."""
        d = dict(self.rules)
        if expert_parallel:
            d[EXPERT] = EXPERT_AXIS
        if overrides:
            d.update(overrides)
        return d

    def param_specs(self, params_or_shapes: Any, axes: AxesTree, *,
                    expert_parallel: bool = False,
                    fsdp_min_size: Optional[int] = None,
                    rule_overrides: Optional[Dict[str, Any]] = None) -> Any:
        """Registry-derived PartitionSpec tree for a params tree under this
        policy — THE resolution path every engine and the tpushard analyzer
        share."""
        return resolve_param_specs(
            params_or_shapes, axes,
            self.rules_dict(expert_parallel=expert_parallel,
                            overrides=rule_overrides),
            fsdp_axis=self.fsdp_axis,
            fsdp_min_size=(self.fsdp_min_size if fsdp_min_size is None
                           else fsdp_min_size))


_POLICIES: Dict[str, Policy] = {}


def register_policy(policy: Policy) -> Policy:
    _POLICIES[policy.name] = policy
    return policy


def get_policy(name: str) -> Policy:
    try:
        return _POLICIES[name]
    except KeyError:
        raise KeyError(f"unknown sharding policy {name!r} "
                       f"(registered: {sorted(_POLICIES)})") from None


def policy_names() -> Tuple[str, ...]:
    return tuple(sorted(_POLICIES))


_TP_ITEMS = tuple(sorted(DEFAULT_TP_RULES.items(),
                         key=lambda kv: kv[0]))

register_policy(Policy(
    name="tp",
    description="Megatron TP only: heads/mlp/vocab over 'model'; the "
                "placement of ZeRO 0-2 params, 0-1 grads, 0 optimizer state",
    rules=_TP_ITEMS))
register_policy(Policy(
    name="fsdp",
    description="TP + largest free dim of each >=fsdp_min_size param over "
                "(expert, data): ZeRO-3 params, ZeRO-2+ grads, ZeRO-1+ "
                "optimizer state",
    rules=_TP_ITEMS, fsdp_axis=DATA_SHARD))
register_policy(Policy(
    name="serving",
    description="inference/serving placement: TP with MoE expert banks over "
                "'expert', no fsdp — also the RLHF flip's target",
    rules=_TP_ITEMS))


def zero_policy(stage: int, state: str = "params") -> Policy:
    """The placement policy ZeRO assigns one state category at one stage —
    the table from the module docstring of ``parallel/zero.py`` as data."""
    thresholds = {"params": 3, "grads": 2, "masters": 1}
    try:
        need = thresholds[state]
    except KeyError:
        raise ValueError(f"state must be one of {sorted(thresholds)}, "
                         f"got {state!r}") from None
    return get_policy("fsdp" if stage >= need else "tp")


# ---------------------------------------------------------------------------
# the analyzer contract (tools/tpushard)
# ---------------------------------------------------------------------------

def shard_tag(policy: str, *, axes: AxesTree, params_arg: int = 0,
              expert_parallel: bool = False,
              fsdp_min_size: Optional[int] = None,
              group: Optional[str] = None,
              check_output: bool = False,
              source: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The ``tags["shard"]`` payload a tpuaudit registration site attaches so
    ``tools/tpushard`` can recompute the entry's expected layout:

    * ``policy``/``expert_parallel``/``fsdp_min_size`` — how to resolve the
      expected specs for the params tree at ``args[params_arg]``;
    * ``axes`` — the model's logical-axis tree (held by reference);
    * ``group`` — entries that exchange live buffers (train↔eval,
      prefill↔decode↔verify, ...) share a group name; the analyzer
      cross-checks same-labelled params across a group;
    * ``check_output=True`` — audit the program's OUTPUT tree against the
      policy instead of an input (the RLHF flip: its outputs must land on
      the serving placement — the analyzer resolves the target mesh from
      the compiled output shardings themselves, since everything in
      ``ep.tags`` must stay JSON-serializable for the crash-bundle
      fingerprints and the analyzers' ``--format json``);
    * ``source`` — a nested tag for the INPUT side when it follows a
      different policy than the output (the flip's train-side source).
    """
    get_policy(policy)   # fail at registration, not analysis
    tag: Dict[str, Any] = {"policy": policy, "axes": axes,
                           "params_arg": params_arg,
                           "expert_parallel": bool(expert_parallel)}
    if fsdp_min_size is not None:
        tag["fsdp_min_size"] = int(fsdp_min_size)
    if group is not None:
        tag["group"] = group
    if check_output:
        tag["check_output"] = True
    if source is not None:
        tag["source"] = source
    return tag
