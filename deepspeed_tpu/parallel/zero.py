"""ZeRO as sharding policy.

The reference implements ZeRO with ~5k LoC of hook-driven tensor surgery
(``runtime/zero/stage_1_and_2.py``, ``stage3.py``, ``partition_parameters.py``):
flattening, bucketing, per-param backward hooks, trace-based prefetch. On TPU
the *entire mechanism* reduces to WHERE each tensor lives on the mesh — XLA's
SPMD partitioner then emits exactly the collectives the reference hand-codes:

  stage 0: params/grads/opt replicated; grads all-reduced          (DDP)
  stage 1: optimizer state (master + moments) sharded over 'data'  — update
           computed shardwise, updated params all-gathered         (= step_1&2 step())
  stage 2: + gradients sharded over 'data' — XLA lowers the grad
           psum to reduce-scatter feeding the sharded update       (= reduce_ipg_grads)
  stage 3: + parameters sharded over 'data' — XLA inserts per-layer
           all-gather before use and discards after                (= fetch_sub_module)

The prefetch/overlap machinery (ZeRoTraceMode, __prefetch_nvme...) disappears:
XLA's latency-hiding scheduler overlaps the gathers with compute.

This module computes the three PartitionSpec trees (params / grads / optimizer
state) from a model's logical axes + the ZeRO stage + TP rules.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config.config import ZeroConfig
from ..utils.logging import logger
from .rules import get_policy, resolve_param_specs
from .mesh import DATA_SHARD, MODEL_AXIS


class ZeroShardingPlan(NamedTuple):
    param_specs: Any      # pytree of PartitionSpec aligned with params
    grad_specs: Any       # same tree — sharding to constrain grads to
    master_specs: Any     # sharding for fp32 master + optimizer moments
    stage: int


def build_sharding_plan(stage: int, params_or_shapes: Any, axes: Any,
                        tp_rules: Optional[Dict[str, Optional[str]]] = None,
                        fsdp_min_size: int = 2 ** 11,
                        expert_parallel: bool = False) -> ZeroShardingPlan:
    """Compute the ZeRO sharding plan.

    The two placements come from the rule registry (``parallel/rules.py``):
    ``tp`` and ``fsdp`` — which state category gets which is the only thing
    the stage decides. ``expert_parallel`` adds the MoE expert-bank rule;
    ``tp_rules`` remains as an explicit-override escape hatch (tests,
    experiments) and bypasses the registry when given.

    ``fsdp_min_size`` mirrors the reference's stage3_param_persistence_threshold
    (partition_parameters.py: small params stay dense); tiny tensors are
    replicated at every stage.
    """
    if not 0 <= stage <= 3:
        raise ValueError(f"ZeRO stage must be 0..3, got {stage}")
    if tp_rules is not None:
        rules = dict(tp_rules)
        tp_only = resolve_param_specs(params_or_shapes, axes, rules,
                                      fsdp_axis=None)
        fsdp = resolve_param_specs(params_or_shapes, axes, rules,
                                   fsdp_axis=DATA_SHARD,
                                   fsdp_min_size=fsdp_min_size)
    else:
        tp_only = get_policy("tp").param_specs(
            params_or_shapes, axes, expert_parallel=expert_parallel)
        fsdp = get_policy("fsdp").param_specs(
            params_or_shapes, axes, expert_parallel=expert_parallel,
            fsdp_min_size=fsdp_min_size)

    param_specs = fsdp if stage >= 3 else tp_only
    grad_specs = fsdp if stage >= 2 else tp_only
    master_specs = fsdp if stage >= 1 else tp_only
    return ZeroShardingPlan(param_specs=param_specs, grad_specs=grad_specs,
                            master_specs=master_specs, stage=stage)


def optimizer_state_specs(state_shapes: Any, params: Any, param_like_specs: Any) -> Any:
    """Map a sharding-spec tree onto an optimizer state whose inner nodes
    contain params-structured subtrees (optax moments, our fp32 master).
    Scalars and anything not params-shaped stay replicated.

    This is the TPU analog of the reference's ZeRO rule "optimizer state is
    partitioned exactly like its param" (stage_1_and_2.py
    get_data_parallel_partitions / stage3 sub-groups).
    """
    params_treedef = jax.tree.structure(params)

    def is_node_leaf(n):
        return hasattr(n, "shape") or n is None

    def rec(node):
        if node is None:
            return None
        if not is_node_leaf(node):
            try:
                if jax.tree.structure(node) == params_treedef:
                    return param_like_specs
            except Exception:
                pass
        if is_node_leaf(node):
            return P()
        # descend one pytree level
        children, treedef = jax.tree_util.tree_flatten(
            node, is_leaf=lambda x: x is not node)
        return jax.tree_util.tree_unflatten(treedef, [rec(c) for c in children])

    return rec(state_shapes)


def as_named(specs: Any, mesh: Mesh) -> Any:
    """PartitionSpec tree → NamedSharding tree (jit in_shardings form)."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        specs, is_leaf=lambda x: isinstance(x, P))


def describe_plan(plan: ZeroShardingPlan, params: Any) -> str:
    total = sum(int(p.size) for p in jax.tree.leaves(params))
    sharded = sum(int(p.size) for p, s in zip(jax.tree.leaves(params),
                                              jax.tree.leaves(plan.param_specs))
                  if any(a is not None for a in (s or ())))
    return (f"ZeRO stage {plan.stage}: {total / 1e6:.1f}M params, "
            f"{sharded / max(total, 1) * 100:.0f}% sharded")
