"""Ring attention — sequence parallelism by rotating KV chunks over ICI.

The second long-context scheme next to Ulysses (parallel/sequence.py).
Reference lineage: v0.9.2 has neither (SURVEY §5 — its long-context story is
block-sparse attention); later DeepSpeed added Ulysses, and ring attention
(Liu et al.) is the standard TPU-native alternative the task brief calls
first-class. Design:

  * tokens stay sharded over the 'seq' axis end-to-end (activations,
    q/k/v) — nothing ever materialises the full sequence;
  * each of the sp steps computes blockwise attention of the LOCAL queries
    against the currently-held KV chunk, merged with an online-softmax
    running (max, denom, acc) state — flash attention's math at chunk
    granularity;
  * the KV pair then rotates one hop around the ring (`ppermute` on ICI),
    overlapping the next chunk's transfer with compute;
  * causality is decided per (query-chunk, key-chunk) pair from absolute
    chunk ids: later chunks are masked entirely, the diagonal chunk gets the
    triangular mask, earlier chunks are dense;
  * backward = jax.grad through the unrolled loop — XLA reverses each
    ppermute, which is exactly the reverse KV rotation of the published
    ring-attention backward.

Runs inside a partial-manual ``shard_map`` over the 'seq' axis (data/model
stay automatic, so ZeRO/TP compose).
"""

from __future__ import annotations

from typing import Optional

import jax
from ..utils.compat import shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import SEQ_AXIS, get_mesh

_RING_ENABLED = False


def set_ring_attention(enabled: bool) -> None:
    """Engine hook: ParallelConfig.sequence_parallel_impl == 'ring'."""
    global _RING_ENABLED
    _RING_ENABLED = enabled


def ring_attention_enabled() -> bool:
    if not _RING_ENABLED:
        return False
    from .sequence import _in_manual_pipe

    if _in_manual_pipe():
        # a nested explicit-mesh shard_map under the pipeline's manual trace
        # is rejected by JAX; the engine refuses ring+PP up front, this
        # guard covers direct forward() calls
        return False
    try:
        return int(get_mesh().shape.get(SEQ_AXIS, 1)) > 1
    except Exception:
        return False


def _ring_body(q, k, v, *, sp: int, scale: float, causal: bool):
    """Per-shard body (manual over 'seq'). q/k/v (B, S_loc, N, D) local
    chunks; returns (B, S_loc, N, D)."""
    my = lax.axis_index(SEQ_AXIS)
    B, S, N, D = q.shape
    q32 = q.astype(jnp.float32) * scale

    m = jnp.full((B, N, S, 1), -1e30, jnp.float32)
    l = jnp.zeros((B, N, S, 1), jnp.float32)
    acc = jnp.zeros((B, N, S, D), jnp.float32)
    k_c, v_c = k, v
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    local = jnp.arange(S)
    for step in range(sp):
        # double-buffer: issue the NEXT chunk's rotation before this step's
        # compute so XLA overlaps the ICI transfer with the einsums
        if step + 1 < sp:
            k_next = lax.ppermute(k_c, SEQ_AXIS, perm)
            v_next = lax.ppermute(v_c, SEQ_AXIS, perm)
        # after `step` rotations this shard holds chunk (my - step) mod sp
        src = (my - step) % sp
        s_ij = jnp.einsum("bsnd,btnd->bnst", q32,
                          k_c.astype(jnp.float32))         # (B,N,S,S)
        if causal:
            q_pos = my * S + local                          # (S,)
            k_pos = src * S + local
            keep = k_pos[None, :] <= q_pos[:, None]         # (S,S)
            s_ij = jnp.where(keep[None, None], s_ij, -1e30)
        m_new = jnp.maximum(m, jnp.max(s_ij, axis=-1, keepdims=True))
        p = jnp.exp(s_ij - m_new)
        corr = jnp.exp(m - m_new)
        l = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bnst,btnd->bnsd", p,
                                      v_c.astype(jnp.float32))
        m = m_new
        if step + 1 < sp:
            k_c, v_c = k_next, v_next

    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / safe_l).swapaxes(1, 2)                     # (B,S,N,D)
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mask=None, causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Sequence-parallel attention over the 'seq' mesh axis. q (B,S,N,D) with
    the token dim seq-sharded (global view — this function wraps the
    shard_map). GQA KV heads are expanded by the caller side (same contract
    as flash_attention). Padding masks are not supported in ring mode (long-
    context pretraining packs sequences instead)."""
    if mask is not None:
        raise NotImplementedError(
            "ring attention does not take padding masks — pack sequences "
            "(the standard long-context pretraining setup) or use Ulysses "
            "(sequence_parallel_impl='ulysses')")
    mesh = get_mesh()
    sp = int(mesh.shape[SEQ_AXIS])
    B, S, N, D = q.shape
    K = k.shape[2]
    if K != N:
        k = jnp.repeat(k, N // K, axis=2)
        v = jnp.repeat(v, N // K, axis=2)
    if S % sp != 0:
        raise ValueError(f"sequence {S} not divisible by seq axis {sp}")
    scale = scale if scale is not None else D ** -0.5

    import functools

    body = functools.partial(_ring_body, sp=sp, scale=scale, causal=causal)
    # partial-manual: only the 'seq' axis is named; batch keeps whatever
    # (expert, data) sharding the surrounding jit gives it automatically
    spec = P(None, SEQ_AXIS, None, None)
    fn = shard_map(body, mesh=mesh,
                       in_specs=(spec, spec, spec),
                       out_specs=spec,
                       check_vma=False,
                       axis_names={SEQ_AXIS})
    return fn(q, k, v)
