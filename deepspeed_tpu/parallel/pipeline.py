"""Pipeline parallelism — TPU-native SPMD execution.

Analog of ``deepspeed/runtime/pipe/`` (``PipelineModule`` module.py:85,
``PipelineEngine`` engine.py:40, ``p2p.py``). The reference runs an
instruction interpreter per rank with pickled-meta p2p sends; on TPU the whole
pipeline is ONE jitted SPMD program:

  * layer params are stacked and the leading stage dim is sharded over the
    'pipe' mesh axis (each device group holds its stage's layers);
  * the microbatch loop is a ``lax.scan`` over M + P - 1 ticks inside a
    partial-manual ``shard_map`` over 'pipe' (other axes stay automatic so
    TP/DP/ZeRO sharding composes);
  * stage-to-stage transfer is a ``ppermute`` ring shift — and jax.grad
    through the loop reverses the ppermutes, deriving the backward pipeline
    schedule automatically (what the reference hand-codes as SendGrad/
    RecvGrad instructions);
  * embeddings/head are replicated over 'pipe'; only stage 0 embeds and only
    the last stage computes logits+loss (runtime-branched, so no wasted
    FLOPs — the reference's tied-embedding layout maps to this too).

Layer partitioning policies (uniform / parameters / type:regex) are kept for
API parity with ``PipelineModule._partition_layers`` (module.py:353).
"""

from __future__ import annotations

import re
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models.core import LAYERS, Model
from ..utils.logging import logger
from .mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS, get_mesh

PIPE_STAGE = "pipe_stage"   # logical axis for the stacked stage dim


# ---------------------------------------------------------------------------
# layer partitioning (reference module.py:353 _partition_layers)
# ---------------------------------------------------------------------------


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries of a uniform split (reference runtime/utils.py:541); the
    remainder is distributed one-per-stage from the front."""
    chunk, residual = divmod(num_items, num_parts)
    return [min(p * chunk + min(p, residual), num_items)
            for p in range(num_parts + 1)]


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Boundaries minimizing the max part weight (reference
    runtime/utils.py:603 partition_balanced, prefix-sum + binary search)."""
    weights = list(weights)
    n = len(weights)
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def parts_for(limit: float) -> Optional[List[int]]:
        bounds = [0]
        for _ in range(num_parts):
            start = bounds[-1]
            # furthest end with weight(start, end) <= limit
            end = int(np.searchsorted(prefix, prefix[start] + limit, side="right") - 1)
            end = max(end, start + 1)  # at least one item per part
            end = min(end, n)
            bounds.append(end)
        return bounds if bounds[-1] >= n else None

    lo = max(weights) if weights else 0.0
    hi = float(prefix[-1])
    for _ in range(40):
        mid = (lo + hi) / 2
        if parts_for(mid) is not None:
            hi = mid
        else:
            lo = mid
    result = parts_for(hi)
    result[-1] = n
    return result


def partition_layers(layers: Sequence[Any], num_stages: int,
                     method: str = "uniform") -> List[int]:
    """Stage boundaries for a layer list. Methods mirror the reference:
    'uniform' | 'parameters' (balance by param count) | 'type:regex'
    (balance count of layers whose class name matches)."""
    method = method.lower()
    if method == "uniform":
        return partition_uniform(len(layers), num_stages)
    if method == "parameters":
        weights = [float(getattr(l, "num_params", 1) or 1) for l in layers]
        return partition_balanced(weights, num_stages)
    if method.startswith("type:"):
        pattern = method.split(":", 1)[1]
        weights = [1.0 if re.search(pattern, type(l).__name__, re.IGNORECASE) else 0.0
                   for l in layers]
        if sum(weights) == 0:
            raise ValueError(f"no layer matches type regex '{pattern}'")
        return partition_balanced(weights, num_stages)
    raise ValueError(f"unknown partition method '{method}'")


class LayerSpec:
    """Deferred layer construction (reference pipe/module.py:29) — records a
    builder + args; ``build()`` instantiates. num_params estimated lazily for
    'parameters' partitioning."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


# ---------------------------------------------------------------------------
# SPMD pipelined transformer loss
# ---------------------------------------------------------------------------


def _split_stages(layer_tree: Any, num_stages: int) -> Any:
    """(L, ...) stacked layer params → (P, L/P, ...)."""

    def reshape(x):
        L = x.shape[0]
        assert L % num_stages == 0, (
            f"num_layers {L} not divisible by pipeline stages {num_stages}")
        return x.reshape(num_stages, L // num_stages, *x.shape[1:])

    return jax.tree.map(reshape, layer_tree)


def _merge_stages(layer_tree: Any) -> Any:
    return jax.tree.map(lambda x: x.reshape(-1, *x.shape[2:]), layer_tree)


def _needs_fp32_body() -> bool:
    try:
        mesh = get_mesh()
        return (int(mesh.shape.get(MODEL_AXIS, 1)) > 1
                or int(mesh.shape.get(SEQ_AXIS, 1)) > 1)
    except Exception:
        return False


def pipelined_loss_fn(cfg, num_stages: int):
    """Build loss_fn(params, batch) where batch leaves have a leading
    microbatch dim M and params['layers'] leaves have leading stage dim P.

    The returned function must run under jit with the global mesh active.
    """
    from ..models.transformer import _layer_forward, _norm, cross_entropy_loss

    def stage_apply(stage_layers, x, mask, positions):
        def block(h, layer):
            h, _, _aux = _layer_forward(cfg, h, layer, mask, positions, None)
            return h, None

        block_fn = jax.checkpoint(block, prevent_cse=False) if cfg.remat else block
        x, _ = lax.scan(block_fn, x, stage_layers)
        return x

    def body(layers_stacked, embed_tree, batch):
        """Runs per-pipe-group (manual over 'pipe'; data/seq/model auto).
        layers_stacked leaves: (1, Lp, ...) — this stage's layers.
        embed_tree: full non-layer params (replicated over pipe).
        batch leaves: (M, mb, S)."""
        stage_id = lax.axis_index(PIPE_AXIS)
        P_ = lax.psum(1, PIPE_AXIS)
        stage_layers = jax.tree.map(lambda x: x[0], layers_stacked)
        body_dtype = jnp.float32 if _needs_fp32_body() else cfg.dtype
        ids = batch["input_ids"]
        attn_mask = batch.get("attention_mask")          # (M, mb, S) or None
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, :, 1:], jnp.full((*ids.shape[:2], 1), -100, ids.dtype)],
                axis=2)
        M, mb, S = ids.shape
        positions = jnp.arange(S)
        H = cfg.hidden_size

        def embed(token_ids):
            x = embed_tree["embed"]["tokens"][token_ids].astype(body_dtype)
            if cfg.position == "learned":
                x = x + embed_tree["pos"][positions].astype(body_dtype)
            return x

        n_ticks = M + P_ - 1

        def tick(carry, t):
            recv = carry
            mb_idx = t - stage_id                       # microbatch this stage works on
            src_idx = jnp.clip(mb_idx, 0, M - 1)
            my_ids = lax.dynamic_index_in_dim(ids, src_idx, axis=0, keepdims=False)
            my_mask = (lax.dynamic_index_in_dim(attn_mask, src_idx, 0, keepdims=False)
                       if attn_mask is not None else None)
            # stage 0 embeds fresh microbatches; others consume the ring buffer
            x = jnp.where(stage_id == 0, embed(my_ids), recv)
            x = stage_apply(stage_layers, x, my_mask, positions)
            # keep the permuted activation replicated over model/seq — a
            # model-sharded carry through collective-permute crashes the XLA
            # CPU partitioner and adds no value (H dim is replicated anyway)
            from .sequence import constrain as _constrain

            x = _constrain(x, P(DATA_AXIS, None, None))
            recv_next = lax.ppermute(x, PIPE_AXIS,
                                     [(i, (i + 1) % P_) for i in range(P_)])
            return recv_next, x

        init = jnp.zeros((mb, S, H), body_dtype)
        _, xs = lax.scan(tick, init, jnp.arange(n_ticks))   # (ticks, mb, S, H)

        # microbatch m finishes on the last stage at tick m + P - 1: its output
        # block is xs[P-1 : P-1+M]. Head+loss run ONCE, on the last stage only
        # (lax.cond branches at runtime — other stages skip the vocab matmul).
        outs = lax.dynamic_slice_in_dim(xs, P_ - 1, M, axis=0)  # (M, mb, S, H)

        def last_stage_loss():
            def one(h, lbl, msk):
                h = _norm(h, embed_tree["final_norm"]["scale"],
                          embed_tree["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
                if cfg.tie_embeddings:
                    logits = jnp.einsum("bsh,vh->bsv", h, embed_tree["embed"]["tokens"])
                else:
                    logits = jnp.einsum("bsh,hv->bsv", h, embed_tree["lm_head"])
                return cross_entropy_loss(logits, lbl, msk)

            if attn_mask is not None:
                losses = jax.vmap(one)(outs, labels, attn_mask)
            else:
                losses = jax.vmap(lambda h, l: one(h, l, None))(outs, labels)
            return losses.mean()

        mb_loss = lax.cond(stage_id == P_ - 1, last_stage_loss,
                           lambda: jnp.float32(0.0))
        return lax.psum(mb_loss, PIPE_AXIS)

    def loss_fn(params, batch):
        mesh = get_mesh()
        layers_in = params["layers"]
        embed_tree = {k: v for k, v in params.items() if k != "layers"}
        if _needs_fp32_body():
            # bf16 operands + model-axis sharding under the manual-'pipe'
            # shard_map trip an XLA SPMD partitioner check
            # (spmd_partitioner_util.cc subgroup mismatch); upcast at the
            # shard_map boundary so sharded collectives move fp32. Params
            # stay bf16 at rest; grads flow back through the cast.
            cast32 = lambda x: (x.astype(jnp.float32)
                                if jnp.issubdtype(x.dtype, jnp.floating) else x)
            layers_in = jax.tree.map(cast32, layers_in)
            embed_tree = jax.tree.map(cast32, embed_tree)
        layer_specs = jax.tree.map(lambda _: P(PIPE_AXIS), layers_in)
        embed_specs = jax.tree.map(lambda _: P(), embed_tree)
        batch_specs = jax.tree.map(lambda _: P(), batch)
        fn = jax.shard_map(
            body, mesh=mesh,
            in_specs=(layer_specs, embed_specs, batch_specs),
            out_specs=P(),
            check_vma=False,
            axis_names={PIPE_AXIS})
        return fn(layers_in, embed_tree, batch)

    return loss_fn


def pipelinize_model(model: Model, num_stages: int) -> Model:
    """Transform a (transformer) Model into its pipelined variant:
    layers reshaped (L, ...) → (P, Lp, ...) with the stage dim sharded over
    'pipe'; loss_fn consumes a whole microbatch stack (M, mb, S) per call.
    The reference equivalent is wrapping layers in PipelineModule."""
    cfg = model.config
    if cfg is None:
        raise ValueError("pipelinize_model requires a transformer Model (with config)")
    if num_stages <= 1:
        return model

    base_init = model.init

    def init(rng):
        params = base_init(rng)
        params["layers"] = _split_stages(params["layers"], num_stages)
        return params

    axes = dict(model.axes)
    axes["layers"] = jax.tree.map(
        lambda ax: (PIPE_STAGE,) + tuple(ax),
        model.axes["layers"],
        is_leaf=lambda x: isinstance(x, tuple) and all(
            e is None or isinstance(e, str) for e in x))
    # Under PP, embedding/head stay vocab-replicated: a model-sharded vocab dim
    # consumed inside the manual-pipe shard_map (CE's take_along_axis gather)
    # trips an XLA SPMD partitioner check (spmd_partitioner_util.cc). The
    # vocab matmul still TP-shards on its contraction side; only the table
    # layout is denser. Revisit when the partitioner handles it.
    axes["embed"] = {"tokens": (None, "embed")}
    if "lm_head" in axes:
        axes["lm_head"] = ("embed", None)

    loss_fn = pipelined_loss_fn(cfg, num_stages)

    def apply(params, batch, **kw):
        # unpipelined eval path: merge stages back and run the plain forward
        from ..models.transformer import forward

        merged = dict(params)
        merged["layers"] = _merge_stages(params["layers"])
        logits, new_cache, _ = forward(merged, batch["input_ids"], cfg,
                                       attention_mask=batch.get("attention_mask"), **kw)
        return logits, new_cache

    return Model(init=init, apply=apply, loss_fn=loss_fn, axes=axes,
                 config=cfg, name=f"{model.name}-pp{num_stages}",
                 pipelined=True, num_stages=num_stages)
